#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line for the harness, ALWAYS.

Headline metric (BASELINE.md): LeNet-5 (the "MNIST CNN") steps/sec/chip at
the reference's original dist-config geometry (global batch 200 = 2 workers
x 100 — SURVEY.md §0.1), plus MFU (ANALYTIC model FLOPs ÷ step time ÷ chip
bf16 peak, utils/flops.py; the XLA-counted figure rides along as a
cross-check — it understates scan-over-layers models by ~depth x) — the
honest cross-dataset utilization number. The run uses the scanned fused-input step: dataset resident in HBM,
batch sampling compiled into the step, zero host work per step — the polar
opposite of the reference's per-step feed_dict -> gRPC -> PS round-trip
(§3.3).

Provenance: this box has no egress, so when real MNIST IDX files are absent
the data is the procedural synthetic twin (data/synthetic.py) — EASIER than
real MNIST. `synthetic_data` is reported at TOP level, and the ≥99%-in-<60s
north-star race (`vs_baseline` = 60s / wall_to_99) is only scored when the
data is real; on synthetic data the race result is still measured but
reported under `extra.accuracy_race` with vs_baseline pinned to 0.0
(= "no valid baseline comparison").

Robustness: the TPU tunnel in this environment can be down. Backend init is
probed in a BOUNDED subprocess with retries, the whole run sits under a
SIGALRM deadline, and every failure path still prints a structured JSON
line — `BENCH_r*.json.parsed` can never be null again (VERDICT r2 item 1).

Ladder mode (`python bench.py --config resnet20_cifar [--steps N]`) times
any BASELINE.md config's steady-state steps/sec/chip + MFU on the config's
own mesh when this box has enough chips (single-chip fallback is labeled).

CPU smoke mode: an explicit `JAX_PLATFORMS=cpu` (+
`XLA_FLAGS=--xla_force_host_platform_device_count=N`) is honored in both
the probe and the run — a no-TPU CI lane for the bench plumbing itself.
MFU/anchors are correctly absent (unknown CPU peak, device_kind mismatch).
Use LIGHT configs only (mlp_mnist): XLA-CPU compiles of the conv configs'
scanned chunks exceed any reasonable deadline, and the SIGALRM watchdog
will (by design) convert that into a structured error line.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import signal
import subprocess
import sys
import time

#: process-start anchor for the --coldstart-child startup attribution
#: (compilecache/StartupClock): bench's own import is stdlib-cheap, so the
#: child's jax import lands in the ``init`` bucket where it belongs
_T0 = time.monotonic()

HEADLINE_METRIC = "lenet5_mnist_steps_per_sec_per_chip"

#: merged into every emitted record by `emit` — the CPU-fallback probe
#: (probe_backend_with_fallback) sets {"backend": "cpu-fallback"} here so
#: a measurement taken on the fallback backend can never be mistaken for
#: an on-chip number.
_RECORD_TAGS: dict = {}


def emit(obj) -> None:
    print(json.dumps({**obj, **_RECORD_TAGS}), flush=True)


def emit_error(metric: str, message: str, **extra) -> None:
    """Structured failure line: parseable, value 0, error field populated."""
    emit({
        "metric": metric,
        "value": 0.0,
        "unit": "steps/sec/chip",
        "vs_baseline": 0.0,
        "error": message,
        "extra": extra,
    })


# The axon sitecustomize in this image force-selects the TPU platform; an
# explicit JAX_PLATFORMS=cpu must be re-applied in-process to take effect
# (cluster.coordination.force_platform — the same mechanism behind
# cli/train's --platform). Lets bench's ladder paths run on a CPU mesh
# (CI smoke) and keeps the probe honest about WHICH backend the run uses.
# The subprocess string is the probe-side half of the same logic.
_PLATFORM_OVERRIDE = (
    "import os, sys\n"
    f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
    "if os.environ.get('JAX_PLATFORMS', '').strip() == 'cpu':\n"
    "    from dist_mnist_tpu.cluster.coordination import force_platform\n"
    "    force_platform('cpu')\n"
    "import jax\n"
)


def apply_platform_override() -> None:
    """In-process half of the override above. Call AFTER probe_backend():
    it imports jax, and an import failure here would crash without the
    structured JSON line the probe guarantees."""
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        from dist_mnist_tpu.cluster.coordination import force_platform

        force_platform("cpu")


#: error substrings that mean the relay/tunnel is DOWN, not flaky — further
#: probe attempts (3 x 150 s in round 5's outage, BENCH_r05.json) cannot
#: succeed, so they are skipped and the CPU-smoke/error line lands fast
_PROBE_FATAL_MARKERS = (
    "connection refused",
    "econnrefused",
    "failed to connect",
    "connect failed",
    "could not connect",
    "no route to host",
)


def _probe_fatal(err: str) -> bool:
    low = err.lower()
    return any(m in low for m in _PROBE_FATAL_MARKERS)


def _probe_timeout_s(default_s: int) -> int:
    """`BENCH_PROBE_TIMEOUT_S` overrides the per-attempt probe deadline
    (CI smoke lanes set it low so a down relay costs seconds, not 450 s)."""
    raw = os.environ.get("BENCH_PROBE_TIMEOUT_S", "").strip()
    try:
        return int(raw) if raw else default_s
    except ValueError:
        return default_s


def _probe_cache_key() -> str:
    """Verdicts are per requested platform: the TPU-then-cpu-fallback
    sequence (probe_backend_with_fallback) caches BOTH outcomes under
    distinct keys, so later stages replay the same two-phase decision."""
    return os.environ.get("JAX_PLATFORMS", "").strip() or "default"


def _probe_cache_read() -> list[str] | None:
    """Cached probe verdict for the current platform key from the file
    named by `BENCH_PROBE_CACHE`, or None when uncached/unset/unreadable.
    A verdict is [] (backend up) or the error list the probing stage saw.
    measure_all.sh points every stage of one run at the same file, so the
    ~N x (probe subprocess or, on a down relay, N x BENCH_PROBE_TIMEOUT_S)
    cost is paid once per run instead of once per stage."""
    path = os.environ.get("BENCH_PROBE_CACHE", "").strip()
    if not path:
        return None
    try:
        with open(path) as fh:
            verdicts = json.load(fh)
        v = verdicts.get(_probe_cache_key())
        return [str(e) for e in v] if isinstance(v, list) else None
    except (OSError, ValueError):
        return None


def _probe_cache_write(errs: list[str]) -> None:
    path = os.environ.get("BENCH_PROBE_CACHE", "").strip()
    if not path:
        return
    try:
        try:
            with open(path) as fh:
                verdicts = json.load(fh)
            if not isinstance(verdicts, dict):
                verdicts = {}
        except (OSError, ValueError):
            verdicts = {}
        verdicts[_probe_cache_key()] = errs
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(verdicts, fh)
        os.replace(tmp, path)
    except OSError:
        pass  # verdict cache is an optimization, never a failure source


def _probe(retries: int, timeout_s: int) -> list[str]:
    """Bounded out-of-process backend probe; [] on success, else the error
    per attempt. A hung/down TPU tunnel makes `import jax; jax.devices()`
    block or die IN-PROCESS — exactly what produced round 1's unparseable
    bench. Probing in a subprocess bounds the blast radius; retries cover
    transient tunnel restarts. Connection-refused-class failures short-
    circuit the remaining attempts (nothing transient about a dead relay).

    With `BENCH_PROBE_CACHE` set, a verdict already recorded for this
    platform key is returned without probing at all."""
    cached = _probe_cache_read()
    if cached is not None:
        if not cached:
            return []
        return [*cached[:-1],
                cached[-1] + " [cached verdict: BENCH_PROBE_CACHE]"]
    timeout_s = _probe_timeout_s(timeout_s)
    errs = []
    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c",
                 _PLATFORM_OVERRIDE
                 + "print('DEVCOUNT', jax.device_count())"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if out.returncode == 0 and "DEVCOUNT" in out.stdout:
                _probe_cache_write([])
                return []
            errs.append(f"rc={out.returncode}: {out.stderr.strip()[-300:]}")
        except subprocess.TimeoutExpired:
            errs.append(f"probe timed out after {timeout_s}s")
        if _probe_fatal(errs[-1]):
            errs[-1] += " [connection-refused class: retries short-circuited]"
            break
        if attempt < retries - 1:
            time.sleep(min(30, 5 * 2 ** attempt))
    _probe_cache_write(errs)
    return errs


def probe_backend(metric: str, retries: int = 3, timeout_s: int = 150) -> bool:
    """Bench-mode probe: emits the bench-schema error line on failure.

    The failure line carries the last COMMITTED on-chip number for this
    metric (docs/PERF_ANCHOR.json) as context — labeled as such, value
    stays 0.0: an outage must not masquerade as a measurement, but the
    reader should know where the maintained number lives."""
    errs = _probe(retries, timeout_s)
    if not errs:
        return True
    extra = {"probe_errors": errs}
    anchor = _load_anchor(metric)
    if anchor:
        extra["last_committed_anchor"] = {
            **anchor,
            "note": "last committed on-chip measurement (docs/PERF.md) "
                    "— NOT produced by this run; backend was down",
        }
    emit_error(metric, "backend probe failed after "
               f"{retries} attempts: {errs[-1]}", **extra)
    return False


def probe_backend_with_fallback(metric: str, retries: int = 3,
                                timeout_s: int = 150) -> bool:
    """Bench-mode probe with a CPU fallback (BENCH_r01: a down axon relay
    used to end the run with rc=1/no measurement). When the TPU probe
    fails, the process re-probes under `JAX_PLATFORMS=cpu`; on success
    every record it emits is tagged `backend: cpu-fallback` — a labeled
    CPU number instead of no number. Only when the CPU probe ALSO fails
    does the structured error line (with both probes' errors) land."""
    errs = _probe(retries, timeout_s)
    if not errs:
        return True
    cpu_errs = []
    if os.environ.get("JAX_PLATFORMS", "").strip() != "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"  # honored by _PLATFORM_OVERRIDE
        cpu_errs = _probe(1, timeout_s)
        if not cpu_errs:
            _RECORD_TAGS["backend"] = "cpu-fallback"
            return True
    extra = {"probe_errors": errs + cpu_errs}
    anchor = _load_anchor(metric)
    if anchor:
        extra["last_committed_anchor"] = {
            **anchor,
            "note": "last committed on-chip measurement (docs/PERF.md) "
                    "— NOT produced by this run; backend was down",
        }
    emit_error(metric, f"backend probe failed after {retries} attempts "
               f"(and the cpu fallback failed too): {errs[-1]}", **extra)
    return False


def probe_or_exit(script: str, retries: int = 2, timeout_s: int = 150) -> None:
    """Shared preamble for the perf scripts (perf_sweep / step_ablation /
    vit_probe): probe the backend boundedly (a down TPU tunnel otherwise
    hangs them forever at first device use), exit(1) with a script-schema
    JSON line on failure — NOT bench's steps/sec-shaped error line — and
    apply the in-process platform override on success so the backend the
    probe validated is the one the run uses."""
    errs = _probe(retries, timeout_s)
    if errs:
        emit({"script": script, "error": "backend probe failed after "
              f"{retries} attempts: {errs[-1]}", "probe_errors": errs})
        sys.exit(1)
    apply_platform_override()


def install_deadline(metric: str, seconds: int) -> None:
    """SIGALRM watchdog: if the run wedges (backend hang mid-run), print a
    structured line and exit 0 before the driver's own timeout hits."""

    def on_alarm(signum, frame):
        emit_error(metric, f"bench deadline ({seconds}s) exceeded — "
                   "backend hang or pathological compile")
        os._exit(0)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)


def _load_anchor(metric: str) -> dict | None:
    """The last committed on-chip number for `metric`
    (docs/PERF_ANCHOR.json, updated only together with docs/PERF.md);
    None when absent/unreadable/schema-invalid."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "docs", "PERF_ANCHOR.json")) as fh:
            anchor = json.load(fh).get(metric)
    except (OSError, ValueError):
        return None
    return anchor if isinstance(anchor, dict) and anchor.get("value") else None


def _anchor_fields(metric: str, value: float) -> dict:
    """Regression guard: compare against the last committed on-chip number.
    Only emitted when the running chip's device_kind matches the anchor's —
    a cross-hardware ratio would read as a fake regression.

    `vs_anchor` is ALWAYS oriented so >1.0 means improvement: for metrics
    whose anchor declares ``"direction": "lower_is_better"`` (latencies,
    stalls) the ratio is anchor/value, otherwise value/anchor. That keeps
    scripts/check_bench_regression.py's single `vs_anchor < 1 - tol` gate
    correct for both kinds."""
    import jax

    anchor = _load_anchor(metric)
    if anchor and anchor.get("device_kind") == jax.devices()[0].device_kind:
        if anchor.get("direction") == "lower_is_better":
            ratio = anchor["value"] / value if value else float("inf")
        else:
            ratio = value / anchor["value"]
        return {"anchor": anchor["value"], "vs_anchor": round(ratio, 3)}
    return {}


def _mfu_fields(run, state, dt_per_step: float, *, model=None,
                sample_shape=None, batch=None):
    """MFU block, PER-CHIP basis: pass `batch` = batch per chip, and the
    ratio is against ONE chip's peak (XLA's cost analysis is likewise
    per-shard on a partitioned program — verified: the 8-way CPU mesh
    reports 1/8 of the global count). Numerator of record = ANALYTIC model
    FLOPs (fwd published per model, bwd = 2x fwd) — XLA's compiled count
    understates scan-over-layers models by ~depth x (it counts a scan body
    once, utils/flops.py) so it is kept only as the `flops_per_step_xla`
    cross-check. Falls back to the XLA count when the model doesn't
    publish an analytic figure."""
    import jax

    from dist_mnist_tpu.utils.flops import (
        analytic_step_flops,
        device_peak_flops,
        mfu,
        step_flops,
    )

    flops_xla = step_flops(run, state)
    flops_analytic = (
        analytic_step_flops(model, sample_shape, batch)
        if model is not None and sample_shape is not None and batch
        else None
    )
    flops_step = flops_analytic or flops_xla
    util = mfu(flops_step, dt_per_step)
    return {
        "mfu": round(util, 4) if util is not None else None,
        "flops_per_step": round(flops_step) if flops_step else None,
        "flops_basis": "analytic" if flops_analytic else "xla",
        "flops_per_step_xla": round(flops_xla) if flops_xla else None,
        "model_tflops_per_sec": (
            round(flops_step / dt_per_step / 1e12, 2) if flops_step else None
        ),
        "device_kind": jax.devices()[0].device_kind,
        "peak_bf16_tflops": (
            device_peak_flops() / 1e12 if device_peak_flops() else None
        ),
    }


def ladder_batch(cfg, n_chips: int) -> tuple[int, str]:
    """Global batch to run a ladder config with on `n_chips`.

    A config's batch_size is sized for `cfg.ladder_devices` chips; on a
    smaller box the PER-CHIP batch (the steps/sec/chip-relevant quantity)
    is preserved instead of cramming the pod-slice batch into one chip's
    HBM (measured: vit_tiny_cifar's batch-1024 step needs 19.4G vs the
    v5e's 15.75G). Returns (batch, provenance_note)."""
    if n_chips != cfg.ladder_devices:
        # both directions: a smaller box must not cram the pod-slice batch
        # into one chip's HBM, and a BIGGER box must not shrink the per-chip
        # batch (which would read as a fake per-chip regression vs anchors)
        per_chip = max(1, cfg.batch_size // cfg.ladder_devices)
        return per_chip * n_chips, (
            f"per-chip geometry of the {cfg.ladder_devices}-chip ladder "
            f"config: {per_chip}/chip x {n_chips} chips")
    return cfg.batch_size, "config global batch"


def bench_config(name: str, n_timed: int) -> int:
    """Steady-state throughput + MFU for one ladder config (no accuracy
    race — only the headline MNIST config has a published accuracy target).

    Times the config's REAL training step: optimizer pipeline (schedule,
    clipping, weight decay, accumulation) via cli.train.build_optimizer and
    the config's loss — not a simplified stand-in. Runs on the config's own
    mesh (`cfg.mesh`) when this box has the chips; otherwise falls back to
    all visible devices and says so."""
    import jax

    from dist_mnist_tpu.cli.train import build_optimizer
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.data import DeviceDataset, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops import losses
    from dist_mnist_tpu.parallel.sharding import resolve_rules, shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.step import make_scanned_train_fn
    from dist_mnist_tpu.utils.prng import prng_impl_scope
    from dist_mnist_tpu.utils.timing import timed_chunks

    cfg = get_config(name)
    rules = resolve_rules(cfg.sharding_rules)  # a TP config benches TP
    try:
        mesh = make_mesh(cfg.mesh)  # the config's declared topology
        mesh_note = "config"
    except ValueError:
        # e.g. an 8-way config on this 1-chip box: run on what exists. The
        # data-only fallback mesh collapses the strategy axes (model/seq/
        # pipe) to 1, so a non-DP rule set cannot measure its strategy —
        # bench DP and SAY SO instead of mislabeling (ADVICE r3 #1).
        from dist_mnist_tpu.parallel.sharding import DP_RULES

        mesh = make_mesh(MeshSpec(data=-1))
        mesh_note = f"fallback (config wants {cfg.mesh}, have {jax.device_count()})"
        if cfg.sharding_rules != "dp":
            rules = DP_RULES
            mesh_note += (
                f"; strategy axes unavailable — benched as DP, not "
                f"{cfg.sharding_rules!r}")
    n_chips = mesh.devices.size
    global_batch, batch_note = ladder_batch(cfg, n_chips)
    dataset = load_dataset(cfg.dataset, "/tmp/mnist-data", seed=cfg.seed)
    model = get_model(cfg.model, **cfg.model_kwargs)
    optimizer = build_optimizer(cfg)
    loss_fn = (losses.clipped_softmax_cross_entropy if cfg.loss == "clipped"
               else losses.softmax_cross_entropy)
    chunk = 100
    # the config's PRNG impl, like cli/train: keys are made at state
    # creation, so the scope covers build + timed run (utils/prng.py)
    with prng_impl_scope(cfg.prng_impl), activate(mesh):
        state = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        state = shard_train_state(state, mesh, rules)
        dd = DeviceDataset(dataset, mesh)
        run = make_scanned_train_fn(model, optimizer, mesh, dd,
                                    global_batch, chunk, loss_fn=loss_fn,
                                    rules=rules,
                                    remat=cfg.remat, augment=cfg.augment,
                                    remat_policy=cfg.remat_policy)
        # timed_chunks = the axon-hardened device_get stop-clock
        dt, state, _ = timed_chunks(run, state, max(1, n_timed // chunk))
        n_steps = max(1, n_timed // chunk) * chunk
        rate = n_steps / dt / n_chips
        # PER-CHIP basis: batch/chip vs one chip's peak (XLA's count is
        # per-shard on a partitioned program, matching this convention)
        mfu_block = _mfu_fields(run, state, dt / n_steps, model=model,
                                sample_shape=dataset.train_images[:1].shape,
                                batch=global_batch // n_chips)
    emit({
        "metric": f"{name}_steps_per_sec_per_chip",
        "value": round(rate, 2),
        "unit": "steps/sec/chip",
        "vs_baseline": 0.0,  # no published reference numbers (BASELINE.md)
        "synthetic_data": dataset.synthetic,
        "extra": {
            "chips": n_chips,
            "mesh": mesh_note,
            "global_batch": global_batch,
            "batch_note": batch_note,
            "examples_per_sec": round(rate * n_chips * global_batch),
            **mfu_block,
            **_anchor_fields(f"{name}_steps_per_sec_per_chip", rate),
        },
    })
    return 0


def bench_serve(n_requests: int, concurrency: int) -> int:
    """Online-serving latency: drive the inference server with the
    deterministic closed-loop loadgen and report p99 request latency.

    `vs_baseline` is 0.0 (latency has no seed anchor yet; the anchor file
    machinery picks it up once a BENCH round records one). Weights are a
    fresh deterministic init — serving latency does not depend on weight
    VALUES, and bench must not require a training run to have happened."""
    import jax

    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.serve import (
        InferenceEngine,
        InferenceServer,
        ServeConfig,
        load_for_serving,
        run_loadgen,
    )

    metric = "serve_p99_latency_ms"
    mesh = make_mesh(MeshSpec(data=-1))
    bundle = load_for_serving("mlp_mnist", mesh)
    engine = InferenceEngine(
        bundle.model, bundle.params, bundle.model_state, mesh,
        model_name="mlp", image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=64,
    )
    server = InferenceServer(engine, ServeConfig(
        max_batch=64, max_wait_ms=2.0, queue_depth=4 * concurrency,
    ))
    with server:
        # warmup pass so compile/first-dispatch never lands in the timed run
        run_loadgen(server, n_requests=concurrency,
                    concurrency=concurrency,
                    image_shape=bundle.image_shape, seed=1)
        summary = run_loadgen(server, n_requests=n_requests,
                              concurrency=concurrency,
                              image_shape=bundle.image_shape, seed=0)
    # the streaming-histogram layer's view of the same run (obs/hist.py —
    # what /metrics exposes live): bounded-error percentiles next to the
    # loadgen's exact ones, as a cross-check on the exposition path
    hist_pcts = server.metrics.latency_percentiles()
    emit({
        "metric": metric,
        "value": round(summary["p99_ms"], 2),
        "unit": "ms",
        "vs_baseline": 0.0,
        "extra": {
            "chips": jax.device_count(),
            "p50_ms": round(summary["p50_ms"], 2),
            "p95_ms": round(summary["p95_ms"], 2),
            "mean_ms": round(summary["mean_ms"], 2),
            "hist_latency_ms": {k: round(v, 2)
                                for k, v in hist_pcts.items()},
            "n_requests": n_requests,
            "concurrency": concurrency,
            "ok": summary["ok"],
            "rejected_queue_full": summary["rejected_queue_full"],
            "mean_batch_size": round(summary["mean_batch_size"], 2),
            "mean_occupancy": round(summary["mean_occupancy"], 3),
            "cache": summary["cache"],
            **_anchor_fields(metric, summary["p99_ms"]),
        },
    })
    return 0


def bench_serve_longctx(n_requests: int, concurrency: int) -> int:
    """Long-context (variable-length) serving through the model-zoo grid
    (serve/zoo.py): a maskable ViT behind the auto power-of-two height
    ladder, driven with seeded variable-height traffic. Reports the p99
    over ALL heights plus the zoo's load-bearing counters: per-device
    resident weight bytes (the sharded-serving number), per-seq-bucket
    request routing, and the compile-cache miss delta during traffic —
    which must be ZERO after prewarm (the no-hot-path-recompile
    guarantee the 2-D grid exists to give)."""
    import jax

    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.serve import (
        InferenceServer,
        ServeConfig,
        build_zoo_engine,
        load_for_serving,
        run_longctx_loadgen,
    )

    metric = "longctx_p99_ms"
    mesh = make_mesh(MeshSpec(data=-1))
    bundle = load_for_serving("vit_tiny_cifar", mesh)
    # max_batch 32 bounds the grid: 3 batch buckets x (1 dense + masked
    # ladder) executables, all compiled up front by prewarm
    engine = build_zoo_engine(
        bundle, mesh, model_name="vit_tiny", max_bucket=32,
        seq_buckets="auto",
    )
    server = InferenceServer(engine, ServeConfig(
        max_batch=32, max_wait_ms=2.0, queue_depth=4 * concurrency,
    ))
    with server:
        # warmup traffic AFTER prewarm: first-dispatch cost off the timed
        # run (prewarm already took every compile off it)
        run_longctx_loadgen(server, n_requests=concurrency,
                            concurrency=concurrency, seed=1)
        summary = run_longctx_loadgen(server, n_requests=n_requests,
                                      concurrency=concurrency, seed=0)
    if summary["recompiles_during_traffic"]:
        emit_error(metric,
                   f"{summary['recompiles_during_traffic']} hot-path "
                   "recompile(s) after a full grid prewarm")
        return 1
    state_bytes = engine.state_bytes_per_device()
    emit({
        "metric": metric,
        "value": round(summary["p99_ms"], 2),
        "unit": "ms",
        "vs_baseline": 0.0,
        "extra": {
            "chips": jax.device_count(),
            "p50_ms": round(summary["p50_ms"], 2),
            "p95_ms": round(summary["p95_ms"], 2),
            "mean_ms": round(summary["mean_ms"], 2),
            "n_requests": n_requests,
            "concurrency": concurrency,
            "ok": summary["ok"],
            "seq_buckets": list(engine.seq_grid.heights),
            "seq_bucket_counts": summary["seq_bucket_counts"],
            "recompiles_during_traffic":
                summary["recompiles_during_traffic"],
            "serve_state_bytes_per_device": state_bytes,
            "cache": summary["cache"],
            "mean_seq_occupancy": round(summary["mean_seq_occupancy"], 3),
            "mean_batch_size": round(summary["mean_batch_size"], 2),
            **_anchor_fields(metric, summary["p99_ms"]),
        },
    })
    return 0


def _decode_forced_agreement(engine, reqs, streams) -> tuple[int, int]:
    """Teacher-forced next-token agreement: replay a reference engine's
    token streams through `engine`, forcing every step's input token to
    the reference token, and count argmax matches. This isolates
    KV-quantization fidelity per position — a free-running comparison
    would let one flipped near-tie cascade through the rest of the
    stream and punish the quantizer for autoregression, not accuracy."""
    import numpy as np

    rows = engine.grid.rows
    match = total = 0
    for at in range(0, len(reqs), engine.max_slots):
        chunk = list(zip(reqs[at:at + engine.max_slots],
                         streams[at:at + engine.max_slots]))
        slots = list(range(len(chunk)))
        for slot, ((prompt, _), stream) in zip(slots, chunk):
            if not engine.try_reserve(slot, len(prompt) + len(stream)):
                raise RuntimeError("KV page pool too small for replay")
        first = engine.prefill([p for (p, _), _ in chunk], slots)
        tokens = np.zeros(rows, np.int32)
        positions = np.zeros(rows, np.int32)
        live = {}
        plen = {}
        for slot, ((prompt, _), stream) in zip(slots, chunk):
            match += int(first[slot] == stream[0])
            total += 1
            plen[slot] = len(prompt)
            if len(stream) > 1:
                live[slot] = 1  # index of the next position to predict
        while live:
            for slot, i in live.items():
                tokens[slot] = streams[at + slot][i - 1]
                positions[slot] = plen[slot] + i - 1
            nxt = engine.decode(tokens, positions)
            for slot, i in list(live.items()):
                match += int(nxt[slot] == streams[at + slot][i])
                total += 1
                if i + 1 < len(streams[at + slot]):
                    live[slot] = i + 1
                else:
                    del live[slot]
        for slot in slots:
            engine.release_slot(slot)
    return match, total


def bench_serve_decode(n_requests: int, concurrency: int) -> int:
    """Autoregressive decode serving (serve/decode.py): continuous
    batching vs the static-batch baseline, SAME engine weights, SAME
    compiled executables (one shared CompiledModelCache), SAME seeded
    request stream. Reports decode's two SLO numbers — TTFT p99 and
    per-request token throughput — side by side for both modes, and
    enforces the three contracts the subsystem exists to give:

    - bit-identical token streams between scheduling modes (scheduling
      decides WHEN a request runs, never WHAT it computes),
    - zero hot-path recompiles after the decode-grid prewarm,
    - continuous batching strictly beats static on TTFT p99 at equal
      offered load (the reason continuous batching exists: a request
      arriving mid-batch is admitted at the next step instead of
      waiting for the whole static batch to finish).

    Then the paged + quantized KV trio, at EQUAL worst-case capacity
    (every engine provisioned for the same long max_seq, driven by the
    same short-request traffic — the serving regime paging exists for,
    where the dense stripe pays full-capacity attention every step and
    the paged engine pays only for live pages):

    - paged-float streams bitwise-identical to the dense twin's (the
      cache_layout="dense" contract: paging relocates KV, never
      changes the math),
    - int8 KV teacher-forced token agreement >= 0.99 vs the float
      engine (per-position fidelity, cascade-free),
    - peak resident KV bytes (pinned pages + scratch stripe, the
      high-water the allocator actually charged) <= 0.35x the dense
      engine's allocation,
    - int8 tokens/s strictly above dense and TTFT p99 no worse,
    - zero hot-path recompiles on all three engines.

    Emits two extra anchored records: `decode_kv_bytes_ratio` and
    `decode_tokens_per_s` (the int8 engine's per-request throughput).
    """
    import jax

    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.serve import (
        CompiledModelCache,
        DecodeScheduler,
        build_decode_engine,
        run_decode_loadgen,
    )

    metric = "decode_ttft_p99_ms"
    mesh = make_mesh(MeshSpec(data=-1))
    cache = CompiledModelCache()
    max_slots = 8

    def run(mode: str) -> dict:
        # a fresh engine per mode resets the KV cache and slot state, but
        # the shared compile cache means mode 2 compiles NOTHING
        engine = build_decode_engine(mesh, max_slots=max_slots,
                                     cache=cache)
        engine.prewarm()
        sched = DecodeScheduler(engine, mode=mode)
        try:
            # warmup traffic after prewarm: first-dispatch cost off the
            # timed run
            run_decode_loadgen(sched, n_requests=2 * max_slots,
                               concurrency=concurrency, seed=1)
            return run_decode_loadgen(sched, n_requests=n_requests,
                                      concurrency=concurrency, seed=0,
                                      keep_streams=True)
        finally:
            sched.close()

    continuous = run("continuous")
    static = run("static")

    for mode, summary in (("continuous", continuous), ("static", static)):
        if summary["errors"] or summary["ok"] != n_requests:
            emit_error(metric,
                       f"{mode} run lost requests: ok={summary['ok']} "
                       f"errors={summary['errors']} of {n_requests}")
            return 1
        if summary["recompiles_during_traffic"]:
            emit_error(metric,
                       f"{summary['recompiles_during_traffic']} hot-path "
                       f"recompile(s) in {mode} mode after a full decode-"
                       "grid prewarm")
            return 1
    if continuous["streams"] != static["streams"]:
        ndiff = sum(a != b for a, b in zip(continuous["streams"],
                                           static["streams"]))
        emit_error(metric,
                   f"token streams differ between scheduling modes "
                   f"({ndiff}/{n_requests} requests) — continuous "
                   "batching changed WHAT was computed, not just when")
        return 1
    if not continuous["ttft_p99_ms"] < static["ttft_p99_ms"]:
        emit_error(metric,
                   f"continuous TTFT p99 {continuous['ttft_p99_ms']:.2f} ms"
                   f" not better than static {static['ttft_p99_ms']:.2f} ms"
                   " at equal offered load",
                   continuous_ttft_p99_ms=round(
                       continuous["ttft_p99_ms"], 2),
                   static_ttft_p99_ms=round(static["ttft_p99_ms"], 2))
        return 1

    # ---- paged + quantized KV trio: equal worst-case capacity ----------
    # long-capacity engines under short-request traffic; the trio's
    # geometry is independent of the mode-comparison legs above, whose
    # defaults (and decode_ttft_p99_ms semantics) are untouched
    from dist_mnist_tpu.serve.loadgen import make_prompts

    geom = dict(dim=128, heads=8, max_seq=4096, depth=2)
    traffic = dict(max_prompt=32, max_new=32)

    def run_capacity(**overrides) -> tuple:
        engine = build_decode_engine(mesh, max_slots=max_slots,
                                     cache=CompiledModelCache(),
                                     prompt_buckets=(16, 32),
                                     **geom, **overrides)
        engine.prewarm()
        with DecodeScheduler(engine, mode="continuous") as sched:
            run_decode_loadgen(sched, n_requests=2 * max_slots,
                               concurrency=concurrency, seed=1, **traffic)
            summary = run_decode_loadgen(sched, n_requests=n_requests,
                                         concurrency=concurrency, seed=0,
                                         keep_streams=True, **traffic)
        return summary, engine

    dense_cap, dense_eng = run_capacity()
    paged_cap, _ = run_capacity(cache_layout="paged", kv_page_tokens=32)
    int8_cap, int8_eng = run_capacity(cache_layout="paged",
                                      kv_page_tokens=32, kv_quant="int8")
    for name, summary in (("dense-cap", dense_cap), ("paged-cap", paged_cap),
                          ("int8-cap", int8_cap)):
        if summary["errors"] or summary["ok"] != n_requests:
            emit_error(metric,
                       f"{name} leg lost requests: ok={summary['ok']} "
                       f"errors={summary['errors']} of {n_requests}")
            return 1
        if summary["recompiles_during_traffic"]:
            emit_error(metric,
                       f"{summary['recompiles_during_traffic']} hot-path "
                       f"recompile(s) in the {name} leg after prewarm")
            return 1
    if paged_cap["streams"] != dense_cap["streams"]:
        ndiff = sum(a != b for a, b in zip(paged_cap["streams"],
                                           dense_cap["streams"]))
        emit_error(metric,
                   f"paged-float streams differ from the dense twin's "
                   f"({ndiff}/{n_requests} requests) — paging changed "
                   "the math, not just the KV layout")
        return 1
    # teacher-forced replay of the dense streams through the int8 engine
    # (bounded: 64 requests is plenty of positions for the gate)
    n_replay = min(n_requests, 64)
    reqs = make_prompts(n_replay, max_seq=geom["max_seq"], seed=0,
                        vocab_size=int8_eng.model.vocab_size, **traffic)
    agree_hits, agree_total = _decode_forced_agreement(
        int8_eng, reqs, dense_cap["streams"][:n_replay])
    agreement = agree_hits / max(1, agree_total)
    if agreement < 0.99:
        emit_error(metric,
                   f"int8 KV teacher-forced agreement {agreement:.4f} "
                   f"< 0.99 ({agree_hits}/{agree_total} positions)")
        return 1
    kv = int8_eng.kv_stats()
    dense_kv_bytes = dense_eng.kv_stats()["kv_bytes_pinned"]
    ratio = kv["kv_bytes_peak"] / dense_kv_bytes
    if ratio > 0.35:
        emit_error(metric,
                   f"int8 paged peak resident KV {kv['kv_bytes_peak']} B "
                   f"is {ratio:.3f}x the dense allocation "
                   f"{dense_kv_bytes} B (> 0.35x)")
        return 1
    if not int8_cap["tokens_per_s_mean"] > dense_cap["tokens_per_s_mean"]:
        emit_error(metric,
                   f"int8 paged tokens/s {int8_cap['tokens_per_s_mean']:.2f}"
                   f" not above dense {dense_cap['tokens_per_s_mean']:.2f}"
                   " at equal capacity")
        return 1
    if int8_cap["ttft_p99_ms"] > dense_cap["ttft_p99_ms"]:
        emit_error(metric,
                   f"int8 paged TTFT p99 {int8_cap['ttft_p99_ms']:.2f} ms "
                   f"worse than dense {dense_cap['ttft_p99_ms']:.2f} ms")
        return 1

    emit({
        "metric": metric,
        "value": round(continuous["ttft_p99_ms"], 2),
        "unit": "ms",
        "vs_baseline": 0.0,
        "extra": {
            "chips": jax.device_count(),
            "decode_tokens_per_s": round(
                continuous["tokens_per_s_mean"], 2),
            "ttft_p50_ms": round(continuous["ttft_p50_ms"], 2),
            "static_ttft_p99_ms": round(static["ttft_p99_ms"], 2),
            "static_tokens_per_s": round(static["tokens_per_s_mean"], 2),
            "ttft_p99_speedup_vs_static": round(
                static["ttft_p99_ms"] / continuous["ttft_p99_ms"], 2),
            "n_requests": n_requests,
            "concurrency": concurrency,
            "max_slots": max_slots,
            "tokens_out": continuous["tokens_out"],
            "streams_identical": True,
            "recompiles_during_traffic": 0,
            "mean_active_slots": {
                "continuous": round(
                    continuous["scheduler"]["mean_active_slots"], 2),
                "static": round(
                    static["scheduler"]["mean_active_slots"], 2),
            },
            "cache": continuous["cache"],
            **_anchor_fields(metric, continuous["ttft_p99_ms"]),
        },
    })
    emit({
        "metric": "decode_kv_bytes_ratio",
        "value": round(ratio, 4),
        "unit": "ratio",
        "vs_baseline": 0.0,
        "extra": {
            "kv_bytes_peak": kv["kv_bytes_peak"],
            "dense_kv_bytes": dense_kv_bytes,
            "kv_pages_total": kv["kv_pages_total"],
            "page_tokens": kv["page_tokens"],
            "kv_quant": kv["kv_quant"],
            "int8_forced_agreement": round(agreement, 4),
            "paged_float_streams_bitwise": True,
            **_anchor_fields("decode_kv_bytes_ratio", ratio),
        },
    })
    emit({
        "metric": "decode_tokens_per_s",
        "value": round(int8_cap["tokens_per_s_mean"], 2),
        "unit": "tokens/s/request",
        "vs_baseline": 0.0,
        "extra": {
            "dense_tokens_per_s": round(dense_cap["tokens_per_s_mean"], 2),
            "paged_float_tokens_per_s": round(
                paged_cap["tokens_per_s_mean"], 2),
            "speedup_vs_dense": round(int8_cap["tokens_per_s_mean"]
                                      / dense_cap["tokens_per_s_mean"], 2),
            "int8_ttft_p99_ms": round(int8_cap["ttft_p99_ms"], 2),
            "dense_ttft_p99_ms": round(dense_cap["ttft_p99_ms"], 2),
            "max_seq": geom["max_seq"],
            **_anchor_fields("decode_tokens_per_s",
                             int8_cap["tokens_per_s_mean"]),
        },
    })
    return 0


def bench_serve_quant(n_requests: int, concurrency: int) -> int:
    """Quantized serving, proved not just logged: the SAME deterministic
    loadgen stream through a float engine and an int8 weight-only engine
    (ops/quant.py) side by side. Asserts, per ISSUE 14's ladder:
    resident weight bytes <= 0.30x float, top-1 agreement >= 0.99 on the
    stream's image pool, quantized p99 <= 1.10x float p99, and ZERO
    hot-path recompiles after prewarm on both engines. Reports
    `quant_p99_ms` plus a second anchored record,
    `quant_resident_bytes_ratio`."""
    import jax
    import numpy as np

    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.serve import (
        InferenceServer,
        ServeConfig,
        build_zoo_engine,
        load_for_serving,
        run_loadgen,
    )
    from dist_mnist_tpu.serve.loadgen import make_images

    metric = "quant_p99_ms"
    mesh = make_mesh(MeshSpec(data=-1))
    # one float load + one int8 load of the SAME deterministic init: the
    # quant bundle's params are the float bundle's, converted (the bench
    # needs no training run to exist; agreement is measured between the
    # two engines, not against labels)
    bundle_f = load_for_serving("mlp_mnist", mesh)
    bundle_q = load_for_serving("mlp_mnist", mesh, quant="int8")
    runs = {}
    engines = {}
    for tag, bundle in (("float", bundle_f), ("int8", bundle_q)):
        engine = build_zoo_engine(bundle, mesh, model_name="mlp",
                                  max_bucket=64)
        engines[tag] = engine
        server = InferenceServer(engine, ServeConfig(
            max_batch=64, max_wait_ms=2.0, queue_depth=4 * concurrency,
        ))
        with server:
            # warmup AFTER prewarm: first-dispatch cost off the timed run
            run_loadgen(server, n_requests=concurrency,
                        concurrency=concurrency,
                        image_shape=bundle.image_shape, seed=1)
            misses0 = engine.cache.misses
            summary = run_loadgen(server, n_requests=n_requests,
                                  concurrency=concurrency,
                                  image_shape=bundle.image_shape, seed=0)
        summary["recompiles_during_traffic"] = \
            engine.cache.misses - misses0
        runs[tag] = summary
    for tag, summary in runs.items():
        if summary["recompiles_during_traffic"]:
            emit_error(metric,
                       f"{summary['recompiles_during_traffic']} hot-path "
                       f"recompile(s) on the {tag} engine after prewarm")
            return 1
    # resident weight bytes under the engines' ACTUAL placements — the
    # number the serve memory budget rations
    bytes_f = engines["float"].state_bytes_per_device()
    bytes_q = engines["int8"].state_bytes_per_device()
    ratio = bytes_q["param_bytes"] / max(bytes_f["param_bytes"], 1)
    if ratio > 0.30:
        emit_error(metric,
                   f"quantized resident weight bytes {ratio:.3f}x float "
                   "(gate: <= 0.30x)")
        return 1
    # top-1 agreement over the timed stream's image pool (seed=0 — the
    # exact images the loadgen cycled through), batch-bucket sized chunks
    # so no new executable compiles here
    pool = make_images(bundle_f.image_shape, seed=0)
    flips = 0
    for i in range(0, len(pool), 64):
        lf = engines["float"].predict(pool[i:i + 64])
        lq = engines["int8"].predict(pool[i:i + 64])
        flips += int(np.sum(np.argmax(lf, -1) != np.argmax(lq, -1)))
    agreement = 1.0 - flips / len(pool)
    if agreement < 0.99:
        emit_error(metric,
                   f"top-1 agreement {agreement:.4f} vs the float engine "
                   "(gate: >= 0.99)")
        return 1
    p99_f, p99_q = runs["float"]["p99_ms"], runs["int8"]["p99_ms"]
    if p99_q > 1.10 * p99_f:
        emit_error(metric,
                   f"quantized p99 {p99_q:.2f} ms > 1.10x float p99 "
                   f"{p99_f:.2f} ms")
        return 1
    report = bundle_q.quant_report
    # the resident-bytes ratio is its own anchored record: deterministic
    # (pure dtype arithmetic), so the regression gate pins it tightly
    emit({
        "metric": "quant_resident_bytes_ratio",
        "value": round(ratio, 4),
        "unit": "x_float",
        "vs_baseline": 0.0,
        "extra": {
            "float_param_bytes": bytes_f["param_bytes"],
            "int8_param_bytes": bytes_q["param_bytes"],
            **_anchor_fields("quant_resident_bytes_ratio", ratio),
        },
    })
    emit({
        "metric": metric,
        "value": round(p99_q, 2),
        "unit": "ms",
        "vs_baseline": 0.0,
        "extra": {
            "chips": jax.device_count(),
            "float_p99_ms": round(p99_f, 2),
            "p99_ratio_vs_float": round(p99_q / max(p99_f, 1e-9), 3),
            "p50_ms": round(runs["int8"]["p50_ms"], 2),
            "mean_ms": round(runs["int8"]["mean_ms"], 2),
            "float_mean_ms": round(runs["float"]["mean_ms"], 2),
            "resident_bytes_ratio": round(ratio, 4),
            "top1_agreement": round(agreement, 4),
            "top1_flips": flips,
            "pool_size": len(pool),
            "quant_error_max": report["max_abs_err"],
            "quant_rel_err_max": report["max_rel_err"],
            "quant_leaves": report["n_quantized"],
            "per_leaf_rel_err": {
                k: round(v["rel_err"], 6)
                for k, v in report["leaves"].items()},
            "recompiles_during_traffic": 0,
            "n_requests": n_requests,
            "concurrency": concurrency,
            "ok": runs["int8"]["ok"],
            "cache": runs["int8"]["cache"],
            **_anchor_fields(metric, p99_q),
        },
    })
    return 0


def bench_serve_fleet(n_requests: int, concurrency: int, *,
                      replicas: int = 3) -> int:
    """Fleet-serving robustness: two-class traffic through a 3-replica
    `serve/router.py` Router while a seeded fault plan kills one replica
    and stalls another, then a new checkpoint commit triggers a live
    replica-by-replica weight roll UNDER load. Reports the
    latency-sensitive p99 across both events (the SLO the tiering, hedging
    and failover machinery exists to protect), and asserts the router
    contract outright: zero latency-sensitive requests failed or shed
    (only best-effort may shed), zero in-flight requests dropped, and the
    fleet serving the new weights at the end. `replica_down` -> first
    rerouted response is reported as recovery_ms."""
    import dataclasses
    import shutil
    import tempfile
    import threading
    import time

    import jax
    import jax.numpy as jnp

    from dist_mnist_tpu.checkpoint.manager import CheckpointManager
    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.faults import Fault, FaultPlan
    from dist_mnist_tpu.models.registry import get_model
    from dist_mnist_tpu.obs import HealthState, RunJournal
    from dist_mnist_tpu.obs import events as events_mod
    from dist_mnist_tpu.optim import adam
    from dist_mnist_tpu.serve import (
        LATENCY_SENSITIVE,
        BEST_EFFORT,
        CheckpointWatcher,
        CompiledModelCache,
        InferenceEngine,
        InferenceServer,
        InProcessReplica,
        Router,
        RouterConfig,
        ServeConfig,
        load_for_serving,
        run_fleet_loadgen,
    )
    from dist_mnist_tpu.train.state import create_train_state

    metric = "fleet_p99_latency_sensitive_ms"
    base_step, new_step = 100, 200
    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    journal = RunJournal(f"{tmp}/events.jsonl")
    prev_journal = events_mod.set_journal(journal)
    mesh = make_mesh(MeshSpec(data=-1))
    cfg = get_config("mlp_mnist")
    ckpt_dir = f"{tmp}/ckpt"

    # a real committed checkpoint as the swap SOURCE: base weights at
    # base_step now, perturbed weights at new_step mid-run (the commit the
    # watcher reacts to)
    model = get_model(cfg.model, **cfg.model_kwargs)
    sample = jnp.zeros((1, 28, 28, 1), jnp.float32)
    state = create_train_state(model, adam(1e-3),
                               jax.random.PRNGKey(cfg.seed), sample)
    state = dataclasses.replace(state, step=jnp.asarray(base_step, jnp.int32))
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    assert mgr.save(state)
    mgr.wait()

    bundle = load_for_serving(cfg, mesh, checkpoint_dir=ckpt_dir,
                              step=base_step)
    assert bundle.restored
    # seeded incident plan, per-replica predict-call ordinals: replica 0
    # straggles once early (hedge territory), replica 1 dies permanently
    # (failover territory); replica 2 is never touched
    plan = FaultPlan([
        Fault.serve_replica_stall(replica=0, seconds=0.25, request=2),
        Fault.serve_replica_kill(replica=1, request=3),
    ])
    shared_cache = CompiledModelCache()

    def make_server_factory(rid: int):
        def make_server():
            engine = InferenceEngine(
                bundle.model, bundle.params, bundle.model_state, mesh,
                model_name="mlp", image_shape=bundle.image_shape,
                rules=bundle.rules, max_bucket=32, cache=shared_cache)
            return InferenceServer(
                plan.wrap_engine(engine, replica_id=rid),
                ServeConfig(max_batch=32, max_wait_ms=1.0,
                            queue_depth=4 * concurrency),
                health=HealthState(),
            ).start()

        return make_server

    def load_weights(step: int):
        new = load_for_serving(cfg, mesh, checkpoint_dir=ckpt_dir, step=step)
        if not new.restored:
            raise FileNotFoundError(f"no committed checkpoint at {step}")
        return new.params, new.model_state

    fleet = [InProcessReplica(i, make_server_factory(i),
                              load_weights=load_weights).start()
             for i in range(replicas)]
    router = Router(fleet, RouterConfig(hedge_after_ms=50.0,
                                        health_interval_s=0.05),
                    ).start()
    watcher = CheckpointWatcher(ckpt_dir, router.roll_weights,
                                poll_interval_s=0.05,
                                initial_step=base_step)

    def run_phase(n, seed):
        return run_fleet_loadgen(
            router, n_requests=n, concurrency=concurrency,
            image_shape=bundle.image_shape, seed=seed, ls_fraction=0.8,
            keep_latencies=True)

    try:
        # -- phase 1: the stall + the kill land under steady load ------------
        phase1 = run_phase(n_requests, seed=0)
        extra_rounds = 0
        while any(not f.fired for f in plan.faults) and extra_rounds < 5:
            # ordinals are per-replica; tiny fleets can need a little more
            # traffic before the victim's own call clock reaches them
            extra_rounds += 1
            run_phase(max(concurrency * 2, 64), seed=10 + extra_rounds)
        assert all(f.fired for f in plan.faults), \
            f"fault plan did not fully fire: {plan.to_json()}"

        # -- phase 2: commit new weights mid-load; the watcher rolls ---------
        watcher.start()
        phase2_out: dict = {}

        def phase2_run():
            phase2_out.update(run_phase(n_requests, seed=1))

        t_load = threading.Thread(target=phase2_run, name="fleet-phase2")
        t_load.start()
        time.sleep(0.15)  # the roll must overlap live traffic
        state2 = dataclasses.replace(
            state, step=jnp.asarray(new_step, jnp.int32),
            params=jax.tree.map(lambda p: p + 1.0, state.params))
        assert mgr.save(state2)
        mgr.wait()
        t_load.join(timeout=180)
        assert not t_load.is_alive(), "phase-2 loadgen hung"
        deadline = time.monotonic() + 30
        while router.serving_step != new_step:
            assert time.monotonic() < deadline, "weight roll never completed"
            time.sleep(0.05)

        # -- the router contract, asserted ----------------------------------
        for phase, name in ((phase1, "phase1"), (phase2_out, "phase2")):
            assert phase["errors"][LATENCY_SENSITIVE] == 0, \
                f"{name}: LS errors {phase['errors']}"
            assert phase["shed"][LATENCY_SENSITIVE] == 0, \
                f"{name}: LS shed {phase['shed']}"
            assert sum(phase["dropped"].values()) == 0, \
                f"{name}: dropped in-flight {phase['dropped']}"
        rsnap = router.metrics.snapshot()
        assert rsnap["replica_downs"] >= 1, "kill never surfaced"
        assert rsnap["recovery_ms"], "no failover recovery latency recorded"
        assert rsnap["swaps"] >= replicas - 1, \
            f"expected >= {replicas - 1} live-replica swaps, got {rsnap}"
        for r in fleet:
            if router.replica_states()[r.id] == "serving":
                assert r.server.engine.weights_version == new_step

        recs = events_mod.read_journal(f"{tmp}/events.jsonl")
        kinds = [r.get("event") for r in recs]
        assert "replica_down" in kinds and "failover_first_response" in kinds
        n_swap_ok = sum(1 for r in recs
                        if r.get("event") == "weights_swap" and r.get("ok"))
        assert n_swap_ok >= replicas - 1

        ls_lat = (phase1["raw_latencies"][LATENCY_SENSITIVE]
                  + phase2_out["raw_latencies"][LATENCY_SENSITIVE])
        import numpy as np

        p99 = float(np.percentile(np.asarray(ls_lat), 99))
        emit({
            "metric": metric,
            "value": round(p99, 2),
            "unit": "ms",
            "vs_baseline": 0.0,
            "extra": {
                "chips": jax.device_count(),
                "replicas": replicas,
                "recovery_ms": round(rsnap["recovery_ms"][0], 2),
                "phase1_ls": phase1[f"latency_{LATENCY_SENSITIVE}"],
                "phase2_ls": phase2_out[f"latency_{LATENCY_SENSITIVE}"],
                "be_shed": {"phase1": phase1["shed"][BEST_EFFORT],
                            "phase2": phase2_out["shed"][BEST_EFFORT]},
                "hedges": rsnap["hedges"],
                "requeues": rsnap["requeues"],
                "swaps": rsnap["swaps"],
                "swap_ok_events": n_swap_ok,
                "serving_step": router.serving_step,
                "cache": shared_cache.stats()["hits_memory"],
                **_anchor_fields(metric, p99),
            },
        })
    finally:
        watcher.close()
        router.close()
        for r in fleet:
            r.close(timeout=10)
        mgr.close()
        events_mod.set_journal(prev_journal)
        journal.close()
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def bench_serve_autoscale(*, min_replicas: int = 1,
                          max_replicas: int = 4) -> int:
    """Chip economics of traffic-driven scaling: ONE seeded 10x
    flash-crowd trace (serve/loadgen.py flash_crowd_trace) replayed twice
    through otherwise-identical fleets — static provisioning at
    max_replicas for the whole run vs a serve/autoscale.py Autoscaler
    growing the fleet from min_replicas when the spike hits and shrinking
    it back after. Reports `chip_seconds_per_1k_ok` (replica-seconds
    integrated over the fleet's membership timeline x chips per replica,
    per thousand OK responses) under autoscaling, with the static cost as
    the baseline, and asserts the subsystem's three promises outright:
    the latency-sensitive p99 holds within SLO THROUGH the spike while
    scaling, the autoscaled chip cost is strictly below static, and
    every scale-up is a warm start — the journaled `replica_scale_up`
    receipts show zero shared-cache misses and ~zero compile seconds
    (the new replica rewarns AOT executables, it does not compile).

    Per-predict service time carries a fixed modeled floor (a paced
    engine proxy, the FaultyEngine idiom) so per-replica capacity — and
    therefore how hard the spike bites — is host-independent: the spike
    overwhelms min_replicas and fits inside max_replicas by
    construction, on any machine."""
    import dataclasses
    import shutil
    import tempfile
    import time
    from contextlib import nullcontext
    from pathlib import Path

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dist_mnist_tpu.checkpoint.manager import CheckpointManager
    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.compilecache import ExecutableStore
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.models.registry import get_model
    from dist_mnist_tpu.obs import HealthState, RunJournal
    from dist_mnist_tpu.obs import events as events_mod
    from dist_mnist_tpu.optim import adam
    from dist_mnist_tpu.serve import (
        LATENCY_SENSITIVE,
        Autoscaler,
        CompiledModelCache,
        FleetSignalSource,
        InferenceEngine,
        InferenceServer,
        InProcessReplica,
        Router,
        RouterConfig,
        ScalePolicy,
        ServeConfig,
        flash_crowd_trace,
        load_for_serving,
        run_trace_loadgen,
    )
    from dist_mnist_tpu.train.state import create_train_state

    metric = "chip_seconds_per_1k_ok"
    slo_p99_ms = 1000.0
    service_floor_s = 0.02  # modeled per-batch accelerator time
    tmp = tempfile.mkdtemp(prefix="bench_autoscale_")
    journal = RunJournal(f"{tmp}/events.jsonl")
    prev_journal = events_mod.set_journal(journal)
    mesh = make_mesh(MeshSpec(data=-1))
    cfg = get_config("mlp_mnist")
    ckpt_dir = f"{tmp}/ckpt"

    # a real committed checkpoint: scale-ups restore the SAME weights the
    # seed fleet serves (the peer-ring/store lane the CLI spawn uses)
    model = get_model(cfg.model, **cfg.model_kwargs)
    sample = jnp.zeros((1, 28, 28, 1), jnp.float32)
    state = create_train_state(model, adam(1e-3),
                               jax.random.PRNGKey(cfg.seed), sample)
    state = dataclasses.replace(state, step=jnp.asarray(100, jnp.int32))
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    assert mgr.save(state)
    mgr.wait()
    bundle = load_for_serving(cfg, mesh, checkpoint_dir=ckpt_dir, step=100)
    assert bundle.restored
    # shared cache WITH a disk tier: the warm-start lane under test
    shared_cache = CompiledModelCache(store=ExecutableStore(Path(tmp) / "exe"))

    class _PacedEngine:
        """Engine proxy adding the fixed modeled service time."""

        def __init__(self, inner):
            self._inner = inner

        def predict(self, *args, **kwargs):
            time.sleep(service_floor_s)
            return self._inner.predict(*args, **kwargs)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    max_batch = 4  # capacity per replica ~ max_batch / service_floor_s

    def make_replica(rid: int, startup=None):
        def make_server():
            with (startup.phase("restore") if startup is not None
                  else nullcontext()):
                engine = InferenceEngine(
                    bundle.model, bundle.params, bundle.model_state, mesh,
                    model_name="mlp", image_shape=bundle.image_shape,
                    rules=bundle.rules, max_bucket=max_batch,
                    cache=shared_cache)
                server = InferenceServer(
                    _PacedEngine(engine),
                    ServeConfig(max_batch=max_batch, max_wait_ms=1.0,
                                queue_depth=64),
                    health=HealthState())
            with (startup.phase("compile") if startup is not None
                  else nullcontext()):
                return server.start()

        return InProcessReplica(rid, make_server).start()

    # one seeded 10x flash crowd, reused verbatim for both runs: ~25 rps
    # baseline a single paced replica absorbs (~200 rps capacity), a
    # 250 rps spike only >= 2 can
    duration_s = 12.0
    arrivals = flash_crowd_trace(duration_s=duration_s, base_rps=25.0,
                                 spike_at_s=3.0, spike_len_s=2.5,
                                 spike_mult=10.0, decay_s=1.5, seed=0)

    def run_trace(router):
        return run_trace_loadgen(
            router, arrivals=arrivals, image_shape=bundle.image_shape,
            seed=0, ls_fraction=0.8)

    chips_per_replica = jax.device_count()

    def chip_secs_per_1k(replica_seconds: float, total_ok: int) -> float:
        return replica_seconds * chips_per_replica / max(total_ok, 1) * 1e3

    scaler = None
    try:
        # -- static: max_replicas provisioned for the whole trace ------------
        static_fleet = [make_replica(i) for i in range(max_replicas)]
        static_router = Router(
            static_fleet, RouterConfig(health_interval_s=0.05)).start()
        try:
            t0 = time.monotonic()
            static = run_trace(static_router)
            static_wall_s = time.monotonic() - t0
        finally:
            static_router.close()
            for r in static_fleet:
                r.close(timeout=10)
        static_rs = max_replicas * static_wall_s

        # -- autoscaled: min_replicas + the control loop ---------------------
        auto_fleet = [make_replica(i) for i in range(min_replicas)]
        auto_router = Router(
            auto_fleet, RouterConfig(health_interval_s=0.05)).start()

        def spawn(rid, startup):
            replica = make_replica(rid, startup)
            auto_fleet.append(replica)
            return replica

        def reap(replica):
            replica.close(timeout=10)
            if replica in auto_fleet:
                auto_fleet.remove(replica)

        scaler = Autoscaler(
            auto_router,
            FleetSignalSource(auto_router),
            spawn,
            reap=reap,
            policy=ScalePolicy(min_replicas=min_replicas,
                               max_replicas=max_replicas,
                               slo_p99_ms=slo_p99_ms,
                               backlog_up=0.25, idle_backlog=0.05,
                               idle_window_s=1.5, up_cooldown_s=0.4,
                               down_cooldown_s=2.0),
            interval_s=0.1,
            cache=shared_cache,
            warmup_timeout_s=30.0,
        ).start()
        try:
            auto = run_trace(auto_router)
            auto_rs = scaler.replica_seconds(floor=min_replicas)
        finally:
            scaler.close()
            auto_router.close()
            for r in list(auto_fleet):
                r.close(timeout=10)

        # -- the subsystem's promises, asserted ------------------------------
        assert scaler.scale_ups >= 1, \
            "the 10x flash crowd never triggered a scale-up"
        ups = [h for h in scaler.history if h["action"] == "up"]
        for receipt in ups:
            assert receipt.get("cache_misses", 0) == 0, \
                f"scale-up compiled (cache misses): {receipt}"
            assert receipt.get("cache_compile_ms", 0.0) < 1.0, \
                f"scale-up spent compile time: {receipt}"
        auto_p99 = auto[f"latency_{LATENCY_SENSITIVE}"]["p99_ms"]
        assert np.isfinite(auto_p99) and auto_p99 <= slo_p99_ms, \
            f"autoscaled LS p99 {auto_p99:.1f}ms broke the " \
            f"{slo_p99_ms:.0f}ms SLO through the spike"
        assert auto["errors"][LATENCY_SENSITIVE] == 0, \
            f"LS errors under autoscaling: {auto['errors']}"
        assert sum(auto["dropped"].values()) == 0, \
            f"dropped in-flight under autoscaling: {auto['dropped']}"
        cs_static = chip_secs_per_1k(static_rs, static["total_ok"])
        cs_auto = chip_secs_per_1k(auto_rs, auto["total_ok"])
        assert cs_auto < cs_static, \
            f"autoscaling did not beat static provisioning: " \
            f"{cs_auto:.1f} vs {cs_static:.1f} chip-s/1k ok"

        recs = events_mod.read_journal(f"{tmp}/events.jsonl")
        kinds = [r.get("event") for r in recs]
        assert "autoscale_decision" in kinds and "replica_scale_up" in kinds

        emit({
            "metric": metric,
            "value": round(cs_auto, 2),
            "unit": "chip_s/1k_ok",
            "vs_baseline": round(cs_static / max(cs_auto, 1e-9), 3),
            "extra": {
                "chips": chips_per_replica,
                "static_chip_seconds_per_1k_ok": round(cs_static, 2),
                "min_replicas": min_replicas,
                "max_replicas": max_replicas,
                "scale_ups": scaler.scale_ups,
                "scale_downs": scaler.scale_downs,
                "replica_seconds": {"static": round(static_rs, 2),
                                    "autoscaled": round(auto_rs, 2)},
                "ok": {"static": static["total_ok"],
                       "autoscaled": auto["total_ok"]},
                "ls_p99_ms": {
                    "static": round(
                        static[f"latency_{LATENCY_SENSITIVE}"]["p99_ms"], 2),
                    "autoscaled": round(auto_p99, 2)},
                "slo_p99_ms": slo_p99_ms,
                "warm_start": {
                    "scale_up_total_ms": [u["total_ms"] for u in ups],
                    "scale_up_compile_ms": [u["compile_ms"] for u in ups],
                    "cache_misses": [u.get("cache_misses") for u in ups],
                },
                "trace": {"kind": "flash_crowd", "arrivals": len(arrivals),
                          "duration_s": duration_s, "spike_mult": 10.0},
                **_anchor_fields(metric, cs_auto),
            },
        })
    finally:
        mgr.close()
        events_mod.set_journal(prev_journal)
        journal.close()
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def bench_input(n_timed: int, *, depth: int = 2, batch: int = 1024,
                warmup: int = 5) -> int:
    """Input-stall attribution: the same model/stream timed twice — once
    with the synchronous host feed (ShardedBatcher issues the sharded
    transfer inline in the hot loop) and once through `DevicePrefetcher`
    (transfer issued `depth` ahead by a background worker). Emits
    `input_stall_ms_per_step` (the prefetched feed's residual stall) with
    both feeds' numbers under extra, so a regression in overlap shows up
    as attribution, not just a slower headline.

    Both runs start from the SAME initial state (donate=False) over the
    same deterministic stream, so their loss trajectories are bit-identical
    — the final losses are cross-checked into extra."""
    import jax

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import DevicePrefetcher, ShardedBatcher, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.step import make_train_step

    metric = "input_stall_ms_per_step"
    mesh = make_mesh(MeshSpec(data=-1))
    n_chips = mesh.devices.size
    dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)
    with activate(mesh):
        model = get_model("mlp")
        optimizer = optim.adam(1e-3)
        state0 = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        state0 = shard_train_state(state0, mesh)
        # donate=False so BOTH timed runs consume the same initial buffers
        step = make_train_step(model, optimizer, mesh, donate=False)

        def timed_feed(batches) -> dict:
            """(wall_s, feed_stall_s, last_loss) over n_timed steps; warmup
            absorbs compile + first dispatch (and primes the prefetch ring)."""
            it = iter(batches)
            state = state0
            try:
                for _ in range(warmup):
                    state, out = step(state, next(it))
                jax.device_get(out["loss"])  # fence: warmup off the clock
                feed_s = 0.0
                t0 = time.monotonic()
                for _ in range(n_timed):
                    f0 = time.monotonic()
                    b = next(it)
                    feed_s += time.monotonic() - f0
                    state, out = step(state, b)
                loss = float(jax.device_get(out["loss"]))  # stop-clock
                wall_s = time.monotonic() - t0
            finally:
                if hasattr(it, "close"):
                    it.close()
            return {"wall_s": wall_s, "feed_s": feed_s, "loss": loss}

        sync_src = ShardedBatcher(dataset, batch, mesh, seed=0)
        sync = timed_feed(sync_src)
        pre_src = DevicePrefetcher(
            ShardedBatcher(dataset, batch, mesh, seed=0), depth=depth)
        pre = timed_feed(pre_src)
        pre_stats = pre_src.stats()

    ms = lambda s: round(s / n_timed * 1e3, 3)
    emit({
        "metric": metric,
        "value": ms(pre["feed_s"]),
        "unit": "ms/step",
        "vs_baseline": 0.0,  # attribution metric: no published reference
        "synthetic_data": bool(dataset.synthetic),
        "extra": {
            "chips": n_chips,
            "global_batch": batch,
            "depth": depth,
            "timed_steps": n_timed,
            "sync_stall_ms_per_step": ms(sync["feed_s"]),
            "prefetched_stall_ms_per_step": ms(pre["feed_s"]),
            "stall_reduction_ms_per_step": ms(sync["feed_s"] - pre["feed_s"]),
            "sync_steps_per_sec": round(n_timed / sync["wall_s"], 2),
            "prefetched_steps_per_sec": round(n_timed / pre["wall_s"], 2),
            "mean_ring_occupancy": pre_stats["mean_occupancy"],
            "h2d_mbytes_per_step": round(
                pre_stats["h2d_bytes"] / max(1, pre_stats["batches"]) / 2**20,
                3),
            # same init + same stream => bit-identical trajectories; a
            # mismatch here means the prefetcher reordered or dropped
            "loss_sync": round(sync["loss"], 6),
            "loss_prefetched": round(pre["loss"], 6),
            "trajectory_identical": sync["loss"] == pre["loss"],
            **_anchor_fields(metric, ms(pre["feed_s"])),
        },
    })
    return 0


def bench_faults(n_steps: int = 60, *, preempt_at: int = 40,
                 ckpt_every: int = 10, batch: int = 256,
                 async_save: bool = False) -> int:
    """Resilience mode (`--faults`): run the SAME short training job twice
    — once clean, once under an injected fault plan (preemption at
    `preempt_at` plus a corrupted latest checkpoint, so the restore must
    quarantine it and fall back an extra `ckpt_every` steps) — and report
    `recovery_latency_ms`: wall time from the failure to the first
    post-failure step that advanced the training frontier (restore +
    replay; faults/goodput.py). `goodput_fraction` and the full bucket
    breakdown ride along in extra.

    With `async_save=True` (``--async-save``) the fault run checkpoints
    through the write-behind `AsyncSnapshotter` (checkpoint/snapshot.py)
    instead of blocking saves — the quarantine ladder, replay, and the
    bit-identical assert below must all hold unchanged through the async
    path, and the `save_s` bucket shows what left the critical path.

    The recovered run's loss trajectory is ASSERTED bit-identical to the
    clean run's, step for step (the loop re-seeks the input stream on
    restore — replay, not skip): a resilience mechanism that perturbs the
    math would be worse than the fault it hides.

    The FAULT run is additionally instrumented with the FULL observability
    stack — run journal (obs/events.py), AnomalyHook (obs/anomaly.py),
    and a live fleet-of-one: a /metrics exporter scraped by a FleetScraper
    (obs/fleet.py) polling concurrently with training — while the clean
    run stays obs-disabled. The trajectory assert above therefore doubles
    as proof that observability is free: the fully-instrumented trajectory
    is bit-identical to an uninstrumented one. The journal is
    cross-checked against the loop's own accounting (restore events ==
    goodput recoveries) and the step-time distribution (obs/hist.py, the
    /metrics histogram layer) plus the fleet-scrape stats ride along in
    extra."""
    import tempfile

    import jax
    import numpy as np

    from dist_mnist_tpu.obs import events as events_mod

    from dist_mnist_tpu import hooks as hooks_lib, optim
    from dist_mnist_tpu.checkpoint import CheckpointManager
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import ShardedBatcher, load_dataset
    from dist_mnist_tpu.faults import Fault, FaultPlan
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import TrainLoop, create_train_state
    from dist_mnist_tpu.train.step import make_train_step

    metric = "recovery_latency_ms"
    mesh = make_mesh(MeshSpec(data=-1))
    n_chips = mesh.devices.size
    dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)

    class _Trajectory:
        """Per-step loss recorder; device scalars held async, fetched once
        at end (keeps the loop's dispatch pipeline intact)."""

        def __init__(self):
            self.loss = {}

        def begin(self, loop):
            pass

        def before_step(self, step):
            pass

        def after_step(self, step, state, outputs):
            self.loss[step] = outputs["loss"]

        def end(self, state):
            self.loss = {k: np.asarray(jax.device_get(v))
                         for k, v in self.loss.items()}

    with activate(mesh):
        model = get_model("mlp")
        optimizer = optim.adam(1e-3)
        state0 = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        state0 = shard_train_state(state0, mesh)
        # donate=False so both runs consume the same initial buffers
        step = make_train_step(model, optimizer, mesh, donate=False)

        def run(plan=None, ckpt_dir=None, instrumented=False):
            traj = _Trajectory()
            hooks = [hooks_lib.StopAtStepHook(last_step=n_steps), traj]
            anomaly = None
            if instrumented:
                from dist_mnist_tpu.obs.anomaly import AnomalyHook

                anomaly = AnomalyHook(every_steps=10)
                hooks.append(anomaly)
            manager = None
            if ckpt_dir:
                manager = CheckpointManager(ckpt_dir, async_save=False,
                                            max_restore_fallbacks=2)
                if plan is not None:
                    manager = plan.wrap_checkpoint_manager(manager)
                if async_save:
                    # write-behind wrapper OUTSIDE the fault wrapper: the
                    # injected corruption still hits the durable store,
                    # the snapshotter just takes the write off the loop
                    from dist_mnist_tpu.checkpoint import AsyncSnapshotter

                    manager = AsyncSnapshotter(manager)
                hooks.append(
                    hooks_lib.CheckpointHook(manager, every_steps=ckpt_every))
            batches = ShardedBatcher(dataset, batch, mesh, seed=0)
            if plan is not None:
                hooks.append(plan.hook())
                batches = plan.wrap_batches(batches)
            loop = TrainLoop(step, state0, batches, hooks,
                             checkpoint_manager=manager, max_recoveries=3)
            exporter = scraper = obs_stats = None
            if instrumented:
                # fleet-of-one scraping the live run: exporter serves the
                # loop's step-time histogram, the scraper polls it
                # concurrently with training — exactly the supervisor-side
                # fleet path, pointed at one host
                from dist_mnist_tpu.obs import MetricRegistry, MetricsExporter
                from dist_mnist_tpu.obs.fleet import FleetScraper

                registry = MetricRegistry()
                registry.attach_histogram("train/step_time_ms",
                                          loop.step_time_hist)
                exporter = MetricsExporter(
                    registry, port=0,
                    info={"host_id": "0", "generation": "0",
                          "role": "train"},
                ).start()
                scraper = FleetScraper(interval_s=0.05)
                scraper.set_targets({0: exporter.url("")})
                scraper.start()
            try:
                loop.run()
            finally:
                if scraper is not None:
                    scraper.scrape_once()  # final deterministic pass
                    snap = scraper.snapshot()
                    obs_stats = {
                        "scrapes": snap["scrapes"],
                        "scrape_errors": snap["scrape_errors"],
                        "host_reachable": snap["hosts"][0]["reachable"],
                        "anomalies": len(anomaly.anomalies),
                    }
                    scraper.close()
                if exporter is not None:
                    exporter.close()
            if manager:
                manager.close()
            return traj.loss, loop, obs_stats

        clean_loss, _, _ = run()  # obs-disabled: no journal installed
        plan = FaultPlan([
            Fault.preempt(preempt_at),
            # target the checkpoint the restore will want (the save at the
            # failure step): the ladder must quarantine it and fall back
            Fault.corrupt_checkpoint(preempt_at),
        ])
        with tempfile.TemporaryDirectory(prefix="bench_faults_") as ckpt_dir:
            journal_path = os.path.join(ckpt_dir, "journal.jsonl")
            prev = events_mod.set_journal(events_mod.RunJournal(journal_path))
            try:
                fault_loss, fault_loop, obs_stats = run(
                    plan=plan, ckpt_dir=ckpt_dir, instrumented=True)
            finally:
                j = events_mod.set_journal(prev)
                if j is not None:
                    j.close()
            journal = events_mod.read_journal(journal_path)
        goodput = fault_loop.goodput

    identical = (set(clean_loss) == set(fault_loss) and all(
        clean_loss[s].tobytes() == fault_loss[s].tobytes()
        for s in clean_loss))
    assert identical, (
        "recovered loss trajectory diverged from the fault-free run "
        "(the fault run carried the full obs stack — journal, AnomalyHook, "
        "live fleet scraper: observability must not perturb the math)")
    assert all(f.fired for f in plan.faults), (
        f"planned faults did not all fire: {plan.to_json()}")
    assert obs_stats is not None and obs_stats["host_reachable"] and (
        obs_stats["scrapes"] >= 1), (
        f"fleet-of-one scraper never reached the live exporter: {obs_stats}")
    snap = goodput.snapshot()
    # journal cross-check: the lifecycle record must agree with the loop's
    # own goodput accounting, restart for restart
    journal_restores = sum(1 for r in journal if r.get("event") == "restore")
    journal_events = [r.get("event") for r in journal]
    assert journal_restores == snap["recoveries"], (
        f"journal restore events ({journal_restores}) != goodput "
        f"recoveries ({snap['recoveries']}); journal saw: {journal_events}")
    step_pcts = fault_loop.step_time_hist.percentiles()
    emit({
        "metric": metric,
        "value": round(snap["recovery_latency_ms"], 2),
        "unit": "ms",
        "vs_baseline": 0.0,  # resilience metric: no published reference
        "synthetic_data": bool(dataset.synthetic),
        "extra": {
            "chips": n_chips,
            "global_batch": batch,
            "steps": n_steps,
            "preempt_at_step": preempt_at,
            "ckpt_every": ckpt_every,
            "goodput_fraction": round(snap["goodput_fraction"], 4),
            "recoveries": snap["recoveries"],
            "replayed_steps": snap["replayed_steps"],
            "productive_s": round(snap["productive_s"], 3),
            "restore_s": round(snap["restore_s"], 3),
            "replay_s": round(snap["replay_s"], 3),
            "stall_s": round(snap["stall_s"], 3),
            "save_s": round(snap["save_s"], 3),
            "async_save": async_save,
            "total_wall_s": round(snap["total_wall_s"], 3),
            "trajectory_identical": identical,
            "faults_fired": [f.kind for f in plan.fired()],
            # fault-run step-time distribution (obs/hist.py — the same
            # histogram /metrics exposes live)
            "step_time_ms": {k: round(v, 3) for k, v in step_pcts.items()},
            "journal_events": journal_events,
            "journal_restores": journal_restores,
            # fleet-of-one scrape stats (obs/fleet.py polled the live run)
            "fleet": obs_stats,
            **_anchor_fields(metric, snap["recovery_latency_ms"]),
        },
    })
    return 0


def bench_ckpt(n_steps: int = 60, *, ckpt_every: int = 10, batch: int = 256,
               elastic_steps: int = 60, kill_step: int = 35,
               elastic_batch: int = 64, procs: int = 2,
               devices_per_process: int = 4) -> int:
    """Checkpoint-cost mode (`--ckpt`), two legs:

    LEG 1 — save-stall attribution, in process: the SAME short training
    job twice with cadence checkpointing — once saving SYNCHRONOUSLY
    (CheckpointManager with the write on the loop thread), once through
    the write-behind `AsyncSnapshotter` (checkpoint/snapshot.py: the loop
    pays a device-side fork + queue handoff; a background writer owns
    serialization, commit marker, durability). Headline
    `save_stall_ms_per_step` is the ASYNC run's per-step save cost from
    the goodput `save_s` bucket (CheckpointHook times `manager.save`
    into it; train/loop.py keeps it out of productive time) — ASSERTED
    strictly below the sync run's, with bit-identical loss trajectories
    (the device fork must not perturb the math) and every async save's
    `checkpoint_commit` journal event paired with its `snapshot_fork`
    (the dispatch→durable span scripts/fleet_trace.py renders).

    LEG 2 — peer-replicated elastic restore: PR 8's seeded
    permanent-host-loss plan (`kill_host` at `kill_step`) under the
    shrink-to-survive supervisor, twice — once checkpointing through
    ``--async_snapshot --peer_dir`` (ring redundancy, checkpoint/peer.py),
    once through the plain store. Both must shrink and finish all steps;
    the peer side must restore from the RING (a `peer_restore` journal
    event, and no store restore at all) with restore latency AND
    whole-run recovery/goodput ASSERTED no worse than the store run's —
    the disk ladder PR 8's recovery paid, re-measured side-by-side here
    because absolute goodput tracks the tree's startup cost (PR 8's
    committed 0.322 is reported as `vs_pr8_committed`, not gated)."""
    import tempfile

    import jax
    import numpy as np

    from dist_mnist_tpu.obs import events as events_mod

    from dist_mnist_tpu import hooks as hooks_lib, optim
    from dist_mnist_tpu.checkpoint import AsyncSnapshotter, CheckpointManager
    from dist_mnist_tpu.cli.launch import launch
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import ShardedBatcher, load_dataset
    from dist_mnist_tpu.faults import Fault, FaultPlan
    from dist_mnist_tpu.faults.goodput import elastic_summary
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import TrainLoop, create_train_state
    from dist_mnist_tpu.train.step import make_train_step

    metric = "save_stall_ms_per_step"
    # PR 8's committed elastic goodput on this plan — reporting reference
    # only; the HARD gate is the same-run store leg (see the asserts),
    # because absolute goodput moves with the tree's startup cost while
    # the side-by-side comparison is the actual claim
    pr8_committed_goodput = 0.322
    mesh = make_mesh(MeshSpec(data=-1))
    n_chips = mesh.devices.size
    dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)

    class _Traj:
        """Per-step loss recorder; device scalars fetched once at end."""

        def __init__(self):
            self.loss = {}

        def begin(self, loop):
            pass

        def before_step(self, step):
            pass

        def after_step(self, step, state, outputs):
            self.loss[step] = outputs["loss"]

        def end(self, state):
            self.loss = {k: np.asarray(jax.device_get(v))
                         for k, v in self.loss.items()}

    def _ev(records, name):
        return [r for r in records if r.get("event") == name]

    with activate(mesh):
        model = get_model("mlp")
        optimizer = optim.adam(1e-3)
        state0 = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        state0 = shard_train_state(state0, mesh)
        # donate=False so both runs consume the same initial buffers
        step = make_train_step(model, optimizer, mesh, donate=False)

        def run(mode: str) -> dict:
            with tempfile.TemporaryDirectory(
                    prefix=f"bench_ckpt_{mode}_") as ckpt_dir:
                manager = CheckpointManager(ckpt_dir, async_save=False)
                if mode == "async":
                    manager = AsyncSnapshotter(manager)
                traj = _Traj()
                hooks = [
                    hooks_lib.StopAtStepHook(last_step=n_steps), traj,
                    hooks_lib.CheckpointHook(manager, every_steps=ckpt_every),
                ]
                loop = TrainLoop(step, state0,
                                 ShardedBatcher(dataset, batch, mesh, seed=0),
                                 hooks, checkpoint_manager=manager)
                journal_path = os.path.join(ckpt_dir, "journal.jsonl")
                prev = events_mod.set_journal(
                    events_mod.RunJournal(journal_path))
                try:
                    loop.run()  # end() drains: every save durable after this
                finally:
                    j = events_mod.set_journal(prev)
                    if j is not None:
                        j.close()
                writer = None
                if mode == "async":
                    writer = {
                        "dropped": manager.dropped,
                        "write_behind_stall_s": round(
                            manager.consume_save_stall_s(), 4),
                    }
                journal = events_mod.read_journal(journal_path)
                manager.close()
            return {"loss": traj.loss, "snap": loop.goodput.snapshot(),
                    "journal": journal, "writer": writer}

        sync = run("sync")
        asyn = run("async")

    identical = (set(sync["loss"]) == set(asyn["loss"]) and all(
        sync["loss"][s].tobytes() == asyn["loss"][s].tobytes()
        for s in sync["loss"]))
    assert identical, (
        "async-snapshot trajectory diverged from the synchronous-save run "
        "— the device-side fork must not perturb the math")
    forks = _ev(asyn["journal"], "snapshot_fork")
    commits = _ev(asyn["journal"], "checkpoint_commit")
    assert forks, "async run forked no snapshots"
    assert len(commits) == len(forks), (
        f"{len(forks)} snapshot forks but {len(commits)} checkpoint_commit "
        f"events — a dispatched save never became durable")
    assert all(isinstance(c.get("dur_ms"), (int, float)) and c["dur_ms"] >= 0
               for c in commits), commits
    sync_save_s = sync["snap"]["save_s"]
    async_save_s = asyn["snap"]["save_s"]
    assert async_save_s < sync_save_s, (
        f"async save stall {async_save_s:.4f}s/run is not below the "
        f"synchronous baseline {sync_save_s:.4f}s/run")
    sync_ms = round(sync_save_s * 1e3 / n_steps, 3)
    async_ms = round(async_save_s * 1e3 / n_steps, 3)

    def _mean_ms(events_):
        return round(sum(e["dur_ms"] for e in events_) / len(events_), 3) \
            if events_ else 0.0

    # -- leg 2: elastic peer-vs-store restore under the same kill plan ------
    plan = FaultPlan([Fault.kill_host(1, step=kill_step)])
    with tempfile.TemporaryDirectory(prefix="bench_ckpt_elastic_") as root:
        data_dir = os.path.join(root, "data")
        # materialize the dataset once so the children don't race the
        # synthetic-twin cache write
        dl = subprocess.run(
            [sys.executable, "-m", "dist_mnist_tpu.cli.train",
             "--download_only", f"--data_dir={data_dir}",
             "--config=mlp_mnist", "--platform=cpu"],
            capture_output=True, text=True, timeout=300,
        )
        if dl.returncode != 0:
            raise RuntimeError(
                f"dataset download child rc={dl.returncode}: "
                f"{dl.stderr.strip()[-400:]}")

        def supervised(tag: str, *, peer: bool) -> dict:
            journal = os.path.join(root, f"journal_{tag}.jsonl")
            args = [
                "--config=mlp_mnist", f"--data_dir={data_dir}",
                f"--checkpoint_dir={os.path.join(root, 'ckpt_' + tag)}",
                f"--train_steps={elastic_steps}",
                f"--batch_size={elastic_batch}",
                "--eval_every=0", "--log_every=10",
                f"--checkpoint_every_steps={ckpt_every}",
                f"--fault_plan={plan.to_json()}",
            ]
            if peer:
                args += ["--async_snapshot",
                         f"--peer_dir={os.path.join(root, 'peer_' + tag)}"]
            rc = launch(
                procs, args, platform="cpu",
                devices_per_process=devices_per_process,
                max_restarts=procs - 1, restart_backoff_s=1.0,
                journal=journal, elastic=True, min_processes=1,
                host_kill=plan.host_kill_spec(),
            )
            assert rc == 0, f"{tag} supervised run failed rc={rc}"
            records = events_mod.read_journal(journal)
            summary = elastic_summary(records)
            summary["records"] = records
            return summary

        pr = supervised("peer", peer=True)
        st = supervised("store", peer=False)

    for tag, s in (("peer", pr), ("store", st)):
        assert [r for r in s["resizes"] if r["kind"] == "shrink"], (
            f"{tag} run never shrank: {s['resizes']}")
        assert s["final_step"] == elastic_steps, (tag, s["final_step"])
    peer_restores = _ev(pr["records"], "peer_restore")
    assert peer_restores, (
        "peer run restored without a peer_restore event — the ring never "
        "engaged")
    assert not _ev(pr["records"], "checkpoint_restore"), (
        "peer run fell back to the store ladder")
    store_restores = _ev(st["records"], "checkpoint_restore")
    assert store_restores, "store run journal shows no checkpoint_restore"
    # Both sides must resume at the LAST cadence save before the kill —
    # one cadence interval of replay, never more. This is the
    # deterministic gate for commit-marker regressions: a marker that
    # doesn't land as soon as the async write is durable quarantines that
    # step on restart, and the restore silently rolls back a further
    # whole interval (exactly the bug the per-step flush_commits poll
    # fixed; goodput bands alone sit inside startup noise and miss it).
    expected_restore = (kill_step // ckpt_every) * ckpt_every
    for tag, ev in (("peer", peer_restores[-1]), ("store", store_restores[-1])):
        assert ev["step"] == expected_restore, (
            f"{tag} run restored step {ev['step']}, expected "
            f"{expected_restore} (a durable cadence save was not "
            f"restore-eligible)")
    peer_restore_ms = peer_restores[-1]["dur_ms"]
    store_restore_ms = store_restores[-1]["dur_ms"]
    assert peer_restore_ms < store_restore_ms, (
        f"peer restore ({peer_restore_ms:.1f} ms) is not below the store "
        f"restore it replaces ({store_restore_ms:.1f} ms)")
    # Whole-run recovery/goodput are compared against the PR 8 disk
    # baseline measured HERE under identical conditions: the store leg
    # runs PR 8's exact restore path on the same seeded plan in the same
    # process environment. (PR 8's committed absolutes — 0.322 goodput,
    # 2.39 s recovery — are not comparable across trees: its own
    # `--faults --elastic` leg re-measures below them on the current tree
    # because startup got heavier since; reported as vs_pr8_committed.)
    # Both whole-run numbers are dominated by process respawn + jax init
    # (~2.5-3.5 s, identical in both legs, ±0.5 s run-to-run) and gen-0
    # startup (±1.5 s), so the restore path's causal wins are gated on
    # the deterministic signals above (ring engaged, restored step,
    # restore-op latency); the bands below are coarse rails that catch a
    # peer path that is catastrophically slower — e.g. an assembly that
    # re-reads the store, or replay past the cadence interval — without
    # flaking on single-sample noise inversions.
    assert pr["recovery_latency_s"] <= st["recovery_latency_s"] + 1.5, (
        f"peer recovery ({pr['recovery_latency_s']:.3f} s) is well above "
        f"the store-restore recovery ({st['recovery_latency_s']:.3f} s)")
    assert pr["goodput_fraction"] >= st["goodput_fraction"] - 0.08, (
        f"async+peer elastic goodput {pr['goodput_fraction']:.4f} fell "
        f"well below the same-plan store baseline "
        f"{st['goodput_fraction']:.4f}")

    def _side(s: dict) -> dict:
        return {
            "goodput_fraction": round(s["goodput_fraction"], 4),
            "recovery_latency_s": round(s["recovery_latency_s"], 3),
            "total_wall_s": round(s["total_wall_s"], 3),
            "final_step": s["final_step"],
            "resizes": s["resizes"],
        }

    emit({
        "metric": metric,
        "value": async_ms,
        "unit": "ms/step",
        "vs_baseline": round(sync_ms / async_ms, 3) if async_ms > 0 else 0.0,
        "synthetic_data": bool(dataset.synthetic),
        "extra": {
            "chips": n_chips,
            "global_batch": batch,
            "steps": n_steps,
            "ckpt_every_steps": ckpt_every,
            "sync_save_ms_per_step": sync_ms,
            "async_save_ms_per_step": async_ms,
            "save_removed_ms_per_step": round(sync_ms - async_ms, 3),
            "saves_per_run": len(commits),
            "trajectory_identical": identical,
            # dispatch→durable spans: the async commit covers the whole
            # background write (it back-dates to the fork), the sync one
            # is the blocking write the loop used to eat
            "sync_commit_ms_mean": _mean_ms(
                _ev(sync["journal"], "checkpoint_commit")),
            "async_commit_ms_mean": _mean_ms(commits),
            "write_behind": asyn["writer"],
            "elastic": {
                "processes": procs,
                "devices_per_process": devices_per_process,
                "global_batch": elastic_batch,
                "steps": elastic_steps,
                "kill_step": kill_step,
                "peer_restore_ms": round(peer_restore_ms, 3),
                "store_restore_ms": round(store_restore_ms, 3),
                "restore_speedup": round(
                    store_restore_ms / peer_restore_ms, 3
                ) if peer_restore_ms > 0 else 0.0,
                "peer_restore_sources": peer_restores[-1].get("sources"),
                "peer": _side(pr),
                "store_baseline": _side(st),
                "goodput_vs_store": round(
                    pr["goodput_fraction"] / st["goodput_fraction"], 3
                ) if st["goodput_fraction"] > 0 else 0.0,
                "pr8_committed_goodput": pr8_committed_goodput,
                "vs_pr8_committed": round(
                    pr["goodput_fraction"] / pr8_committed_goodput, 3),
            },
            **_anchor_fields(metric, async_ms),
        },
    })
    return 0


def bench_faults_elastic(n_steps: int = 60, *, kill_step: int = 35,
                         ckpt_every: int = 10, batch: int = 64,
                         procs: int = 2, devices_per_process: int = 4) -> int:
    """Elastic-resilience mode (`--faults --elastic`): run the SAME seeded
    fault plan — a permanent non-chief host loss (`kill_host`, the victim
    SIGKILLs itself at `kill_step`) — under two supervisors and compare
    whole-run goodput:

    - ELASTIC: the supervisor excludes the dead host and re-forms the
      cluster at the surviving world size (shrink, no backoff); training
      continues on the smaller mesh from the latest checkpoint
      (resharding-by-construction restore).
    - RESTART baseline: the PR-4 supervisor restarts the FULL world with
      backoff — which, for a permanently lost host, means paying the
      restart and then losing the host again would loop forever; here the
      kill fires only in generation 0 (faults/inject.py), so the baseline
      models the best case where the host happens to come back instantly.

    Both runs share one journal schema, and `elastic_summary`
    (faults/goodput.py) computes productive/wall and the uniform
    failure→frontier recovery window from each, so `goodput_fraction` is
    directly comparable. The headline is the ELASTIC fraction;
    vs_baseline is elastic/restart (>1 means shrink-to-survive beat
    restart-the-world on the same plan). Asserted: both runs complete all
    steps, the elastic journal shows exactly a shrink resize (no
    full-world restart), the baseline shows a restart (no resize), and
    the elastic fraction is STRICTLY above the baseline's. Post-shrink
    trajectory determinism is pinned separately in tests/test_elastic.py.

    The elastic side additionally runs the FULL fleet-observability path:
    children expose /metrics (--metrics_port base+rank) and emit
    cadence-gated span records (--span_steps), the supervisor runs the
    FleetScraper (supervisor_port), and afterwards
    scripts/fleet_trace.py merges the host-stamped journal into a chrome
    trace — asserted to contain per-host tracks (>= world size) and the
    shrink resize marker, i.e. correlated step tracing survives a mesh
    resize. Trace stats ride along in extra."""
    import tempfile

    from dist_mnist_tpu.cli.launch import launch
    from dist_mnist_tpu.data import load_dataset
    from dist_mnist_tpu.faults import Fault, FaultPlan
    from dist_mnist_tpu.faults.goodput import elastic_summary
    from dist_mnist_tpu.obs import events as events_mod

    metric = "elastic_goodput_fraction"
    plan = FaultPlan([Fault.kill_host(1, step=kill_step)])

    def _free_port_block(n: int) -> int | None:
        """A base port with n consecutive free ports (children bind
        metrics_port base+rank). Best-effort: probed then released, so a
        race is possible — child exporters degrade gracefully (warn and
        run unexposed) if it loses."""
        import socket

        for _ in range(20):
            with socket.socket() as probe:
                probe.bind(("127.0.0.1", 0))
                base = probe.getsockname()[1]
            if base + n >= 65535:
                continue
            held = []
            try:
                for i in range(n):
                    s = socket.socket()
                    s.bind(("127.0.0.1", base + i))
                    held.append(s)
                return base
            except OSError:
                continue
            finally:
                for s in held:
                    s.close()
        return None

    with tempfile.TemporaryDirectory(prefix="bench_elastic_") as root:
        data_dir = os.path.join(root, "data")
        # materialize the dataset once so the children don't race the
        # synthetic-twin cache write
        dl = subprocess.run(
            [sys.executable, "-m", "dist_mnist_tpu.cli.train",
             "--download_only", f"--data_dir={data_dir}",
             "--config=mlp_mnist", "--platform=cpu"],
            capture_output=True, text=True, timeout=300,
        )
        if dl.returncode != 0:
            raise RuntimeError(
                f"dataset download child rc={dl.returncode}: "
                f"{dl.stderr.strip()[-400:]}")

        def supervised(tag: str, *, elastic: bool) -> dict:
            journal = os.path.join(root, f"journal_{tag}.jsonl")
            args = [
                "--config=mlp_mnist", f"--data_dir={data_dir}",
                f"--checkpoint_dir={os.path.join(root, 'ckpt_' + tag)}",
                f"--train_steps={n_steps}", f"--batch_size={batch}",
                "--eval_every=0", "--log_every=10",
                # step-cadence checkpoints: one deterministically lands
                # before the kill, so both runs restore the same frontier
                f"--checkpoint_every_steps={ckpt_every}",
                f"--fault_plan={plan.to_json()}",
            ]
            supervisor_port = None
            if elastic:
                # fleet observability on the elastic side: child /metrics,
                # span records for the trace, supervisor-side FleetScraper
                # on an ephemeral port (launch resolves port 0 itself)
                metrics_base = _free_port_block(procs)
                if metrics_base is not None:
                    args.append(f"--metrics_port={metrics_base}")
                args.append(f"--span_steps={ckpt_every}")
                supervisor_port = 0
            rc = launch(
                procs, args, platform="cpu",
                devices_per_process=devices_per_process,
                max_restarts=procs - 1, restart_backoff_s=1.0,
                journal=journal, elastic=elastic,
                min_processes=1,
                host_kill=plan.host_kill_spec() if elastic else None,
                supervisor_port=supervisor_port,
            )
            assert rc == 0, f"{tag} supervised run failed rc={rc}"
            records = events_mod.read_journal(journal)
            summary = elastic_summary(records)
            summary["journal_events"] = [r.get("event") for r in records]
            summary["journal_path"] = journal
            return summary

        el = supervised("elastic", elastic=True)
        rs = supervised("restart", elastic=False)

        # correlated step tracing must survive the resize: merge the
        # host-stamped elastic journal into one chrome trace and check the
        # per-host tracks + the shrink marker are all there
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "scripts"))
        try:
            from fleet_trace import build_fleet_trace
        finally:
            sys.path.pop(0)
        trace = build_fleet_trace(el["journal_path"])["traceEvents"]
        host_tracks = {ev["pid"] for ev in trace
                       if ev.get("ph") != "M" and ev.get("pid", 0) >= 1}
        span_gens = {ev.get("tid") for ev in trace
                     if ev.get("cat") == "span"}
        assert len(host_tracks) >= procs, (
            f"fleet trace holds {len(host_tracks)} host tracks, "
            f"wanted >= {procs}")
        assert any(ev.get("name") == "generation_resize" for ev in trace), (
            "no resize marker in the merged fleet trace")
        assert len(span_gens) >= 2, (
            f"span records did not straddle the resize: gens {span_gens}")
        trace_stats = {
            "events": len(trace),
            "host_tracks": len(host_tracks),
            "span_generations": sorted(span_gens),
        }

    # the mechanisms must have actually engaged, each on its own side
    assert [r for r in el["resizes"] if r["kind"] == "shrink"
            and r["old_world"] == procs
            and r["new_world"] == procs - 1], el["resizes"]
    assert "supervisor_restart" not in el["journal_events"], (
        "elastic run fell back to a full-world restart")
    assert "supervisor_restart" in rs["journal_events"], (
        "baseline never restarted — the fault did not engage")
    assert not rs["resizes"], rs["resizes"]
    assert el["final_step"] == n_steps, el
    assert rs["final_step"] == n_steps, rs
    el_frac, rs_frac = el["goodput_fraction"], rs["goodput_fraction"]
    assert el_frac > rs_frac, (
        f"elastic goodput {el_frac:.4f} did not beat the restart "
        f"baseline {rs_frac:.4f} on the same fault plan")

    dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)

    def _side(s: dict) -> dict:
        return {
            "goodput_fraction": round(s["goodput_fraction"], 4),
            "recovery_latency_s": round(s["recovery_latency_s"], 3),
            "total_wall_s": round(s["total_wall_s"], 3),
            "productive_s": round(s["productive_s"], 3),
            "generations": s["generations"],
            "recoveries": s["recoveries"],
            "resizes": s["resizes"],
            "final_step": s["final_step"],
        }

    emit({
        "metric": metric,
        "value": round(el_frac, 4),
        "unit": "fraction",
        "vs_baseline": round(el_frac / rs_frac, 3) if rs_frac > 0 else 0.0,
        "synthetic_data": bool(dataset.synthetic),
        "extra": {
            "chips": procs * devices_per_process,
            "processes": procs,
            "devices_per_process": devices_per_process,
            "global_batch": batch,
            "steps": n_steps,
            "kill_step": kill_step,
            "ckpt_every_steps": ckpt_every,
            "elastic": _side(el),
            "restart_baseline": _side(rs),
            "recovery_speedup": round(
                rs["recovery_latency_s"] / el["recovery_latency_s"], 3
            ) if el["recovery_latency_s"] > 0 else 0.0,
            # merged chrome trace of the elastic run (scripts/fleet_trace.py)
            "fleet_trace": trace_stats,
            **_anchor_fields(metric, el_frac),
        },
    })
    return 0


def coldstart_child(cache_dir: str, n_steps: int) -> int:
    """One measured process of the cold/warm pair (`--coldstart-child`):
    build the LeNet-5 training step against the warm-start cache in
    `cache_dir` (compilecache/), run `n_steps` deterministic steps, and
    print one JSON line — time-to-first-step, the StartupClock buckets,
    the ExecutableStore stats, and the loss trajectory as exact float32
    hex so the parent can assert bit-identity across the pair. The conv
    model is chosen deliberately: its XLA-CPU compile is seconds, so the
    cold-vs-warm gap dwarfs any load-time noise."""
    apply_platform_override()
    from pathlib import Path

    from dist_mnist_tpu.compilecache import (
        ExecutableStore,
        StartupClock,
        cache_key,
        enable_persistent_cache,
    )

    clock = StartupClock(t0=_T0)
    clock.note("import", time.monotonic() - _T0)
    with clock.phase("init"):
        import jax
        import numpy as np

        from dist_mnist_tpu import optim
        from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
        from dist_mnist_tpu.data import ShardedBatcher, load_dataset
        from dist_mnist_tpu.models import get_model
        from dist_mnist_tpu.parallel.sharding import shard_train_state
        from dist_mnist_tpu.train import create_train_state
        from dist_mnist_tpu.train.step import make_train_step

        root = Path(cache_dir)
        enable_persistent_cache(root / "xla")
        store = ExecutableStore(root / "exe")
        mesh = make_mesh(MeshSpec(data=-1))
        batch = 16 * mesh.devices.size
        dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)
        model = get_model("lenet5")
        optimizer = optim.adam(1e-3)
        key = cache_key({
            "kind": "coldstart", "model": "lenet5", "batch": batch,
            "mesh": tuple(sorted(mesh.shape.items())), "sharding": "dp",
            "dtype": "float32", "donate": False,
        })
    with activate(mesh):
        with clock.phase("init"):
            state = create_train_state(
                model, optimizer, jax.random.PRNGKey(0),
                dataset.train_images[:1]
            )
            state = shard_train_state(state, mesh)
            # donate=False: cold and warm must consume identical buffers
            step = make_train_step(model, optimizer, mesh, donate=False,
                                   store=store, cache_key=key)
            batches = ShardedBatcher(dataset, batch, mesh, seed=0)
        it = iter(batches)
        losses = []
        state, out = step(state, next(it))
        jax.device_get(out["loss"])  # fence: the step actually finished
        clock.first_step_done()
        # compile-or-load attribution AFTER the freeze: first_step is the
        # residual at snapshot time, so this never double-counts
        clock.note("compile", step.consume_compile_s())
        losses.append(out["loss"])
        for _ in range(n_steps - 1):
            state, out = step(state, next(it))
            losses.append(out["loss"])
        traj = [np.asarray(jax.device_get(l), dtype=np.float32).tobytes().hex()
                for l in losses]
    snap = clock.snapshot()
    print(json.dumps({
        "time_to_first_step_ms": snap["time_to_first_step_ms"],
        "startup": snap,
        "cache": store.stats(),
        "tier": step.cache_stats["tier"],
        "losses": traj,
    }), flush=True)
    return 0


def bench_coldstart(n_steps: int = 20, *, child_timeout_s: int = 600) -> int:
    """Cold-start mode (`--coldstart`): run the SAME short training job in
    two fresh processes sharing one warm-start cache directory — the first
    cold (compiles, saves), the second warm (deserializes the executable
    the first saved). Emits `time_to_first_step_ms` (the WARM number, the
    one a supervisor restart pays) with the cold number and
    `restart_compile_saved_ms` alongside; asserts the warm process hit the
    cache, beat the cold time, and produced a bit-identical trajectory."""
    import shutil
    import tempfile

    metric = "time_to_first_step_ms"
    pair_dir = tempfile.mkdtemp(prefix="bench_coldstart_")

    def run_child(tag: str) -> dict:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             f"--coldstart-child={pair_dir}",
             f"--coldstart-steps={n_steps}"],
            capture_output=True, text=True, timeout=child_timeout_s,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"{tag} coldstart child rc={out.returncode}: "
                f"{out.stderr.strip()[-400:]}")
        for line in reversed(out.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except ValueError:
                continue
        raise RuntimeError(f"{tag} coldstart child printed no JSON line")

    try:
        cold = run_child("cold")
        warm = run_child("warm")
    finally:
        shutil.rmtree(pair_dir, ignore_errors=True)

    assert warm["cache"]["hits"] > 0, (
        f"warm process missed the executable store: {warm['cache']}")
    assert warm["losses"] == cold["losses"], (
        "warm trajectory diverged from cold — the deserialized executable "
        "is not the program that was saved")
    cold_ms = cold["time_to_first_step_ms"]
    warm_ms = warm["time_to_first_step_ms"]
    assert warm_ms < cold_ms, (
        f"warm start ({warm_ms:.0f} ms) not faster than cold "
        f"({cold_ms:.0f} ms)")
    emit({
        "metric": metric,
        "value": round(warm_ms, 1),
        "unit": "ms",
        "vs_baseline": 0.0,  # startup metric: no published reference
        "extra": {
            "cold_ms": round(cold_ms, 1),
            "warm_ms": round(warm_ms, 1),
            # compile wall time the warm process did not pay, as recorded
            # by the cold process when it saved the entry
            "restart_compile_saved_ms": round(
                warm["cache"]["compile_ms_saved"], 1),
            "ttfs_saved_ms": round(cold_ms - warm_ms, 1),
            "steps": n_steps,
            "trajectory_identical": True,
            "warm_tier": warm.get("tier"),
            "cold_startup": {k: round(v, 1)
                             for k, v in cold["startup"].items()},
            "warm_startup": {k: round(v, 1)
                             for k, v in warm["startup"].items()},
            "warm_cache": {k: (round(v, 2) if isinstance(v, float) else v)
                           for k, v in warm["cache"].items()},
            **_anchor_fields(metric, warm_ms),
        },
    })
    return 0


def _mem_stats_dict(ma) -> dict | None:
    """CompiledMemoryStats -> plain dict of the byte fields this jax
    version exposes (field set varies across versions); None when the
    backend reported nothing."""
    if ma is None:
        return None
    out = {}
    for f in ("temp_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "alias_size_in_bytes",
              "peak_memory_in_bytes"):
        v = getattr(ma, f, None)
        if isinstance(v, int) and v >= 0:
            out[f] = v
    return out or None


def bench_memory(name: str | None) -> int:
    """HBM attribution mode (`--memory`): per-device resident-state bytes
    under `dp` vs `fsdp` on this box's mesh, plus the compiled step's
    XLA memory analysis for both. The headline value is the fsdp
    param+opt-state bytes per device; `extra.reduction_x` is the dp/fsdp
    ratio — the ZeRO claim as ONE number (≈ data-axis size when every
    big leaf divides it)."""
    import jax

    from dist_mnist_tpu.cli.train import build_optimizer
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.data import load_dataset
    from dist_mnist_tpu.data.pipeline import shard_batch
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops import losses
    from dist_mnist_tpu.parallel.sharding import (
        DP_RULES,
        FSDP_RULES,
        shard_train_state,
    )
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.state import state_memory_bytes
    from dist_mnist_tpu.train.step import make_train_step
    from dist_mnist_tpu.utils.prng import prng_impl_scope

    cfg = get_config(name or "lenet5_mnist")
    mesh = make_mesh(MeshSpec(data=-1))  # every visible chip on `data`
    n_chips = mesh.devices.size
    dataset = load_dataset(cfg.dataset, "/tmp/mnist-data", seed=cfg.seed)
    model = get_model(cfg.model, **cfg.model_kwargs)
    optimizer = build_optimizer(cfg)
    loss_fn = (losses.clipped_softmax_cross_entropy if cfg.loss == "clipped"
               else losses.softmax_cross_entropy)
    # state bytes don't depend on batch; keep the compile bounded but the
    # batch divisible over the data axis
    batch_size = max(1, min(cfg.batch_size, 512) // n_chips) * n_chips
    per = {}
    with prng_impl_scope(cfg.prng_impl), activate(mesh):
        base = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        batch = shard_batch(
            {"image": dataset.train_images[:batch_size],
             "label": dataset.train_labels[:batch_size]}, mesh)
        for label, rules in (("dp", DP_RULES), ("fsdp", FSDP_RULES)):
            state = shard_train_state(base, mesh, rules)
            step = make_train_step(model, optimizer, mesh, loss_fn=loss_fn,
                                   rules=rules, donate=False,
                                   remat=cfg.remat,
                                   remat_policy=cfg.remat_policy)
            entry = dict(state_memory_bytes(state))
            # lower+compile only — memory_analysis never executes the step
            stats = _mem_stats_dict(step.memory_analysis(state, batch))
            if stats:
                entry["compiled"] = stats
            per[label] = entry
    resident = lambda e: e["param_bytes"] + e["opt_state_bytes"]
    value = resident(per["fsdp"])
    emit({
        "metric": "fsdp_per_device_state_bytes",
        "value": float(value),
        "unit": "bytes/device",
        "vs_baseline": 0.0,  # attribution metric: no published reference
        "synthetic_data": bool(dataset.synthetic),
        "extra": {
            "chips": n_chips,
            "config": cfg.name,
            "dp": per["dp"],
            "fsdp": per["fsdp"],
            "reduction_x": round(resident(per["dp"]) / max(1, value), 2),
            "note": "param_bytes/opt_state_bytes are per-device RESIDENT "
                    "state from shard shapes; 'compiled' blocks are XLA's "
                    "per-device memory analysis of one training step",
        },
    })
    return 0


def bench_overlap(n_timed: int, *, batch: int = 512, bucket_mb: float = 1.0,
                  warmup: int = 3) -> int:
    """Comm-overlap attribution mode (`--overlap`): the SAME fsdp model
    timed twice — once through the barriered serial schedule (every param
    gather strictly before compute, every grad flush strictly after the
    full backward: ALL communication exposed) and once through the
    overlapped bucket schedule (parallel/overlap.py). Reports
    `comm_exposed_ms_per_step` = serial − overlapped step time: the
    communication the overlap schedule removed from the critical path.

    Both schedules are value-level identities over the same init and
    stream, so their loss trajectories are asserted bit-identical — an
    overlap "win" that perturbed the math would be disqualifying. CPU
    timing can be too noisy to resolve the schedule difference (XLA-CPU
    runs collectives inline); the chunk-structure evidence rides along:
    per-variant HLO collective counts and the bucket count, so
    `extra.hlo_chunked` certifies the overlapped program actually emits
    one gather region per bucket even when the timing washes out."""
    import jax

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import ShardedBatcher, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.overlap import OverlapConfig, plan_stats
    from dist_mnist_tpu.parallel.sharding import FSDP_RULES, shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.step import make_train_step

    metric = "comm_exposed_ms_per_step"
    mesh = make_mesh(MeshSpec(data=-1))
    n_chips = mesh.devices.size
    if n_chips < 2:
        # a 1-chip "mesh" has no communication to overlap; a valid zero is
        # the honest report (this box's TPU is single-chip — the CPU lane
        # with --xla_force_host_platform_device_count=8 exercises the real
        # schedules)
        emit({
            "metric": metric,
            "value": 0.0,
            "unit": "ms/step",
            "vs_baseline": 0.0,
            "extra": {"chips": n_chips, "single_chip": True,
                      "note": "no fsdp communication exists on one chip; "
                              "nothing to overlap"},
        })
        return 0
    dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)
    # hidden width divisible by the data axis so the fsdp shape rule
    # shards both mlp matrices; small enough that XLA-CPU compiles fast
    hidden = max(64, 64 * n_chips)
    with activate(mesh):
        model = get_model("mlp", hidden_units=hidden)
        optimizer = optim.adam(1e-3)
        state0 = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        state0 = shard_train_state(state0, mesh, FSDP_RULES)
        stats = plan_stats(state0.params, mesh, FSDP_RULES,
                           OverlapConfig(bucket_mb=bucket_mb))

        def timed_variant(overlap_cfg) -> dict:
            """(ms/step, last loss, HLO collective counts) for one
            schedule; donate=False so both variants consume the same
            initial buffers and an identical batch stream."""
            step = make_train_step(model, optimizer, mesh, rules=FSDP_RULES,
                                   donate=False, overlap=overlap_cfg)
            it = iter(ShardedBatcher(dataset, batch, mesh, seed=0))
            state = state0
            for _ in range(warmup):
                b = next(it)
                state, out = step(state, b)
            jax.device_get(out["loss"])  # fence: warmup off the clock
            t0 = time.monotonic()
            for _ in range(n_timed):
                state, out = step(state, next(it))
            loss = float(jax.device_get(out["loss"]))  # stop-clock
            wall_s = time.monotonic() - t0
            text = step.compiled_text(state, b) or ""
            return {
                "ms": wall_s / n_timed * 1e3,
                "loss": loss,
                "collectives": {
                    "all_gather": text.count("all-gather("),
                    "reduce_scatter": text.count("reduce-scatter("),
                    "all_reduce": text.count("all-reduce("),
                    "collective_permute": text.count("collective-permute("),
                } if text else None,
            }

        serial = timed_variant(OverlapConfig(bucket_mb=bucket_mb,
                                             serial=True))
        over = timed_variant(OverlapConfig(bucket_mb=bucket_mb))

    oc, n_buckets = over["collectives"], stats["buckets"]
    # chunk-structure proof: the overlapped program gathers bucket-by-bucket
    # (>= one gather collective per bucket) and reduces grads collectively
    hlo_chunked = bool(
        oc and oc["all_gather"] + oc["collective_permute"] >= n_buckets
        and oc["all_reduce"] + oc["reduce_scatter"] > 0
    )
    exposed_ms = max(0.0, serial["ms"] - over["ms"])
    emit({
        "metric": metric,
        "value": round(exposed_ms, 3),
        "unit": "ms/step",
        "vs_baseline": 0.0,  # attribution metric: no published reference
        "synthetic_data": bool(dataset.synthetic),
        "extra": {
            "chips": n_chips,
            "global_batch": batch,
            "timed_steps": n_timed,
            "hidden_units": hidden,
            "bucket_mb": bucket_mb,
            "n_buckets": n_buckets,
            "gathered_mbytes_per_step": round(
                stats["gathered_bytes"] / 2**20, 3),
            "serial_ms_per_step": round(serial["ms"], 3),
            "overlapped_ms_per_step": round(over["ms"], 3),
            "serial_collectives": serial["collectives"],
            "overlapped_collectives": oc,
            "hlo_chunked": hlo_chunked,
            # CPU runs collectives inline; when the pair's timing does not
            # resolve the schedule change, hlo_chunked is the evidence
            "timing_resolves_overlap": serial["ms"] > over["ms"],
            # same init + same stream + identity schedules => bitwise equal
            "loss_serial": round(serial["loss"], 6),
            "loss_overlapped": round(over["loss"], 6),
            "trajectory_identical": serial["loss"] == over["loss"],
            **_anchor_fields(metric, exposed_ms),
        },
    })
    return 0


def bench_kernels() -> int:
    """Pallas-kernel attribution mode (`--kernels`): every hand-written
    kernel parity-gated against its pure-XLA reference, with roofline
    attribution — analytic FLOPs + HBM bytes per kernel, achieved rates
    from the timed wall clock, and achieved-vs-peak fractions against the
    chip tables in utils/flops.py (null off-TPU: CPU interpret-mode wall
    time measures the Pallas INTERPRETER, not the kernel — the CPU lane's
    job here is numerics + structure, not speed).

    Headline `kernels_parity_max_rel_err` = worst parity gap across all
    gates (fused int8 matmul vs `q_dot`'s XLA materialize path, masked
    variable-length flash fwd+bwd vs the -1e30 einsum, one-pass
    clip+Adam+wd vs the chained optimizer) — deterministic on the CPU
    mesh (fixed seeds, interpret mode), so PERF_ANCHOR.json can pin it.
    The masked-flash block also reports the kernel's own `visits` counter
    vs bucket blocks: structural evidence short requests skip padded key
    blocks instead of paying full-bucket math."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from dist_mnist_tpu.ops import quant
    from dist_mnist_tpu.ops.pallas.flash_attention import (
        masked_flash_attention,
        masked_flash_attention_probe,
        masked_flash_flops,
        masked_key_blocks,
    )
    from dist_mnist_tpu.ops.pallas.quant_matmul import (
        quant_matmul,
        quant_matmul_cost,
    )
    from dist_mnist_tpu.utils.flops import (
        device_peak_flops,
        device_peak_hbm_bytes,
    )
    from dist_mnist_tpu import optim

    metric = "kernels_parity_max_rel_err"
    on_tpu = jax.default_backend() == "tpu"
    peak_flops = device_peak_flops()
    peak_hbm = device_peak_hbm_bytes()

    def timed_ms(fn, *a) -> float:
        jax.block_until_ready(fn(*a))  # compile + warm
        t0 = time.monotonic()
        iters = 3
        for _ in range(iters):
            r = fn(*a)
        jax.block_until_ready(r)
        return (time.monotonic() - t0) / iters * 1e3

    def rel_err(got, want) -> float:
        got = jnp.asarray(got, jnp.float32)
        want = jnp.asarray(want, jnp.float32)
        denom = float(jnp.max(jnp.abs(want))) + 1e-12
        return float(jnp.max(jnp.abs(got - want))) / denom

    def roofline(ms: float, flops: float, hbm_bytes: float) -> dict:
        """Achieved rates from the timed wall clock; peak fractions only
        when the chip is in the utils/flops tables (never guessed). On
        CPU the wall time times the interpreter — labeled, not hidden."""
        secs = ms / 1e3
        achieved_fs = flops / secs if secs > 0 else None
        achieved_bs = hbm_bytes / secs if secs > 0 else None
        return {
            "wall_ms": round(ms, 3),
            "flops": flops,
            "hbm_bytes": hbm_bytes,
            "achieved_flops_per_s": achieved_fs,
            "achieved_hbm_bytes_per_s": achieved_bs,
            "frac_peak_flops": (achieved_fs / peak_flops
                                if achieved_fs and peak_flops else None),
            "frac_peak_hbm": (achieved_bs / peak_hbm
                              if achieved_bs and peak_hbm else None),
        }

    rng = np.random.default_rng(0)
    errors: dict[str, float] = {}
    kernels: dict[str, dict] = {}

    # --- fused int8 dequant-matmul vs q_dot's XLA materialize path -------
    m, d, h = 256, 192, 768  # serve-representative dense shape
    x = jnp.asarray(rng.standard_normal((m, d)), jnp.float32)
    w_f = jnp.asarray(rng.standard_normal((d, h)), jnp.float32)
    # tensor mode broadcasts one scale to the [1, H] channel layout —
    # built by hand here since quantize() only falls back to it on
    # degenerate (zero-amax) channels
    t_scale = jnp.broadcast_to(
        jnp.max(jnp.abs(w_f)) / 127.0, (1, h)).astype(jnp.float32)
    tensor_q = quant.QuantizedArray(
        jnp.clip(jnp.round(w_f / t_scale), -127, 127).astype(jnp.int8),
        t_scale, "tensor")
    for mode, w_q in (("channel", quant.quantize(w_f)),
                      ("tensor", tensor_q)):
        ref = x @ quant.dequantize(w_q, x.dtype)
        got = quant_matmul(x, w_q.q, w_q.scale)
        errors[f"quant_matmul_{mode}"] = rel_err(got, ref)
    w_q = quant.quantize(w_f)
    # dispatch liveness: force the Pallas mode and prove q_dot routes here
    orig_mode = quant.FUSED_MATMUL
    try:
        quant.FUSED_MATMUL = "pallas"
        via_qdot = quant.q_dot(x, w_q)
    finally:
        quant.FUSED_MATMUL = orig_mode
    dispatch_live = bool(jnp.array_equal(
        via_qdot, quant_matmul(x, w_q.q, w_q.scale)))
    cost = quant_matmul_cost(x.shape, (d, h), x.dtype)
    kernels["quant_matmul"] = {
        "shape": f"[{m},{d}]x[{d},{h}] int8",
        **roofline(timed_ms(lambda: quant_matmul(x, w_q.q, w_q.scale)),
                   cost["flops"], cost["hbm_bytes"]),
        "q_dot_dispatch_live": dispatch_live,
        # the win the kernel exists for: int8 weight bytes stream once,
        # vs materialize reading int8 AND writing+reading a float copy
        "xla_materialize_hbm_bytes": cost["hbm_bytes"] + 2.0 * 4 * d * h,
    }

    # --- masked variable-length flash vs the -1e30 einsum ----------------
    b, s, heads, dh = 4, 256, 4, 64  # a zoo sub-native bucket shape
    block_k = 128
    lengths = jnp.asarray([64, 128, 192, 256], jnp.int32)
    q3 = jnp.asarray(rng.standard_normal((b, s, heads, dh)), jnp.float32)
    k3 = jnp.asarray(rng.standard_normal((b, s, heads, dh)), jnp.float32)
    v3 = jnp.asarray(rng.standard_normal((b, s, heads, dh)), jnp.float32)

    def ref_attn(q, k, v):
        scale = q.shape[-1] ** -0.5
        logits = (jnp.einsum("bqhd,bkhd->bhqk", q, k)
                  .astype(jnp.float32) * scale)
        keymask = jnp.arange(s)[None, :] < lengths[:, None]
        logits = jnp.where(keymask[:, None, None, :], logits,
                           jnp.float32(-1e30))
        w = jax.nn.softmax(logits, -1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v)

    flash = functools.partial(masked_flash_attention, lengths=lengths,
                              block_k=block_k)
    out_k = flash(q3, k3, v3)
    out_r = ref_attn(q3, k3, v3)
    errors["masked_flash_fwd"] = rel_err(out_k, out_r)
    loss_k = lambda *a: jnp.sum(jnp.sin(flash(*a)))
    loss_r = lambda *a: jnp.sum(jnp.sin(ref_attn(*a)))
    gk = jax.grad(loss_k, (0, 1, 2))(q3, k3, v3)
    gr = jax.grad(loss_r, (0, 1, 2))(q3, k3, v3)
    errors["masked_flash_bwd"] = max(
        rel_err(a, bb) for a, bb in zip(gk, gr))
    # structural evidence from INSIDE the kernel: its visit counter must
    # equal ceil(length/block_k) per row — short requests skip blocks
    _, visits = masked_flash_attention_probe(q3, k3, v3, lengths,
                                             block_k=block_k)
    want_blocks = np.asarray(masked_key_blocks(lengths, block_k))
    visits_ok = bool(np.array_equal(
        np.asarray(visits[:, 0, 0], np.int64), want_blocks))
    flops_masked = masked_flash_flops(lengths, s, heads, dh, block_k)
    flops_full = float(2 * 2 * s * dh * heads * s * b)
    itemsize = q3.dtype.itemsize
    active = np.asarray(want_blocks) * block_k
    hbm_masked = float(itemsize * heads * (
        2 * s * dh * b + 2 * dh * active.sum()))  # q+out full, k+v active
    kernels["masked_flash"] = {
        "shape": f"[{b},{s},{heads},{dh}] lengths {lengths.tolist()}",
        **roofline(timed_ms(flash, q3, k3, v3), flops_masked, hbm_masked),
        "visits_per_row": np.asarray(visits[:, 0, 0], np.int64).tolist(),
        "bucket_blocks": s // block_k,
        "visits_match_lengths": visits_ok,
        "flops_vs_full_bucket": flops_masked / flops_full,
    }

    # --- one-pass clip+Adam+wd vs the chained optimizer ------------------
    params = {"w": jnp.asarray(rng.standard_normal((d, h)), jnp.float32),
              "b": jnp.asarray(rng.standard_normal((h,)), jnp.float32)}
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape) * 3, jnp.float32),
        params)
    chained = optim.chain(optim.clip_by_global_norm(0.5),
                          optim.adamw(1e-3, weight_decay=0.01))
    fused = optim.fused_adamw(1e-3, weight_decay=0.01, clip_norm=0.5)
    s_c, s_f = chained.init(params), fused.init(params)
    u_c, s_c = chained.update(grads, s_c, params)
    u_f, s_f = fused.update(grads, s_f, params)
    errors["fused_adam_clip_wd"] = max(
        rel_err(a, bb) for a, bb in
        zip(jax.tree.leaves(u_f), jax.tree.leaves(u_c)))
    # off-path must be BIT-identical to the original fused kernel
    plain_f = optim.fused_adamw(1e-3, weight_decay=0.0, clip_norm=None)
    plain_a = optim.adam(1e-3, fused=True)
    u_pf, _ = plain_f.update(grads, plain_f.init(params), params)
    u_pa, _ = plain_a.update(grads, plain_a.init(params), params)
    bit_identical = all(
        bool(jnp.array_equal(a, bb)) for a, bb in
        zip(jax.tree.leaves(u_pf), jax.tree.leaves(u_pa)))
    n_elems = sum(p.size for p in jax.tree.leaves(params))
    kernels["fused_adam_clip_wd"] = {
        "shape": f"{n_elems} params",
        # 4 reads (g, m, v, p) + 3 writes (delta, m, v), f32
        **roofline(
            timed_ms(lambda: fused.update(grads, s_f, params)),
            12.0 * n_elems, 7.0 * 4 * n_elems),
        "off_path_bit_identical": bit_identical,
        "chained_hbm_bytes": 13.0 * 4 * n_elems,  # 3 passes re-read g/u/p
    }

    worst = max(errors.values())
    gates_ok = (worst < 2e-5 and visits_ok and dispatch_live
                and bit_identical)
    if not gates_ok:
        emit_error(metric, "kernel parity/structure gate failed",
                   parity_rel_err=errors, visits_match_lengths=visits_ok,
                   q_dot_dispatch_live=dispatch_live,
                   off_path_bit_identical=bit_identical)
        return 1
    emit({
        "metric": metric,
        "value": worst,
        "unit": "max_rel_err",
        "vs_baseline": 0.0,  # attribution metric: no published reference
        "extra": {
            "interpret": not on_tpu,
            "device_kind": jax.devices()[0].device_kind,
            "peak_flops_per_s": peak_flops,
            "peak_hbm_bytes_per_s": peak_hbm,
            "parity_rel_err": {k: float(f"{v:.3e}")
                               for k, v in errors.items()},
            "kernels": kernels,
            **_anchor_fields(metric, worst),
        },
    })
    return 0


def bench_tune() -> int:
    """Closed-loop autotune mode (`--tune`): run the seeded two-knob
    successive-halving search (overlap bucket granularity + the serve
    (batch, seq-bucket) grid) through dist_mnist_tpu/tune's deterministic
    objectives, assert each winner STRICTLY beats the stock default on
    the same seeded stream, and persist the winners — evidence embedded —
    to a TunedConfigStore keyed to this exact geometry, so a later
    `--tuned=auto` train/serve run picks them up.

    Headline `tuned_vs_default_ratio` = geometric mean of the per-knob
    winner/default objective ratios (< 1.0 ⇔ the tuner found strictly
    better settings than the hand-picked defaults). The objectives are
    structural cost models fed by the REAL machinery (overlap planner
    bucket stats, zoo SeqGrid padding arithmetic over a seeded varlen
    stream) rather than wall clock, so the number is deterministic on
    the CPU mesh and PERF_ANCHOR.json can pin it — the same reasoning
    as `kernels_parity_max_rel_err`."""
    import math

    import jax

    from dist_mnist_tpu.cluster.mesh import make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.tune.objectives import (
        TuneObjectiveUnavailable,
        overlap_cost_objective,
        serve_grid_objective,
    )
    from dist_mnist_tpu.tune.search import successive_halving
    from dist_mnist_tpu.tune.spec import KNOBS
    from dist_mnist_tpu.tune.store import (
        TunedConfigStore,
        make_entry,
        tuning_key,
    )

    metric = "tuned_vs_default_ratio"
    cfg = get_config("mlp_mnist")
    mesh = make_mesh(cfg.mesh)

    results, skipped, knob_blocks = [], {}, {}
    for name, build in (
        ("overlap_bucket_mb", lambda: overlap_cost_objective(mesh)),
        ("serve_grid", serve_grid_objective),
    ):
        try:
            objective = build()
        except TuneObjectiveUnavailable as e:
            skipped[name] = str(e)  # e.g. single-chip: nothing to gather
            continue
        res = successive_halving(KNOBS[name], objective, seed=0,
                                 base_budget=32)
        # the whole point of the search: a winner that is not strictly
        # better than the default on the SAME seeded stream is a bug in
        # the ladder or the objective, not a result
        if not res.strictly_beats_default:
            raise AssertionError(
                f"tuned {name}={res.winner!r} does not strictly beat "
                f"default {res.spec.default!r} on the same stream "
                f"({res.spec.metric}: {res.winner_score:.6f} vs "
                f"{res.default_score:.6f})")
        results.append(res)
        knob_blocks[name] = {
            "winner": res.winner,
            "default": res.spec.default,
            res.spec.metric: round(res.winner_score, 6),
            f"default_{res.spec.metric}": round(res.default_score, 6),
            "vs_default_ratio": round(res.vs_default_ratio, 6),
            "rounds": res.rounds,
            "trials": len(res.trials),
            "final_budget": res.final_budget,
        }
    if not results:
        raise TuneObjectiveUnavailable(
            f"no knob was searchable on this geometry: {skipped}")

    ratio = math.exp(
        sum(math.log(r.vs_default_ratio) for r in results) / len(results))

    store_dir = os.environ.get("DIST_MNIST_TPU_TUNED_DIR",
                               "/tmp/dist_mnist_tpu_tuned")
    store = TunedConfigStore(store_dir)
    key = tuning_key(cfg, mesh)
    store.save(key, make_entry(cfg, mesh, results))

    emit({
        "metric": metric,
        "value": round(ratio, 6),
        "unit": "tuned/default ratio",  # < 1.0 ⇔ tuned strictly wins
        "vs_baseline": 0.0,  # attribution metric: no published reference
        "extra": {
            "chips": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind,
            "seed": 0,
            "knobs": knob_blocks,
            "skipped": skipped,
            "store": store_dir,
            "key": key,
            **_anchor_fields(metric, ratio),
        },
    })
    return 0


def main() -> int:
    import jax

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import DeviceDataset, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, evaluate, make_eval_step
    from dist_mnist_tpu.train.step import make_scanned_train_fn
    from dist_mnist_tpu.utils.timing import timed_chunks

    n_chips = jax.device_count()
    mesh = make_mesh(MeshSpec(data=-1))
    dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)
    model = get_model("lenet5")
    optimizer = optim.adam(1e-3)
    batch = 200  # reference dist config: 2 workers x batch 100

    t_start = time.monotonic()
    with activate(mesh):
        state = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        state = shard_train_state(state, mesh)
        dd = DeviceDataset(dataset, mesh)
        chunk = 100  # steps per compiled scan: no per-step dispatch at all
        run = make_scanned_train_fn(model, optimizer, mesh, dd, batch, chunk)
        eval_step = make_eval_step(model, mesh)

        # --- accuracy race: train to 99% test acc, wall-clock from start ---
        wall_to_99 = None
        for rounds in range(40):  # 40 x 2 x 100 = up to 8000 steps
            for _ in range(2):
                state, out = run(state)
            res = evaluate(
                eval_step, state, dataset.test_images, dataset.test_labels,
                mesh, batch_size=10_000,  # one dispatch for the whole test set
            )
            if res["accuracy"] >= 0.99:
                wall_to_99 = time.monotonic() - t_start
                break

        # --- steady-state throughput (post-compile, post-warmup; the
        # axon-hardened device_get stop-clock, utils/timing.py) ---
        n_timed = 2000
        dt, state, _ = timed_chunks(run, state, n_timed // chunk)
        mfu_block = _mfu_fields(run, state, dt / n_timed, model=model,
                                sample_shape=dataset.train_images[:1].shape,
                                batch=batch // n_chips)  # per-chip basis

    steps_per_sec_per_chip = n_timed / dt / n_chips
    synthetic = bool(dataset.synthetic)
    # the ≥99%-in-<60s north star (BASELINE.json) is a REAL-MNIST target;
    # the synthetic twin is easier, so a synthetic race win scores 0.0 here
    # and is reported, labeled, under extra.accuracy_race
    vs_baseline = (
        round(60.0 / wall_to_99, 2) if (wall_to_99 and not synthetic) else 0.0
    )
    emit({
        "metric": HEADLINE_METRIC,
        "value": round(steps_per_sec_per_chip, 2),
        "unit": "steps/sec/chip",
        "vs_baseline": vs_baseline,
        "synthetic_data": synthetic,
        "extra": {
            "chips": n_chips,
            "global_batch": batch,
            "examples_per_sec": round(steps_per_sec_per_chip * n_chips * batch),
            **mfu_block,
            **_anchor_fields(HEADLINE_METRIC, steps_per_sec_per_chip),
            "accuracy_race": {
                "target": ">=99% test acc in <60s (north star; REAL MNIST)",
                "provenance": (
                    "synthetic procedural twin — easier than real MNIST; "
                    "NOT a north-star result" if synthetic else "real MNIST"
                ),
                "wall_to_99pct_acc_secs": (
                    round(wall_to_99, 2) if wall_to_99 else None
                ),
                "final_test_acc": round(res["accuracy"], 4),
            },
        },
    })
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None,
                    help="ladder config to time (default: headline LeNet-5 "
                         "accuracy race + throughput)")
    ap.add_argument("--steps", type=int, default=500,
                    help="timed steps in --config mode")
    ap.add_argument("--serve", action="store_true",
                    help="serving-latency mode: p99 request latency through "
                         "the online inference server (serve_p99_latency_ms)")
    ap.add_argument("--fleet", action="store_true",
                    help="with --serve: fleet-robustness mode — two-class "
                         "traffic through a multi-replica router under a "
                         "seeded replica-kill + replica-stall plan and a "
                         "live weight hot-swap; asserts zero "
                         "latency-sensitive failures and reports their p99 "
                         "(fleet_p99_latency_sensitive_ms)")
    ap.add_argument("--fleet-replicas", type=int, default=3,
                    help="fleet size in --serve --fleet mode")
    ap.add_argument("--autoscale", action="store_true",
                    help="with --serve: chip-economics mode — one seeded "
                         "10x flash-crowd trace replayed under static "
                         "max-replica provisioning and under the "
                         "serve/autoscale.py control loop; asserts the "
                         "latency-sensitive p99 holds through the spike, "
                         "warm-start scale-ups (zero compile), and a "
                         "strictly lower autoscaled chip cost "
                         "(chip_seconds_per_1k_ok)")
    ap.add_argument("--quant", action="store_true",
                    help="with --serve: quantized-serving mode — the same "
                         "loadgen stream through a float and an int8 "
                         "weight-only engine side by side; asserts the "
                         "resident-bytes ratio, top-1 agreement, p99 "
                         "parity, and zero hot-path recompiles "
                         "(quant_p99_ms)")
    ap.add_argument("--decode", action="store_true",
                    help="with --serve: autoregressive-decode mode — "
                         "continuous batching vs the static-batch "
                         "baseline on the same compiled executables, "
                         "bit-identical token streams enforced "
                         "(decode_ttft_p99_ms)")
    ap.add_argument("--longctx", action="store_true",
                    help="with --serve: long-context mode — variable-height "
                         "traffic through the model-zoo (batch, seq-bucket) "
                         "grid on a maskable ViT; asserts zero hot-path "
                         "recompiles after prewarm and reports p99 over all "
                         "heights plus per-device resident bytes "
                         "(longctx_p99_ms)")
    ap.add_argument("--kernels", action="store_true", dest="kernels_mode",
                    help="Pallas-kernel attribution mode: parity-gate every "
                         "hand-written kernel against its pure-XLA "
                         "reference (fused int8 matmul vs q_dot, masked "
                         "variable-length flash vs the -1e30 einsum, "
                         "one-pass clip+Adam+wd vs the chained optimizer) "
                         "and report per-kernel roofline attribution — "
                         "analytic FLOPs/HBM bytes, achieved rates, "
                         "achieved-vs-peak fractions on TPU "
                         "(kernels_parity_max_rel_err)")
    ap.add_argument("--tune", action="store_true", dest="tune_mode",
                    help="closed-loop autotune mode: seeded "
                         "successive-halving search over the overlap "
                         "bucket size and the serve (batch, seq-bucket) "
                         "grid via dist_mnist_tpu/tune's deterministic "
                         "objectives; asserts every winner strictly "
                         "beats the stock default on the same stream and "
                         "persists the winners + evidence to a "
                         "TunedConfigStore for --tuned=auto runs "
                         "(tuned_vs_default_ratio)")
    ap.add_argument("--input", action="store_true", dest="input_mode",
                    help="input-stall attribution mode: time sync-feed vs "
                         "device-prefetched feed on the same model/stream "
                         "(input_stall_ms_per_step)")
    ap.add_argument("--prefetch-depth", type=int, default=2,
                    help="prefetch ring depth in --input mode")
    ap.add_argument("--memory", action="store_true", dest="memory_mode",
                    help="HBM attribution mode: per-device resident-state "
                         "bytes dp vs fsdp + compiled-step memory analysis "
                         "(fsdp_per_device_state_bytes); --config picks the "
                         "ladder config (default lenet5_mnist)")
    ap.add_argument("--overlap", action="store_true", dest="overlap_mode",
                    help="comm-overlap attribution mode: time the barriered "
                         "serial fsdp schedule vs the overlapped bucket "
                         "schedule on the same model/stream and report the "
                         "communication removed from the critical path "
                         "(comm_exposed_ms_per_step)")
    ap.add_argument("--bucket-mb", type=float, default=1.0,
                    help="overlap bucket granularity (MiB) in --overlap mode")
    ap.add_argument("--faults", action="store_true", dest="faults_mode",
                    help="resilience mode: inject a preemption + corrupted "
                         "checkpoint into a short training run and report "
                         "recovery latency, goodput fraction, and a "
                         "bit-identical-trajectory check "
                         "(recovery_latency_ms)")
    ap.add_argument("--async-save", action="store_true", dest="async_save",
                    help="with --faults: checkpoint through the "
                         "write-behind AsyncSnapshotter instead of "
                         "blocking saves (same asserts must hold)")
    ap.add_argument("--ckpt", action="store_true", dest="ckpt_mode",
                    help="checkpoint-cost mode: sync vs async-snapshot "
                         "save stall on the same job (bit-identical "
                         "trajectories), plus the elastic kill-plan with "
                         "peer-ring vs store restore "
                         "(save_stall_ms_per_step)")
    ap.add_argument("--elastic", action="store_true", dest="elastic_mode",
                    help="with --faults: elastic-resilience mode — run the "
                         "same seeded permanent-host-loss plan under the "
                         "shrink-to-survive supervisor and the "
                         "restart-the-world baseline and compare whole-run "
                         "goodput (elastic_goodput_fraction)")
    ap.add_argument("--coldstart", action="store_true", dest="coldstart_mode",
                    help="cold-start mode: run the same short training job "
                         "in a cold process then a warm one sharing a "
                         "compile-cache dir; reports warm "
                         "time_to_first_step_ms + restart_compile_saved_ms "
                         "and asserts a bit-identical trajectory")
    ap.add_argument("--coldstart-child", default=None, metavar="CACHE_DIR",
                    help=argparse.SUPPRESS)  # internal: one measured process
    ap.add_argument("--coldstart-steps", type=int, default=20,
                    help="steps per process in --coldstart mode")
    ap.add_argument("--requests", type=int, default=512,
                    help="loadgen request count in --serve mode")
    ap.add_argument("--concurrency", type=int, default=64,
                    help="loadgen in-flight window in --serve mode")
    ap.add_argument("--deadline", type=int, default=1500,
                    help="hard wall-clock bound; a structured JSON error "
                         "line is printed if exceeded")
    args = ap.parse_args()
    if args.coldstart_child:
        # measured child of --coldstart: no probe (the parent probed), no
        # deadline (the parent bounds it), raw traceback on failure (the
        # parent wraps it into ITS structured line)
        sys.exit(coldstart_child(args.coldstart_child, args.coldstart_steps))
    metric = ("chip_seconds_per_1k_ok"
              if args.serve and args.autoscale
              else "fleet_p99_latency_sensitive_ms"
              if args.serve and args.fleet
              else "decode_ttft_p99_ms" if args.serve and args.decode
              else "longctx_p99_ms" if args.serve and args.longctx
              else "quant_p99_ms" if args.serve and args.quant
              else "serve_p99_latency_ms" if args.serve
              else "kernels_parity_max_rel_err" if args.kernels_mode
              else "tuned_vs_default_ratio" if args.tune_mode
              else "input_stall_ms_per_step" if args.input_mode
              else "fsdp_per_device_state_bytes" if args.memory_mode
              else "comm_exposed_ms_per_step" if args.overlap_mode
              else "save_stall_ms_per_step" if args.ckpt_mode
              else "elastic_goodput_fraction"
              if args.faults_mode and args.elastic_mode
              else "recovery_latency_ms" if args.faults_mode
              else "time_to_first_step_ms" if args.coldstart_mode
              else f"{args.config}_steps_per_sec_per_chip" if args.config
              else HEADLINE_METRIC)

    install_deadline(metric, args.deadline)
    if not probe_backend_with_fallback(metric):
        sys.exit(0)  # structured error line already printed
    apply_platform_override()  # after the probe: see its docstring

    # persistent XLA compile cache for BOTH modes: repeat invocations skip
    # the ~45 s of scan/init/eval compiles entirely (cold-compile time still
    # counts against wall_to_99 on the first run — reported honestly)
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    try:
        sys.exit(bench_serve_autoscale()
                 if args.serve and args.autoscale
                 else bench_serve_fleet(args.requests, args.concurrency,
                                        replicas=args.fleet_replicas)
                 if args.serve and args.fleet
                 else bench_serve_decode(args.requests, args.concurrency)
                 if args.serve and args.decode
                 else bench_serve_longctx(args.requests, args.concurrency)
                 if args.serve and args.longctx
                 else bench_serve_quant(args.requests, args.concurrency)
                 if args.serve and args.quant
                 else bench_serve(args.requests, args.concurrency)
                 if args.serve
                 else bench_kernels() if args.kernels_mode
                 else bench_tune() if args.tune_mode
                 else bench_input(args.steps, depth=args.prefetch_depth)
                 if args.input_mode
                 else bench_memory(args.config) if args.memory_mode
                 else bench_overlap(min(args.steps, 60),
                                    bucket_mb=args.bucket_mb)
                 if args.overlap_mode
                 else bench_ckpt() if args.ckpt_mode
                 else bench_faults_elastic()
                 if args.faults_mode and args.elastic_mode
                 else bench_faults(async_save=args.async_save)
                 if args.faults_mode
                 else bench_coldstart(args.coldstart_steps)
                 if args.coldstart_mode
                 else bench_config(args.config, args.steps) if args.config
                 else main())
    except Exception as e:  # noqa: BLE001 — the contract is ONE JSON line, always
        emit_error(metric, f"{type(e).__name__}: {e}")
        sys.exit(0)
