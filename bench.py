#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line for the harness.

Headline metric (BASELINE.md): LeNet-5 (the "MNIST CNN") steps/sec/chip at
the reference's original dist-config geometry (global batch 200 = 2 workers
x 100 — SURVEY.md §0.1). The run uses the fused-input step
(train/step.make_fused_train_step): dataset resident in HBM, batch sampling
compiled into the step, zero host work per step — the polar opposite of the
reference's per-step feed_dict -> gRPC -> PS round-trip (§3.3).

`vs_baseline`: the reference publishes no steps/sec numbers
(BASELINE.json `published: {}`), so the only authoritative target is the
north star "≥99% MNIST test accuracy in <60 s wall-clock". We time the
accuracy race (training start -> first eval ≥99%, compile included) and
report vs_baseline = 60s / wall_to_99 (>1 = beating the target).

Ladder mode (`python bench.py --config resnet20_cifar [--steps N]`) times
any BASELINE.md config's steady-state steps/sec/chip with the same fused
machinery — the default invocation (what the driver runs) is unchanged.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax


def bench_config(name: str, n_timed: int):
    """Steady-state throughput for one ladder config (no accuracy race —
    only the headline MNIST config has a published accuracy target).

    Times the config's REAL training step: optimizer pipeline (schedule,
    clipping, weight decay, accumulation) via cli.train.build_optimizer and
    the config's loss — not a simplified stand-in."""
    from dist_mnist_tpu.cli.train import build_optimizer
    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.data import DeviceDataset, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops import losses
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.step import make_scanned_train_fn

    cfg = get_config(name)
    n_chips = jax.device_count()
    mesh = make_mesh(MeshSpec(data=-1))  # whatever this box has
    dataset = load_dataset(cfg.dataset, "/tmp/mnist-data", seed=cfg.seed)
    model = get_model(cfg.model, **cfg.model_kwargs)
    optimizer = build_optimizer(cfg)
    loss_fn = (losses.clipped_softmax_cross_entropy if cfg.loss == "clipped"
               else losses.softmax_cross_entropy)
    chunk = 100
    with mesh:
        state = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        state = shard_train_state(state, mesh)
        dd = DeviceDataset(dataset, mesh)
        run = make_scanned_train_fn(model, optimizer, mesh, dd,
                                    cfg.batch_size, chunk, loss_fn=loss_fn,
                                    remat=cfg.remat, augment=cfg.augment)
        state, out = run(state)  # compile + warmup
        jax.block_until_ready(out["loss"])
        t0 = time.monotonic()
        for _ in range(max(1, n_timed // chunk)):
            state, out = run(state)
        jax.block_until_ready(out["loss"])
        dt = time.monotonic() - t0
    rate = max(1, n_timed // chunk) * chunk / dt / n_chips
    print(json.dumps({
        "metric": f"{name}_steps_per_sec_per_chip",
        "value": round(rate, 2),
        "unit": "steps/sec/chip",
        "vs_baseline": 0.0,  # no published reference numbers (BASELINE.md)
        "extra": {
            "chips": n_chips,
            "global_batch": cfg.batch_size,
            "examples_per_sec": round(rate * n_chips * cfg.batch_size),
            "synthetic_data": dataset.synthetic,
        },
    }))
    return 0


def main():
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.data import DeviceDataset, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, evaluate, make_eval_step
    from dist_mnist_tpu.train.step import make_scanned_train_fn

    n_chips = jax.device_count()
    mesh = make_mesh(MeshSpec(data=-1))
    dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)
    model = get_model("lenet5")
    optimizer = optim.adam(1e-3)
    batch = 200  # reference dist config: 2 workers x batch 100

    t_start = time.monotonic()
    with mesh:
        state = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), dataset.train_images[:1]
        )
        state = shard_train_state(state, mesh)
        dd = DeviceDataset(dataset, mesh)
        chunk = 100  # steps per compiled scan: no per-step dispatch at all
        run = make_scanned_train_fn(model, optimizer, mesh, dd, batch, chunk)
        eval_step = make_eval_step(model, mesh)

        # --- accuracy race: train to 99% test acc, wall-clock from start ---
        wall_to_99 = None
        for rounds in range(40):  # 40 x 2 x 100 = up to 8000 steps
            for _ in range(2):
                state, out = run(state)
            res = evaluate(
                eval_step, state, dataset.test_images, dataset.test_labels,
                mesh, batch_size=10_000,  # one dispatch for the whole test set
            )
            if res["accuracy"] >= 0.99:
                wall_to_99 = time.monotonic() - t_start
                break

        # --- steady-state throughput (post-compile, post-warmup) ---
        state, out = run(state)
        jax.block_until_ready(out["loss"])
        n_timed = 2000
        t0 = time.monotonic()
        for _ in range(n_timed // chunk):
            state, out = run(state)
        jax.block_until_ready(out["loss"])
        dt = time.monotonic() - t0

    steps_per_sec_per_chip = n_timed / dt / n_chips
    result = {
        "metric": "lenet5_mnist_steps_per_sec_per_chip",
        "value": round(steps_per_sec_per_chip, 2),
        "unit": "steps/sec/chip",
        # >1.0 = beat the ≥99%-in-<60s north star; reference publishes no
        # throughput numbers (BASELINE.json published={})
        "vs_baseline": round(60.0 / wall_to_99, 2) if wall_to_99 else 0.0,
        "extra": {
            "chips": n_chips,
            "global_batch": batch,
            "examples_per_sec": round(steps_per_sec_per_chip * n_chips * batch),
            "wall_to_99pct_acc_secs": round(wall_to_99, 2) if wall_to_99 else None,
            "final_test_acc": round(res["accuracy"], 4),
            "synthetic_data": dataset.synthetic,
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    # persistent XLA compile cache for BOTH modes: repeat invocations skip
    # the ~45 s of scan/init/eval compiles entirely (cold-compile time still
    # counts against wall_to_99 on the first run — reported honestly)
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None,
                    help="ladder config to time (default: headline LeNet-5 "
                         "accuracy race + throughput)")
    ap.add_argument("--steps", type=int, default=500,
                    help="timed steps in --config mode")
    args = ap.parse_args()
    if args.config:
        sys.exit(bench_config(args.config, args.steps))
    sys.exit(main())
