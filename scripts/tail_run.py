#!/usr/bin/env python
"""Render a run journal (obs/events.py JSONL) as a human-readable timeline.

The journal is the machine-readable lifecycle record a supervised run
leaves behind — run/generation starts and stops, preemption handshakes,
checkpoint saves/restores/quarantines, fault injections, compile-cache
traffic. This script is the operator's view of it:

    python scripts/tail_run.py /tmp/run/journal.jsonl          # last 50
    python scripts/tail_run.py /tmp/run/journal.jsonl -n 0     # everything
    python scripts/tail_run.py /tmp/run/journal.jsonl --follow # tail -f

Each line renders as

    HH:MM:SS.mmm  gN  pid        event            key=value ...

Stdlib-only and import-light on purpose: usable on a machine that has the
journal file but not jax.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from datetime import datetime

#: record keys already rendered in the fixed columns
_FIXED = ("seq", "ts", "pid", "gen", "event")


def format_record(rec: dict) -> str:
    ts = rec.get("ts")
    try:
        clock = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError, OverflowError):
        clock = "??:??:??.???"
    gen = rec.get("gen", "?")
    pid = rec.get("pid", "?")
    event = rec.get("event", "?")
    skip = _FIXED
    head = ""
    if event == "generation_resize":
        # The one event an operator scans for: show the world transition
        # inline (`shrink 4->3 host=2`) ahead of the remaining fields.
        head = (f"{rec.get('kind', '?')} {rec.get('old_world', '?')}->"
                f"{rec.get('new_world', '?')} host={rec.get('host', '?')} ")
        skip = _FIXED + ("kind", "old_world", "new_world", "host")
    extras = " ".join(
        f"{k}={rec[k]}" for k in rec if k not in skip and rec[k] is not None
    )
    return f"{clock}  g{gen}  {pid:>7}  {event:<20} {head}{extras}".rstrip()


def render_line(raw: str) -> str | None:
    raw = raw.strip()
    if not raw:
        return None
    try:
        rec = json.loads(raw)
    except ValueError:
        return f"?? malformed: {raw[:120]}"
    if not isinstance(rec, dict):
        return f"?? malformed: {raw[:120]}"
    return format_record(rec)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="pretty-print a dist_mnist_tpu run journal")
    parser.add_argument("journal", help="path to the JSONL journal file")
    parser.add_argument("-n", type=int, default=50,
                        help="show the last N records (0 = all; default 50)")
    parser.add_argument("--follow", "-f", action="store_true",
                        help="keep the file open and stream new records")
    args = parser.parse_args(argv)

    try:
        fh = open(args.journal, "r", encoding="utf-8")
    except OSError as e:
        print(f"tail_run: {e}", file=sys.stderr)
        return 1
    with fh:
        lines = fh.readlines()
        if args.n > 0:
            lines = lines[-args.n:]
        for raw in lines:
            out = render_line(raw)
            if out:
                print(out)
        if not args.follow:
            return 0
        try:
            while True:
                raw = fh.readline()
                if not raw:
                    time.sleep(0.25)
                    continue
                out = render_line(raw)
                if out:
                    print(out, flush=True)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
