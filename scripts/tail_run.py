#!/usr/bin/env python
"""Render a run journal (obs/events.py JSONL) as a human-readable timeline.

The journal is the machine-readable lifecycle record a supervised run
leaves behind — run/generation starts and stops, preemption handshakes,
checkpoint saves/restores/quarantines, fault injections, compile-cache
traffic. This script is the operator's view of it:

    python scripts/tail_run.py /tmp/run/journal.jsonl          # last 50
    python scripts/tail_run.py /tmp/run/journal.jsonl -n 0     # everything
    python scripts/tail_run.py /tmp/run/journal.jsonl --follow # tail -f

Each line renders as

    HH:MM:SS.mmm  gN  pid        event            key=value ...

Stdlib-only and import-light on purpose: usable on a machine that has the
journal file but not jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from datetime import datetime

#: record keys already rendered in the fixed columns
_FIXED = ("seq", "ts", "pid", "gen", "event")


def _fmt_num(v, suffix: str = "") -> str:
    try:
        return f"{float(v):.2f}{suffix}"
    except (TypeError, ValueError):
        return f"{v}{suffix}"


def format_record(rec: dict) -> str:
    ts = rec.get("ts")
    try:
        clock = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError, OverflowError):
        clock = "??:??:??.???"
    gen = rec.get("gen", "?")
    pid = rec.get("pid", "?")
    event = rec.get("event", "?")
    skip = _FIXED
    head = ""
    if event == "generation_resize":
        # The one event an operator scans for: show the world transition
        # inline (`shrink 4->3 host=2`) ahead of the remaining fields.
        head = (f"{rec.get('kind', '?')} {rec.get('old_world', '?')}->"
                f"{rec.get('new_world', '?')} host={rec.get('host', '?')} ")
        skip = _FIXED + ("kind", "old_world", "new_world", "host")
    elif event == "straggler_detected":
        # Fleet scraper flagged a host: lead with who and how far behind.
        head = (f"host={rec.get('host', '?')} "
                f"{_fmt_num(rec.get('ratio'), 'x')} median "
                f"({_fmt_num(rec.get('step_time_mean_ms'), 'ms')} vs "
                f"{_fmt_num(rec.get('fleet_median_ms'), 'ms')}) ")
        skip = _FIXED + ("host", "ratio", "step_time_mean_ms",
                         "fleet_median_ms")
    elif event == "anomaly":
        head = (f"{rec.get('kind', '?')} "
                f"z={_fmt_num(rec.get('zscore'))} ")
        skip = _FIXED + ("kind", "zscore")
    elif event == "span":
        if rec.get("dur_ms") is not None:
            head = f"{rec.get('name', '?')} {_fmt_num(rec['dur_ms'], 'ms')} "
            skip = _FIXED + ("name", "dur_ms")
        else:
            head = f"{rec.get('name', '?')} "
            skip = _FIXED + ("name",)
    elif event == "checkpoint_commit":
        # dur_ms spans dispatch->durable (checkpoint/manager.py): lead
        # with step + span so the write-behind window reads inline.
        head = (f"step={rec.get('step', '?')} durable after "
                f"{_fmt_num(rec.get('dur_ms'), 'ms')} ")
        skip = _FIXED + ("step", "dur_ms")
    elif event in ("peer_restore", "checkpoint_restore"):
        head = (f"step={rec.get('step', '?')} "
                f"{_fmt_num(rec.get('dur_ms'), 'ms')} ")
        skip = _FIXED + ("step", "dur_ms")
    elif event == "tuning/applied":
        # An autotuned knob landed: lead with what changed and the
        # measured evidence it rode in on (tune/store.py apply_tuned).
        head = (f"{rec.get('knob', '?')}={rec.get('value', '?')} "
                f"{rec.get('metric', '?')} "
                f"{_fmt_num(rec.get('measured'))} vs default "
                f"{_fmt_num(rec.get('baseline'))} ")
        skip = _FIXED + ("knob", "value", "metric", "measured", "baseline")
    # journal records are host-stamped when DIST_MNIST_TPU_HOST_ID was set
    # in the emitting process; fold that into the fixed columns so merged
    # fleet journals stay scannable. generation_resize keeps its own
    # host field (the host that left), rendered in the head above.
    hostcol = ""
    if "host" in rec and "host" not in skip:
        hostcol = f"h{rec['host']}  "
        skip = skip + ("host",)
    extras = " ".join(
        f"{k}={rec[k]}" for k in rec if k not in skip and rec[k] is not None
    )
    return (f"{clock}  g{gen}  {hostcol}{pid:>7}  {event:<20} "
            f"{head}{extras}").rstrip()


def render_line(raw: str) -> str | None:
    raw = raw.strip()
    if not raw:
        return None
    try:
        rec = json.loads(raw)
    except ValueError:
        return f"?? malformed: {raw[:120]}"
    if not isinstance(rec, dict):
        return f"?? malformed: {raw[:120]}"
    return format_record(rec)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="pretty-print a dist_mnist_tpu run journal")
    parser.add_argument("journal", help="path to the JSONL journal file")
    parser.add_argument("-n", type=int, default=50,
                        help="show the last N records (0 = all; default 50)")
    parser.add_argument("--follow", "-f", action="store_true",
                        help="keep the file open and stream new records")
    args = parser.parse_args(argv)

    try:
        fh = open(args.journal, "r", encoding="utf-8")
    except OSError as e:
        print(f"tail_run: {e}", file=sys.stderr)
        return 1
    try:
        lines = fh.readlines()
        if args.n > 0:
            lines = lines[-args.n:]
        for raw in lines:
            out = render_line(raw)
            if out:
                print(out)
        if not args.follow:
            return 0
        # --follow must survive generation rollover: an elastic supervisor
        # (or log rotation) can replace or truncate the journal under us.
        # Detect inode change / shrink by stat()ing the path and reopen.
        try:
            ino = os.fstat(fh.fileno()).st_ino
            while True:
                raw = fh.readline()
                if raw:
                    out = render_line(raw)
                    if out:
                        print(out, flush=True)
                    continue
                time.sleep(0.25)
                try:
                    st = os.stat(args.journal)
                except OSError:
                    continue  # mid-rotation; keep the old fd until it's back
                if st.st_ino != ino or st.st_size < fh.tell():
                    fh.close()
                    try:
                        fh = open(args.journal, "r", encoding="utf-8")
                    except OSError:
                        continue
                    ino = os.fstat(fh.fileno()).st_ino
        except KeyboardInterrupt:
            return 0
    finally:
        fh.close()


if __name__ == "__main__":
    sys.exit(main())
