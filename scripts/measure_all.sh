#!/usr/bin/env bash
# Measurement-day battery: run EVERYTHING that needs the real chip, in
# dependency-free order, each stage bounded, all output accumulated to one
# timestamped log. Designed for the flaky relay: every stage starts with
# bench's bounded backend probe and fails fast with a structured JSON line
# instead of hanging, so a mid-battery outage costs one stage, not the day.
#
#   bash scripts/measure_all.sh [outdir]
#
# Stages (budgets reflect docs/PERF.md: ViT cold compiles via the remote
# compile helper need ~25 min; repeats hit /tmp/jax_compile_cache):
#   1. bench.py headline (LeNet-5 accuracy race + throughput)
#   2. bench.py --config for every ladder config (light first, ViT last)
#   3. scripts/step_ablation.py  (headline step-time attribution)
#   4. scripts/vit_probe.py      (ViT MFU attribution incl. remat_save_attn)
#   5. scripts/perf_sweep.py     (knob table refresh)
#   6. scripts/pp_probe.py       (pipeline schedules; needs >=8 chips —
#                                 emits a JSON "cannot form mesh" line on 1)
# After a full pass: update docs/PERF.md + docs/PERF_ANCHOR.json together.

set -u
OUT="${1:-/tmp/measure_all_$(date +%Y%m%d_%H%M%S)}"
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
# one probe verdict per backend for the WHOLE battery: the first stage
# probes for real, every later stage reads the cached verdict (bench.py
# _probe) — on a down relay that turns N stages x retries x
# BENCH_PROBE_TIMEOUT_S of waiting into a single timed-out probe
export BENCH_PROBE_CACHE="$OUT/probe_cache.json"

run_stage() { # name timeout_s cmd...
  local name="$1" budget="$2"; shift 2
  echo "=== [$name] start $(date -u +%H:%M:%SZ) budget=${budget}s ==="
  ( timeout "$budget" "$@" ) >"$OUT/$name.log" 2>&1
  local rc=$?
  tail -3 "$OUT/$name.log"
  echo "=== [$name] rc=$rc end $(date -u +%H:%M:%SZ) ==="
}

run_stage bench_headline 1600 python bench.py --deadline 1500
run_stage bench_mlp       900 python bench.py --config mlp_mnist --deadline 800
run_stage bench_lenet5    900 python bench.py --config lenet5_mnist --deadline 800
run_stage bench_fashion   900 python bench.py --config lenet5_fashion --deadline 800
run_stage bench_resnet   1600 python bench.py --config resnet20_cifar --deadline 1500
# ViT family: first one pays the cold compile (~25 min via the remote
# compile helper when /tmp/jax_compile_cache is cold — docs/PERF.md), so it
# gets a 3200 s budget; siblings mostly share cache and keep 1800 s.
run_stage bench_vit      3200 python bench.py --config vit_tiny_cifar --deadline 3000
run_stage bench_vit_tp   1800 python bench.py --config vit_tiny_cifar_tp --deadline 1700
run_stage bench_vit_uly  1800 python bench.py --config vit_tiny_cifar_ulysses --deadline 1700
run_stage bench_vit_ring 1800 python bench.py --config vit_tiny_cifar_ring --deadline 1700
run_stage bench_vit_moe  1800 python bench.py --config vit_tiny_cifar_moe --deadline 1700
run_stage bench_vit_pp   1800 python bench.py --config vit_tiny_cifar_pp --deadline 1700
run_stage bench_vit_flash 1800 python bench.py --config vit_tiny_cifar_flash --deadline 1700
run_stage bench_vit_ring_flash 1800 python bench.py --config vit_tiny_cifar_ring_flash --deadline 1700
run_stage bench_vit_uly_flash 1800 python bench.py --config vit_tiny_cifar_ulysses_flash --deadline 1700
# subsystem modes: serving latency, input-stall attribution, HBM
# attribution, and resilience (recovery latency + goodput) — all
# self-contained bench modes with the same one-JSON-line contract
run_stage bench_serve     900 python bench.py --serve --deadline 800
run_stage bench_serve_fleet 900 python bench.py --serve --fleet --deadline 800
run_stage bench_serve_autoscale 900 python bench.py --serve --autoscale --deadline 800
run_stage bench_serve_longctx 900 python bench.py --serve --longctx --deadline 800
run_stage bench_serve_quant 900 python bench.py --serve --quant --deadline 800
# decode gets a bigger budget than its serve siblings: the paged+int8
# capacity trio (three engines at max_seq=4096 + the teacher-forced
# replay) runs ~9 min on a forced-8-device CPU mesh, ~2 min stock
run_stage bench_serve_decode 1500 python bench.py --serve --decode --requests 64 --concurrency 16 --deadline 1400
run_stage bench_kernels  900 python bench.py --kernels --deadline 800
run_stage bench_input     900 python bench.py --input --steps 200 --deadline 800
run_stage bench_memory    900 python bench.py --memory --deadline 800
run_stage bench_faults    900 python bench.py --faults --deadline 800
run_stage bench_elastic   900 python bench.py --faults --elastic --deadline 800
run_stage bench_ckpt      900 python bench.py --ckpt --deadline 800
run_stage bench_coldstart 900 python bench.py --coldstart --deadline 800
run_stage bench_overlap   900 python bench.py --overlap --deadline 800
run_stage bench_tune      900 python bench.py --tune --deadline 800
run_stage step_ablation  1800 python scripts/step_ablation.py
run_stage vit_probe      3600 python scripts/vit_probe.py
run_stage perf_sweep     1800 python scripts/perf_sweep.py
run_stage pp_probe       1800 python scripts/pp_probe.py
run_stage longctx_probe  1800 python scripts/longctx_probe.py

echo "battery complete -> $OUT"
grep -h '"metric"\|"variant"\|"summary"' "$OUT"/*.log | head -60
