#!/usr/bin/env bash
# One-stop pre-commit gate: the source lints + the measured-numbers gate.
#
#   scripts/lint_all.sh                 # full tree
#   scripts/lint_all.sh --changed-only  # graftlint scoped to git-dirty files
#
# 1. graftlint (python -m dist_mnist_tpu.analysis): AST rules for
#    trace-safety, SPMD divergence, cache-key completeness, thread
#    lifecycle, journal/metric registry drift, bench-stage wiring
#    (docs/ANALYSIS.md). Extra args are passed straight through.
# 2. scripts/check_bench_regression.py: newest BENCH_*.json vs
#    docs/PERF_ANCHOR.json (skips cleanly when no bench artifact or no
#    accelerator is reachable — it gates measurement-day commits, not
#    every edit).
#
# Exit: nonzero if any gate fails.
set -u
cd "$(dirname "$0")/.."

rc=0

echo "== graftlint (python -m dist_mnist_tpu.analysis $*)"
python -m dist_mnist_tpu.analysis "$@" || rc=1

echo "== bench regression gate (scripts/check_bench_regression.py)"
python scripts/check_bench_regression.py || rc=1

if [ "$rc" -eq 0 ]; then
    echo "lint_all: all gates clean"
else
    echo "lint_all: FAILURES above" >&2
fi
exit "$rc"
