#!/usr/bin/env python
"""Bounded TPU-relay liveness probe with an append-only evidence log.

The axon relay backend in this image goes down for days at a time
(docs/OUTAGES.md); bench/measure-day tooling needs a cheap, *bounded*
"is the chip reachable right now?" check whose result is recorded in-repo
so each round's verdict can audit when measurement was actually possible.

    python scripts/probe_tpu.py [--retries 3] [--timeout 150] [--log ...]

Probe semantics are deliberately STRICTER than bench.py's `_probe`
(which only lists devices): this one also executes a tiny program and
`device_get`s the result, because on this relay a value transfer cannot
complete early (docs/PERF.md "Timing methodology"). The retry/timeout
constants DO match bench's (3 × 150 s) so an OUTAGES.md row and a
BENCH_rNN.json `probe_errors` entry from the same window agree about
whether measurement was possible. Unlike bench's probe there is no
JAX_PLATFORMS=cpu override path — liveness of the site-default (axon
TPU) platform is exactly the question. Appends one markdown table row
per invocation (not per retry) and prints one JSON line. Exit 0 = alive.
"""
from __future__ import annotations

import argparse
import datetime
import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent

_PROBE_SRC = (
    "import jax, time; t0=time.time(); d=jax.devices();"
    "import jax.numpy as jnp;"
    "x=jnp.ones((128,128)); v=float(jax.device_get(jnp.dot(x,x)).sum());"
    "print('PROBE_OK', d[0].platform, len(d), round(time.time()-t0,1), v)"
)


def probe(retries: int, timeout_s: int) -> dict:
    t0 = time.time()
    ok, detail = False, ""
    for attempt in range(retries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=timeout_s,
                env=os.environ.copy(),
            )
            ok = r.returncode == 0 and "PROBE_OK" in r.stdout
            lines = (r.stdout + r.stderr).strip().splitlines()
            detail = lines[-1] if lines else ""
        except subprocess.TimeoutExpired:
            ok, detail = False, f"probe timed out after {timeout_s}s"
        if ok:
            break
        detail = f"attempt {attempt + 1}/{retries}: {detail}"
    return {
        "ts": datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"),
        "ok": ok,
        "elapsed_s": round(time.time() - t0, 1),
        "detail": detail[-200:],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--retries", type=int, default=3)
    ap.add_argument("--timeout", type=int, default=150)
    ap.add_argument("--log", default=str(REPO / "docs" / "OUTAGES.md"))
    ap.add_argument("--no-log", action="store_true")
    args = ap.parse_args()

    res = probe(args.retries, args.timeout)
    print(json.dumps(res))
    if not args.no_log:
        log = pathlib.Path(args.log)
        if not log.exists():
            log.write_text(
                "# TPU relay probe log\n\n"
                "Append-only record of bounded liveness probes "
                "(`scripts/probe_tpu.py`). Each row is one out-of-process\n"
                "probe: import jax, run one tiny program, device_get the "
                "result, bounded by the stated timeout.\n\n"
                "| UTC time | alive | elapsed | detail |\n"
                "|---|---|---|---|\n")
        detail = res["detail"].replace("|", "\\|")
        with log.open("a") as f:
            f.write(f"| {res['ts']} | {'YES' if res['ok'] else 'no'} "
                    f"| {res['elapsed_s']}s | {detail} |\n")
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
