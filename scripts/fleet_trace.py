#!/usr/bin/env python
"""Merge a fleet's run journal + per-host timeline exports into ONE
chrome://tracing file with one track per host.

The run journal (obs/events.py) is host-stamped: every record carries
`host` (from DIST_MNIST_TPU_HOST_ID) and `gen`, and the train loop emits
cadence-gated `span` records (input_wait / h2d / dispatch / checkpoint)
with wall-clock timestamps. Those three coordinates — (host, gen, step)
— are exactly what chrome trace needs:

    pid = host + 1      (track per host; supervisor-side records on pid 0)
    tid = generation    (a resize shows up as the work hopping threads)
    ts  = journal wall clock, rebased to the earliest record

`span` records with `dur_ms` become complete events (ph "X") ending at
their journal timestamp — and so does `checkpoint_commit`, whose dur_ms
is back-dated to the save's DISPATCH (checkpoint/manager.py), so the
async write-behind shows as a real dispatch→durable bar next to the
skinny host-side `checkpoint` span it detached from. Spans without a
duration (h2d carries bytes, not time) and every other lifecycle record
(generation_resize, preemption, straggler_detected, anomaly,
checkpoint_restore, peer_restore) become instants (ph "i"), so the
resize/fault story lines up against the per-host step work.

Per-host profiler exports (obs/timeline.py `timeline-h<host>-<run>.json`)
can be merged in with --timelines: their events keep their internal
structure but are remapped onto fresh pids grouped under the owning
host's name. Profiler clocks are per-process, not fleet-aligned, so each
file is rebased to its own start rather than the journal's.

    python scripts/fleet_trace.py /tmp/run/journal.jsonl -o fleet.json
    python scripts/fleet_trace.py j.jsonl --timelines /tmp/run/logs

Stdlib-only on purpose: runs on a machine that has the artifacts but
not jax.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

#: journal keys not worth repeating inside trace-event args
_SKIP_ARGS = ("seq", "ts", "pid", "gen", "event", "host", "name", "dur_ms")

#: timeline export filename -> host id ("timeline-h3-run.json" -> 3)
_TIMELINE_RE = re.compile(r"^timeline-h(\d+)-.*\.json$")


def _read_journal(path: str | Path) -> list[dict]:
    recs = []
    with open(path, "r", encoding="utf-8") as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if isinstance(rec, dict) and isinstance(rec.get("ts"), (int, float)):
                recs.append(rec)
    return recs


def _pid_for(rec: dict) -> int:
    host = rec.get("host")
    try:
        return int(host) + 1
    except (TypeError, ValueError):
        return 0  # supervisor / pre-fleet records


def journal_events(recs: list[dict]) -> list[dict]:
    """Journal records -> trace events (no metadata; see build_fleet_trace)."""
    if not recs:
        return []
    base = min(r["ts"] for r in recs)
    out = []
    for rec in recs:
        pid = _pid_for(rec)
        tid = rec.get("gen", 0)
        ts_us = (rec["ts"] - base) * 1e6
        args = {k: v for k, v in rec.items()
                if k not in _SKIP_ARGS and v is not None}
        event = rec.get("event", "?")
        if event == "span" and isinstance(rec.get("dur_ms"), (int, float)):
            dur_us = rec["dur_ms"] * 1e3
            out.append({
                "name": rec.get("name", "span"), "ph": "X", "cat": "span",
                # the journal stamps span END (emit happens after the work);
                # rebuild the start so the bar covers the right interval
                "ts": round(max(0.0, ts_us - dur_us), 3),
                "dur": round(dur_us, 3),
                "pid": pid, "tid": tid, "args": args,
            })
        elif event == "span":
            out.append({
                "name": rec.get("name", "span"), "ph": "i", "s": "t",
                "cat": "span", "ts": round(ts_us, 3),
                "pid": pid, "tid": tid, "args": args,
            })
        elif (event == "checkpoint_commit"
              and isinstance(rec.get("dur_ms"), (int, float))):
            # dur_ms spans dispatch (snapshot fork / save call) -> durable
            # (commit marker on disk): render it as a bar so the write-
            # behind window is visible against the step work above it
            dur_us = rec["dur_ms"] * 1e3
            out.append({
                "name": "checkpoint_commit", "ph": "X", "cat": "checkpoint",
                "ts": round(max(0.0, ts_us - dur_us), 3),
                "dur": round(dur_us, 3),
                "pid": pid, "tid": tid, "args": args,
            })
        else:
            out.append({
                "name": event, "ph": "i", "s": "p", "cat": "lifecycle",
                "ts": round(ts_us, 3), "pid": pid, "tid": tid, "args": args,
            })
    return out


def _merge_timeline(path: Path, host: int | None, next_pid: int) -> tuple[list[dict], int]:
    """Remap one profiler export onto fresh pids; returns (events, next_pid)."""
    try:
        events = json.loads(path.read_bytes()).get("traceEvents", [])
    except (OSError, ValueError):
        return [], next_pid
    pid_map: dict = {}
    times = [ev.get("ts") for ev in events
             if isinstance(ev, dict) and isinstance(ev.get("ts"), (int, float))]
    base = min(times) if times else 0.0
    label = f"host {host}" if host is not None else path.stem
    out = []
    for ev in events:
        if not isinstance(ev, dict):
            continue
        orig = ev.get("pid", 0)
        if orig not in pid_map:
            pid_map[orig] = next_pid
            next_pid += 1
            out.append({"name": "process_name", "ph": "M",
                        "pid": pid_map[orig],
                        "args": {"name": f"{label} profiler/{orig}"}})
        ev = dict(ev)
        ev["pid"] = pid_map[orig]
        if isinstance(ev.get("ts"), (int, float)):
            ev["ts"] = round(ev["ts"] - base, 3)
        out.append(ev)
    return out, next_pid


def find_timelines(root: str | Path) -> list[tuple[Path, int | None]]:
    """timeline-h<host>-*.json under root (recursively), host parsed from
    the name; legacy un-stamped timeline-*.json files ride along with
    host=None."""
    found = []
    for p in sorted(Path(root).rglob("timeline-*.json")):
        m = _TIMELINE_RE.match(p.name)
        found.append((p, int(m.group(1)) if m else None))
    return found


def build_fleet_trace(
    journal: str | Path | None = None,
    timelines: list[tuple[Path, int | None]] | None = None,
) -> dict:
    """Assemble the merged trace document. Importable for tests/bench."""
    events: list[dict] = []
    pids: set[int] = set()
    if journal is not None:
        jevents = journal_events(_read_journal(journal))
        pids = {ev["pid"] for ev in jevents}
        for pid in sorted(pids):
            name = "supervisor" if pid == 0 else f"host {pid - 1}"
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": name}})
            events.append({"name": "process_sort_index", "ph": "M",
                           "pid": pid, "args": {"sort_index": pid}})
        events.extend(jevents)
    next_pid = (max(pids) if pids else 0) + 1000
    for path, host in (timelines or []):
        merged, next_pid = _merge_timeline(Path(path), host, next_pid)
        events.extend(merged)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="merge a run journal + per-host timelines into one "
                    "chrome://tracing file (one track per host)")
    parser.add_argument("journal", nargs="?",
                        help="path to the JSONL run journal")
    parser.add_argument("--timelines", default=None, metavar="DIR",
                        help="directory scanned (recursively) for "
                             "timeline-h<host>-*.json profiler exports")
    parser.add_argument("-o", "--out", default="fleet_trace.json",
                        help="output path (default fleet_trace.json)")
    args = parser.parse_args(argv)
    if not args.journal and not args.timelines:
        parser.error("need a journal and/or --timelines")
    timelines = find_timelines(args.timelines) if args.timelines else []
    try:
        doc = build_fleet_trace(args.journal, timelines)
    except OSError as e:
        print(f"fleet_trace: {e}", file=sys.stderr)
        return 1
    Path(args.out).write_text(json.dumps(doc), encoding="utf-8")
    tracks = {ev["pid"] for ev in doc["traceEvents"] if "pid" in ev}
    print(f"fleet_trace: {len(doc['traceEvents'])} events across "
          f"{len(tracks)} tracks -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
