#!/usr/bin/env python
"""Gate on bench regressions: newest BENCH_*.json vs docs/PERF_ANCHOR.json.

bench.py stamps `vs_anchor` (measured / last-committed-anchor ratio) on
its one-line JSON report whenever the running chip's device_kind matches
the anchor's. This script turns that number into a pass/fail:

    exit 1  -- a metric's vs_anchor fell below 1 - tolerance (regression)
    exit 0  -- everything within tolerance, OR nothing checkable: no
               BENCH_*.json, no anchor file, bench errored (backend
               down), or hardware mismatch (no vs_anchor). Skips are
               loud on stdout but never fail the build — this box may
               have no accelerator at all.

Tolerance is 0.15 by default (steps/sec is noisy at small step counts;
docs/PERF.md), overridable per metric with a `tolerance` key on the
anchor entry, and globally with --tolerance. Improvements (vs_anchor
well above 1.0) are reported, never failed — update the anchor instead.

    python scripts/check_bench_regression.py            # repo-root scan
    python scripts/check_bench_regression.py --tolerance 0.05

Stdlib-only and fast (no jax import): tests/test_bench_regression.py
runs the `check()` entry point inside tier-1.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DEFAULT_TOLERANCE = 0.15


def newest_bench(root: str | Path = REPO) -> Path | None:
    """Newest BENCH_*.json by round number (BENCH_r05 > BENCH_r04), falling
    back to mtime when the name carries no ordering."""
    found = sorted(Path(root).glob("BENCH_*.json"),
                   key=lambda p: (p.name, p.stat().st_mtime))
    return found[-1] if found else None


def bench_records(path: str | Path) -> list[dict]:
    """Extract bench report lines from a BENCH_*.json driver artifact.

    The artifact wraps bench.py's stdout: `parsed` holds the last JSON
    line, `tail` the raw text (possibly several lines when a battery
    ran). Collect every metric-shaped record, last occurrence wins."""
    try:
        doc = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict):
        return []
    by_metric: dict[str, dict] = {}
    for raw in str(doc.get("tail", "")).splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            rec = json.loads(raw)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            by_metric[rec["metric"]] = rec
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        by_metric.setdefault(parsed["metric"], parsed)
    return list(by_metric.values())


def load_anchors(path: str | Path) -> dict[str, dict]:
    try:
        doc = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return {}
    return {k: v for k, v in doc.items()
            if isinstance(v, dict) and not k.startswith("_")}


def check(
    bench_path: str | Path | None = None,
    anchor_path: str | Path | None = None,
    tolerance: float = DEFAULT_TOLERANCE,
) -> tuple[bool, list[dict]]:
    """Returns (ok, report_rows). ok is False only on a real regression.

    Each row: {"metric", "status": regression|ok|improved|skip,
    "detail", and vs_anchor/floor when checked}."""
    if bench_path is None:
        bench_path = newest_bench()
    if anchor_path is None:
        anchor_path = REPO / "docs" / "PERF_ANCHOR.json"
    rows: list[dict] = []
    if bench_path is None or not Path(bench_path).exists():
        return True, [{"metric": "*", "status": "skip",
                       "detail": "no BENCH_*.json artifact found"}]
    anchors = load_anchors(anchor_path)
    if not anchors:
        return True, [{"metric": "*", "status": "skip",
                       "detail": f"no anchors readable at {anchor_path}"}]
    records = bench_records(bench_path)
    if not records:
        return True, [{"metric": "*", "status": "skip",
                       "detail": f"no bench records in {bench_path}"}]
    ok = True
    for rec in records:
        metric = rec["metric"]
        if rec.get("error"):
            rows.append({"metric": metric, "status": "skip",
                         "detail": f"bench errored: {rec['error']}"})
            continue
        vs = rec.get("vs_anchor")
        if not isinstance(vs, (int, float)):
            rows.append({"metric": metric, "status": "skip",
                         "detail": "no vs_anchor (hardware mismatch or "
                                   "unanchored metric)"})
            continue
        tol = anchors.get(metric, {}).get("tolerance", tolerance)
        floor = 1.0 - float(tol)
        row = {"metric": metric, "vs_anchor": round(float(vs), 3),
               "floor": round(floor, 3)}
        if vs < floor:
            ok = False
            row.update(status="regression",
                       detail=f"vs_anchor {vs:.3f} < floor {floor:.3f}")
        elif vs > 1.0 + float(tol):
            row.update(status="improved",
                       detail=f"vs_anchor {vs:.3f}; consider re-anchoring "
                              "(docs/PERF.md)")
        else:
            row.update(status="ok", detail=f"vs_anchor {vs:.3f}")
        rows.append(row)
    return ok, rows


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="fail when the newest BENCH_*.json regressed vs "
                    "docs/PERF_ANCHOR.json")
    parser.add_argument("--bench", default=None,
                        help="BENCH_*.json path (default: newest in repo root)")
    parser.add_argument("--anchor", default=None,
                        help="anchor file (default docs/PERF_ANCHOR.json)")
    parser.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                        help="allowed fractional drop below the anchor "
                             "(default 0.15; per-metric `tolerance` keys in "
                             "the anchor file override)")
    args = parser.parse_args(argv)
    ok, rows = check(args.bench, args.anchor, args.tolerance)
    for row in rows:
        print(f"check_bench_regression: {row['metric']}: {row['status']} "
              f"({row['detail']})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
