#!/usr/bin/env python
"""Perf sweep for the headline bench — run on the real chip.

Times the LeNet-5 step (the BASELINE.md metric) across the knobs that
matter, one JSON line per variant, so regressions/wins are attributable:

- step dispatch: per-step fused vs lax.scan chunks of {10, 100, 500}
- compute dtype: bfloat16 vs float32
- input path: fused on-device sampling vs host feed (ShardedBatcher)
- remat on/off (memory-for-FLOPs; should be ~neutral for LeNet)

Usage: python scripts/perf_sweep.py [--steps 2000] [--batch 200]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# runnable as `python scripts/perf_sweep.py` from anywhere: the repo root
# must join sys.path WITHOUT touching PYTHONPATH (which would shadow the
# .axon_site entry that registers the TPU platform plugin in this image)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


# the axon-hardened device_get stop-clock (single definition; the loss it
# returns is printed per variant as an executed-for-real sanity check)
from dist_mnist_tpu.utils.timing import timed_chunks as time_variant  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=2000)
    ap.add_argument("--batch", type=int, default=200)
    ap.add_argument("--model", default="lenet5")
    args = ap.parse_args()

    # probe + platform override preamble shared with bench (bench.py):
    # bounds the down-tunnel hang and pins the backend the probe validated
    from bench import probe_or_exit

    probe_or_exit("perf_sweep")

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import jax.numpy as jnp

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import DeviceDataset, ShardedBatcher, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, make_train_step
    from dist_mnist_tpu.train.step import make_scanned_train_fn

    n_chips = jax.device_count()
    mesh = make_mesh(MeshSpec(data=-1))
    dataset = load_dataset("mnist", "/tmp/mnist-data", seed=0)

    def fresh_state(model):
        state = create_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   dataset.train_images[:1])
        return shard_train_state(state, mesh)

    optimizer = optim.adam(1e-3)
    results = []

    with activate(mesh):
        dd = DeviceDataset(dataset, mesh)

        # -- scan chunk size x dtype x remat --------------------------------
        for chunk in (10, 100, 500):
            for dtype_name in ("bfloat16", "float32"):
                for remat in (False, True):
                    if remat and (chunk != 100 or dtype_name != "bfloat16"):
                        continue  # remat: one representative point
                    model = get_model(
                        args.model, compute_dtype=getattr(jnp, dtype_name)
                    )
                    run = make_scanned_train_fn(
                        model, optimizer, mesh, dd, args.batch, chunk,
                        remat=remat,
                    )
                    n_chunks = max(1, args.steps // chunk)
                    dt, _, loss = time_variant(run, fresh_state(model),
                                               n_chunks)
                    steps = n_chunks * chunk
                    results.append({
                        "variant": f"scan{chunk}_{dtype_name}"
                                   + ("_remat" if remat else ""),
                        "steps_per_sec_per_chip": round(steps / dt / n_chips, 2),
                        "final_loss": round(loss, 4),
                    })
                    print(json.dumps(results[-1]), flush=True)

        # -- host-feed path (the reference-style per-step feed) -------------
        model = get_model(args.model)
        step = make_train_step(model, optimizer, mesh)
        state = fresh_state(model)
        batches = iter(ShardedBatcher(dataset, args.batch, mesh, seed=0))
        n = min(args.steps, 500)
        # same shared stop-clock as every other number (timed_chunks);
        # the warmup call consumes one batch, as before
        dt, state, loss = time_variant(
            lambda s: step(s, next(batches)), state, n
        )
        results.append({
            "variant": "host_feed_per_step",
            "steps_per_sec_per_chip": round(n / dt / n_chips, 2),
            "final_loss": round(loss, 4),
        })
        print(json.dumps(results[-1]), flush=True)

    best = max(results, key=lambda r: r["steps_per_sec_per_chip"])
    print(json.dumps({"best": best, "chips": n_chips,
                      "global_batch": args.batch}))


if __name__ == "__main__":
    main()
