#!/usr/bin/env python
"""Perf sweep for the headline bench — run on the real chip.

Now a thin shim over the persistent autotuner (`dist_mnist_tpu/tune`):
the old hand-rolled sweep loops became registered tunables with
successive-halving search, so the knob table refresh and the tuned-config
store are fed by ONE engine instead of two drifting copies. The timed
knobs this script sweeps (`scan_chunk` step-dispatch granularity,
`prefetch_depth` input feed) meter wall-clock and belong on the real
chip — the deterministic knobs run everywhere via `bench.py --tune`.

Output discipline is unchanged: one JSON line per trial plus a summary
line, so measure_all.sh's metric-line harvest keeps working.

Usage: python scripts/perf_sweep.py [--steps 2000] [--batch 200]
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as `python scripts/perf_sweep.py` from anywhere: the repo root
# must join sys.path WITHOUT touching PYTHONPATH (which would shadow the
# .axon_site entry that registers the TPU platform plugin in this image)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=2000)
    ap.add_argument("--batch", type=int, default=200)
    ap.add_argument("--model", default="lenet5")
    ap.add_argument("--store", default=None,
                    help="TunedConfigStore dir (default: "
                         "$DIST_MNIST_TPU_TUNED_DIR)")
    args = ap.parse_args()

    # probe + platform override preamble shared with bench (bench.py):
    # bounds the down-tunnel hang and pins the backend the probe validated
    from bench import probe_or_exit

    probe_or_exit("perf_sweep")

    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dist_mnist_tpu.tune.cli import main as tune_main

    argv = ["--knobs", "scan_chunk,prefetch_depth",
            "--steps", str(args.steps),
            "--batch", str(args.batch),
            "--model", args.model]
    if args.store:
        argv += ["--store", args.store]
    return tune_main(argv)


if __name__ == "__main__":
    sys.exit(main())
