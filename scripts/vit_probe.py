#!/usr/bin/env python
"""ViT-Ti step-time variant probe (docs/PERF.md ViT ladder row).

The single-chip vit_tiny_cifar ladder point (64/chip, depth-12,
remat+augment+dropout, scan_blocks) measured 74.5 steps/s = 0.5 % MFU —
far below what dim-192 matmuls should sustain even at batch 64. This
script times the same step with one knob flipped at a time to attribute
the gap: remat off, augment off, dropout off, unrolled blocks, and a
2x/4x batch (is it the small-batch regime or a fixed overhead?).

JSON line per variant (device_get stop-clock, utils/timing.py).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--chunk", type=int, default=50)
    ap.add_argument("--chunks", type=int, default=4)
    args = ap.parse_args()

    # probe + platform override preamble shared with bench (bench.py):
    # bounds the down-tunnel hang and pins the backend the probe validated
    from bench import probe_or_exit

    probe_or_exit("vit_probe")

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dist_mnist_tpu.cli.train import build_optimizer
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.data import DeviceDataset, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.step import make_scanned_train_fn
    from dist_mnist_tpu.utils.flops import analytic_step_flops, mfu
    from dist_mnist_tpu.utils.timing import timed_chunks
    from dist_mnist_tpu.utils.prng import prng_impl_scope

    cfg = get_config("vit_tiny_cifar")
    mesh = make_mesh(MeshSpec(data=-1))
    n_chips = mesh.devices.size
    dataset = load_dataset(cfg.dataset, "/tmp/mnist-data", seed=cfg.seed)
    optimizer = build_optimizer(cfg)
    # --batch is PER CHIP (the ladder point's 64/chip), like bench's
    # ladder_batch: scale to the mesh so a multi-chip run times the same
    # per-chip regime and steps/sec divides into steps/sec/chip honestly
    per_chip = args.batch

    variants = [
        ("ladder_point", {}, dict(remat=cfg.remat, augment=cfg.augment),
         per_chip),
        ("no_remat", {}, dict(remat=False, augment=cfg.augment), per_chip),
        # remat with the attention outputs SAVED (the suspected fix for the
        # remat-recompute share of the MFU gap: ~+50% backward FLOPs)
        ("remat_save_attn", {},
         dict(remat=True, augment=cfg.augment, remat_policy="save_attn"),
         per_chip),
        ("no_augment", {}, dict(remat=cfg.remat, augment=False), per_chip),
        ("no_dropout", {"dropout_rate": 0.0},
         dict(remat=cfg.remat, augment=cfg.augment), per_chip),
        ("lean", {"dropout_rate": 0.0}, dict(remat=False, augment=False),
         per_chip),
        ("unrolled", {"scan_blocks": False},
         dict(remat=cfg.remat, augment=cfg.augment), per_chip),
        ("batch_2x", {}, dict(remat=cfg.remat, augment=cfg.augment),
         2 * per_chip),
        ("batch_4x", {}, dict(remat=cfg.remat, augment=cfg.augment),
         4 * per_chip),
        # rbg PRNG for the per-layer dropout masks (threefry bit-mixing is
        # a known TPU cost); scoped via the rbg flag below
        ("rbg_prng", {}, dict(remat=cfg.remat, augment=cfg.augment),
         per_chip),
    ]

    results = {}
    with activate(mesh):
        dd = DeviceDataset(dataset, mesh)
        for name, mkw, skw, batch_per_chip in variants:
            batch = batch_per_chip * n_chips
            # the rbg variant scopes the impl around BUILD + RUN (keys are
            # made at state creation) via the shared context manager
            scope = (prng_impl_scope("rbg") if name == "rbg_prng"
                     else contextlib.nullcontext())
            with scope:
                model = get_model(cfg.model, **{**cfg.model_kwargs, **mkw})
                state = shard_train_state(
                    create_train_state(model, optimizer,
                                       jax.random.PRNGKey(0),
                                       dataset.train_images[:1]),
                    mesh,
                )
                run = make_scanned_train_fn(model, optimizer, mesh, dd,
                                            batch, args.chunk, **skw)
                dt, state, loss = timed_chunks(run, state, args.chunks)
            per_step = dt / (args.chunk * args.chunks)
            results[name] = per_step
            # analytic, not XLA-counted (the scan-over-layers stack is
            # understated ~depth x by cost_analysis), on the PER-CHIP
            # basis bench uses: batch/chip FLOPs vs one chip's peak
            fl = analytic_step_flops(model, dataset.train_images[:1].shape,
                                     batch_per_chip)
            util = mfu(fl, per_step)
            print(json.dumps({
                "variant": name, "batch_per_chip": batch_per_chip,
                "chips": n_chips,
                "steps_per_sec_per_chip": round(1.0 / per_step / n_chips, 2),
                "examples_per_sec": round(batch / per_step),
                # null (not 0.0) when the chip's peak is unknown — the
                # repo-wide "report unknowable MFU as null, never guess"
                # rule (utils/flops.py)
                "mfu": round(util, 4) if util is not None else None,
                "flops_per_step": round(fl) if fl else None,
                "final_loss": round(loss, 4),
            }), flush=True)

    # attribution summary: each knob's speedup over the ladder point (>1 =
    # the knob costs that factor), ready to paste into docs/PERF.md
    base = results.get("ladder_point")
    if base:
        print(json.dumps({
            "attribution_speedup_vs_ladder_point": {
                name: round(base / dt, 3)
                for name, dt in results.items() if name != "ladder_point"
            },
            "note": "speedup s on a knob-off variant means the knob adds "
                    "(s-1)/s of the LADDER step (x1.3 -> 23%); batch_2x/4x "
                    "compare per-STEP time (throughput gain = speedup x "
                    "batch factor)",
        }), flush=True)


if __name__ == "__main__":
    main()
