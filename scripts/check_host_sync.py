#!/usr/bin/env python
"""Grep-lint for accidental host synchronization in hot-path modules.

Since ISSUE 15 this is a THIN SHIM over the graftlint rule
`dist_mnist_tpu.analysis.rules.host_sync` — one implementation, two
front doors. The full suite (`python -m dist_mnist_tpu.analysis`) runs
this rule alongside the others; this script keeps the original CLI and
exit codes so existing muscle memory, docs, and
tests/test_host_sync_lint.py all keep working:

- Scanned modules: the curated hot-path set, now owned by the rule as
  `host_sync.HOT_PATH_TARGETS` (train/, faults/, the prefetch worker,
  hook cadence paths, the overlap schedule, serve dispatch/load paths).
- Flagged constructs: bare ``float(``, ``.item()`` methods, bare or
  qualified ``device_get(`` — each a blocking device->host transfer
  when its operand is a device array. AST-scoped: only code inside
  function/lambda bodies counts (module level runs once at import).
- Allowlist: ``# lint: ok[host-sync] <why>`` on the same line or the
  line above; the legacy ``# host-sync-ok: <why>`` marker is still
  honored. The comment is the reviewable artifact: every sync in a hot
  path is either justified in place or a lint failure.

Exit status: 0 clean, 1 violations (printed one per line as
``path:lineno: message``). Wired into tier-1 via
tests/test_host_sync_lint.py; the whole-suite wiring lives in
tests/test_analysis.py.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parent.parent
if str(_REPO_ROOT) not in sys.path:  # script-run without an install
    sys.path.insert(0, str(_REPO_ROOT))

from dist_mnist_tpu.analysis.core import SourceFile  # noqa: E402
from dist_mnist_tpu.analysis.rules import host_sync  # noqa: E402

ALLOW_MARKER = "host-sync-ok"  # legacy marker, still honored

# re-exported so the construct lists live in exactly one place
ANY_NAMES = host_sync.ANY_NAMES
BARE_NAMES = host_sync.BARE_NAMES
METHOD_NAMES = host_sync.METHOD_NAMES


def default_targets(repo_root: Path) -> list[Path]:
    return host_sync.hot_path_files(Path(repo_root))


def scan_file(path: Path) -> list[tuple[int, str]]:
    """(lineno, message) per violation in `path`. Suppressions (both
    marker forms) are applied here — standalone files never pass through
    the engine's suppression stage."""
    path = Path(path)
    sf = SourceFile(path, str(path))
    return [(f.line, f.message)
            for f in host_sync.scan_source(sf)
            if not sf.is_suppressed("host-sync", f.line)]


def main(argv: list[str]) -> int:
    repo_root = Path(__file__).resolve().parent.parent
    targets = ([Path(a) for a in argv] if argv
               else default_targets(repo_root))
    violations = []
    for path in targets:
        for lineno, msg in scan_file(path):
            violations.append(f"{path}:{lineno}: {msg}")
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} host-sync violation(s).", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
