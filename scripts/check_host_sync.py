#!/usr/bin/env python
"""Grep-lint for accidental host synchronization in hot-path modules.

The per-step dispatch pipeline is this framework's whole perf story: a
single stray `float(device_scalar)` / `.item()` / per-key `device_get`
inside the train loop, the prefetch worker, or a hook's cadence path
serializes dispatch exactly the way the reference's per-step feed_dict
round-trip did (SURVEY.md §3.3) — and it regresses silently, because the
numbers stay correct. This lint makes the sync surface explicit:

- Scanned modules (the hot paths): ``dist_mnist_tpu/train/``,
  ``dist_mnist_tpu/faults/``, ``dist_mnist_tpu/data/prefetch.py``,
  ``dist_mnist_tpu/hooks/builtin.py``.
- Flagged constructs: ``float(`` and ``device_get(`` calls, and ``.item()``
  — each an implicit (or explicit) device->host blocking transfer when its
  operand is a device array.
- Allowlist: a ``host-sync-ok`` comment on the same line or the line above
  marks an INTENTIONAL sync (e.g. LoggingHook's one batched fetch per
  cadence, evaluate()'s single end-of-eval pull). The comment is the
  reviewable artifact: every sync in a hot path is either justified in
  place or a lint failure.

Tokenizer-based, not regex-on-lines: occurrences inside comments and
docstrings don't count (several hot-path docstrings MENTION `float()`
while explaining why it was removed).

Exit status: 0 clean, 1 violations (printed one per line as
``path:lineno: message``). Wired into tier-1 via
tests/test_host_sync_lint.py.
"""

from __future__ import annotations

import io
import sys
import tokenize
from pathlib import Path

ALLOW_MARKER = "host-sync-ok"

#: NAME tokens that, followed by "(", count as a sync call whether bare or
#: attribute-qualified (`jax.device_get(...)`).
ANY_NAMES = ("device_get",)

#: NAME tokens that count only when BARE (not `x.float(...)`).
BARE_NAMES = ("float",)

#: NAME tokens that count only as a METHOD call: preceded by "." and
#: followed by "(" — bare `item(` is some other function.
METHOD_NAMES = ("item",)


def default_targets(repo_root: Path) -> list[Path]:
    pkg = repo_root / "dist_mnist_tpu"
    targets = sorted((pkg / "train").glob("*.py"))
    # faults/ sits inside the loop (injection hook per step, goodput clock
    # per iteration) — same hot-path rules apply
    targets += sorted((pkg / "faults").glob("*.py"))
    # parallel/overlap.py builds the comm/compute-overlap prefetch path —
    # one host sync there serializes exactly what it exists to overlap
    targets += [pkg / "data" / "prefetch.py", pkg / "hooks" / "builtin.py",
                pkg / "parallel" / "overlap.py"]
    # serve/zoo.py is the zoo's PLANNING layer: grid/mask/byte accounting
    # must stay metadata-only — every device transfer belongs in engine.py
    targets += [pkg / "serve" / "zoo.py"]
    # the quantized-serving path: ops/quant.py's quantize pass must stay
    # free of hot-path syncs (its one batched error-report pull and the
    # load-time degenerate-scale check are the annotated exceptions), and
    # engine.py/loader.py carry the per-request dispatch + load paths the
    # quant work rides through
    targets += [pkg / "ops" / "quant.py", pkg / "serve" / "engine.py",
                pkg / "serve" / "loader.py"]
    return [t for t in targets if t.exists()]


def scan_file(path: Path) -> list[tuple[int, str]]:
    """(lineno, message) per violation in `path`."""
    src = path.read_text()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except tokenize.TokenError as err:
        return [(1, f"unparseable: {err}")]

    # lines carrying an allowlist comment bless themselves AND the line
    # below (marker-above style for lines that would overflow)
    allowed: set[int] = set()
    for tok in tokens:
        if tok.type == tokenize.COMMENT and ALLOW_MARKER in tok.string:
            allowed.add(tok.start[0])
            allowed.add(tok.start[0] + 1)

    out = []
    # meaningful tokens only: NL/INDENT/COMMENT tokens between a name and
    # its "(" would defeat the adjacency check
    code = [t for t in tokens
            if t.type in (tokenize.NAME, tokenize.OP, tokenize.NUMBER,
                          tokenize.STRING)]
    for i, tok in enumerate(code):
        if tok.type != tokenize.NAME:
            continue
        nxt = code[i + 1] if i + 1 < len(code) else None
        if nxt is None or nxt.string != "(":
            continue
        prev = code[i - 1] if i > 0 else None
        is_method = prev is not None and prev.string == "."
        if (tok.string in ANY_NAMES
                or tok.string in BARE_NAMES and not is_method
                or tok.string in METHOD_NAMES and is_method):
            if tok.start[0] in allowed:
                continue
            what = f".{tok.string}()" if is_method else f"{tok.string}("
            out.append((
                tok.start[0],
                f"{what} in a hot-path module is a blocking device->host "
                f"sync; batch it or annotate with `# {ALLOW_MARKER}: <why>`",
            ))
    return out


def main(argv: list[str]) -> int:
    repo_root = Path(__file__).resolve().parent.parent
    targets = ([Path(a) for a in argv] if argv
               else default_targets(repo_root))
    violations = []
    for path in targets:
        for lineno, msg in scan_file(path):
            violations.append(f"{path}:{lineno}: {msg}")
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} host-sync violation(s).", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
