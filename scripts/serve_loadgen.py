#!/usr/bin/env python
"""Standalone deterministic load generator against an in-process server.

Thin wrapper over `dist_mnist_tpu.serve.loadgen.run_loadgen` (one
definition shared with `cli/serve.py`, `bench.py --serve` and
tests/test_serve.py) with a sweep mode: run the same deterministic load at
several concurrency levels and print one JSON line each, so a latency/
throughput knee is one script run.

    python scripts/serve_loadgen.py --config mlp_mnist --requests 512 \
        --concurrency 1,8,64 --platform cpu --host-device-count 8
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="mlp_mnist")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--requests", type=int, default=512)
    ap.add_argument("--concurrency", default="64",
                    help="comma-separated sweep, e.g. 1,8,64")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--queue-depth", type=int, default=256)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--host-device-count", type=int, default=None)
    args = ap.parse_args()

    from dist_mnist_tpu.cluster import initialize_distributed

    initialize_distributed(
        None, 1, 0,
        platform=args.platform, host_device_count=args.host_device_count,
    )

    from dist_mnist_tpu.cluster.mesh import make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.serve import (
        InferenceEngine,
        InferenceServer,
        ServeConfig,
        load_for_serving,
        run_loadgen,
    )

    cfg = get_config(args.config)
    mesh = make_mesh(cfg.mesh)
    bundle = load_for_serving(cfg, mesh, checkpoint_dir=args.checkpoint_dir)
    engine = InferenceEngine(
        bundle.model, bundle.params, bundle.model_state, mesh,
        model_name=cfg.model, image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=args.max_batch,
    )
    for conc in (int(c) for c in args.concurrency.split(",")):
        # fresh server per level: each level's stats stand alone
        server = InferenceServer(engine, ServeConfig(
            max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth,
            default_deadline_ms=args.deadline_ms,
        ))
        with server:
            summary = run_loadgen(
                server,
                n_requests=args.requests,
                concurrency=conc,
                image_shape=bundle.image_shape,
                seed=args.seed,
            )
        print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
