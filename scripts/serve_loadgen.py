#!/usr/bin/env python
"""Standalone deterministic load generator against an in-process server.

Thin wrapper over `dist_mnist_tpu.serve.loadgen.run_loadgen` (one
definition shared with `cli/serve.py`, `bench.py --serve` and
tests/test_serve.py) with a sweep mode: run the same deterministic load at
several concurrency levels and print one JSON line each, so a latency/
throughput knee is one script run.

    python scripts/serve_loadgen.py --config mlp_mnist --requests 512 \
        --concurrency 1,8,64 --platform cpu --host-device-count 8

``--fleet N`` switches to the two-class fleet generator
(`run_fleet_loadgen`) against an in-process N-replica `serve/router.py`
Router sharing one compile cache — per-class latency/shed/reject
accounting at each concurrency level:

    python scripts/serve_loadgen.py --fleet 3 --ls-fraction 0.8 \
        --ls-deadline-ms 500 --platform cpu --host-device-count 8

``--decode`` switches to the autoregressive generator
(`run_decode_loadgen`) against a `serve/decode.py` continuous-batching
scheduler — TTFT percentiles and per-request token throughput at each
concurrency level; ``--decode-mode static`` runs the static-batch
baseline on the same compiled executables:

    python scripts/serve_loadgen.py --decode --requests 64 \
        --concurrency 4,16 --platform cpu --host-device-count 8
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="mlp_mnist")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--requests", type=int, default=512)
    ap.add_argument("--concurrency", default="64",
                    help="comma-separated sweep, e.g. 1,8,64")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--queue-depth", type=int, default=256)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--host-device-count", type=int, default=None)
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="drive an in-process N-replica router with "
                         "two-class traffic instead of one server")
    ap.add_argument("--ls-fraction", type=float, default=0.8,
                    help="latency_sensitive fraction in --fleet mode")
    ap.add_argument("--ls-deadline-ms", type=float, default=None)
    ap.add_argument("--be-deadline-ms", type=float, default=None)
    ap.add_argument("--decode", action="store_true",
                    help="autoregressive decode mode: drive a "
                         "serve/decode.py scheduler instead of the "
                         "classifier server")
    ap.add_argument("--decode-mode", default="continuous",
                    choices=("continuous", "static"),
                    help="scheduling mode in --decode mode")
    ap.add_argument("--max-slots", type=int, default=8,
                    help="in-flight sequence capacity in --decode mode")
    args = ap.parse_args()

    from dist_mnist_tpu.cluster import initialize_distributed

    initialize_distributed(
        None, 1, 0,
        platform=args.platform, host_device_count=args.host_device_count,
    )

    from dist_mnist_tpu.cluster.mesh import make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.serve import (
        InferenceEngine,
        InferenceServer,
        ServeConfig,
        load_for_serving,
        run_loadgen,
    )

    if args.decode:
        return _decode_sweep(args)
    cfg = get_config(args.config)
    mesh = make_mesh(cfg.mesh)
    bundle = load_for_serving(cfg, mesh, checkpoint_dir=args.checkpoint_dir)
    if args.fleet:
        return _fleet_sweep(args, cfg, mesh, bundle)
    engine = InferenceEngine(
        bundle.model, bundle.params, bundle.model_state, mesh,
        model_name=cfg.model, image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=args.max_batch,
    )
    for conc in (int(c) for c in args.concurrency.split(",")):
        # fresh server per level: each level's stats stand alone
        server = InferenceServer(engine, ServeConfig(
            max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth,
            default_deadline_ms=args.deadline_ms,
        ))
        with server:
            summary = run_loadgen(
                server,
                n_requests=args.requests,
                concurrency=conc,
                image_shape=bundle.image_shape,
                seed=args.seed,
            )
        print(json.dumps(summary, sort_keys=True))
    return 0


def _decode_sweep(args) -> int:
    """Decode mode: fresh scheduler per concurrency level, one engine
    (and therefore one compiled-program set + KV cache) across levels.
    `token_times` is dropped from the printed summary — per-token
    timestamps are a programmatic consumer's field, not a CLI one."""
    from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
    from dist_mnist_tpu.serve import (
        DecodeScheduler,
        build_decode_engine,
        run_decode_loadgen,
    )

    mesh = make_mesh(MeshSpec(data=-1))
    engine = build_decode_engine(mesh, seed=args.seed,
                                 max_slots=args.max_slots)
    engine.prewarm()
    for conc in (int(c) for c in args.concurrency.split(",")):
        scheduler = DecodeScheduler(engine, mode=args.decode_mode,
                                    max_queue=args.queue_depth)
        try:
            summary = run_decode_loadgen(
                scheduler,
                n_requests=args.requests,
                concurrency=conc,
                seed=args.seed,
                ls_fraction=args.ls_fraction,
            )
        finally:
            scheduler.close()
        summary.pop("token_times", None)
        print(json.dumps(summary, sort_keys=True))
    return 0


def _fleet_sweep(args, cfg, mesh, bundle) -> int:
    """Fleet mode: fresh N-replica router per concurrency level, one
    shared compile cache across every replica and level."""
    from dist_mnist_tpu.obs import HealthState
    from dist_mnist_tpu.serve import (
        CompiledModelCache,
        InferenceEngine,
        InferenceServer,
        InProcessReplica,
        Router,
        ServeConfig,
        run_fleet_loadgen,
    )

    shared_cache = CompiledModelCache()

    def make_server():
        engine = InferenceEngine(
            bundle.model, bundle.params, bundle.model_state, mesh,
            model_name=cfg.model, image_shape=bundle.image_shape,
            rules=bundle.rules, max_bucket=args.max_batch,
            cache=shared_cache,
        )
        return InferenceServer(engine, ServeConfig(
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth), health=HealthState()).start()

    for conc in (int(c) for c in args.concurrency.split(",")):
        fleet = [InProcessReplica(i, make_server).start()
                 for i in range(args.fleet)]
        router = Router(fleet).start()
        try:
            summary = run_fleet_loadgen(
                router,
                n_requests=args.requests,
                concurrency=conc,
                image_shape=bundle.image_shape,
                seed=args.seed,
                ls_fraction=args.ls_fraction,
                ls_deadline_ms=args.ls_deadline_ms,
                be_deadline_ms=args.be_deadline_ms,
            )
        finally:
            router.close()
            for r in fleet:
                r.close()
        print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
