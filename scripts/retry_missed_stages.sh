#!/usr/bin/env bash
# Relay-watch continuation of a partial measure_all battery: probe the TPU
# relay on a slow cadence and, the moment it answers, run exactly the
# stages the battery missed (the probe rows land in docs/OUTAGES.md like
# every other probe). One full catch-up pass, then exit — re-launch for
# another. Bounded everywhere; safe to leave running for hours.
#
#   bash scripts/retry_missed_stages.sh [outdir] [max_probe_rounds]

set -u
OUT="$(realpath -m "${1:-/tmp/measure_retry_$(date +%Y%m%d_%H%M%S)}")"
ROUNDS="${2:-32}"
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
# one pattern for every harvest/display site (drift risk otherwise)
METRIC_RE='"metric"\|"variant"\|"summary"'
# shared probe verdict across the catch-up stages (see measure_all.sh);
# NOT set during the probe loop below — each round must re-probe for real
PROBE_CACHE="$OUT/probe_cache.json"

run_stage() { # name timeout_s cmd...   (same shape as measure_all.sh)
  local name="$1" budget="$2"; shift 2
  echo "=== [$name] start $(date -u +%H:%M:%SZ) budget=${budget}s ==="
  ( timeout "$budget" "$@" ) >"$OUT/$name.log" 2>&1
  local rc=$?
  tail -3 "$OUT/$name.log"
  echo "=== [$name] rc=$rc end $(date -u +%H:%M:%SZ) ==="
  # land results in-repo IMMEDIATELY (not at pass end): a late-recovery
  # pass interrupted by round end still leaves every finished stage's
  # metric lines where the driver's final auto-commit captures them
  grep -h "$METRIC_RE" "$OUT/$name.log" \
    >> docs/measurements/r5_retry.jsonl 2>/dev/null || true
}

for i in $(seq 1 "$ROUNDS"); do
  if python scripts/probe_tpu.py --retries 1 --timeout 90 \
       >"$OUT/probe_$i.log" 2>&1; then
    echo "relay alive on probe $i — running missed stages"
    # pass boundary in the evidence file: a re-launched pass appends its
    # own delimited block instead of anonymous duplicate lines
    echo "{\"retry_pass\": \"$(date -u +%FT%TZ)\", \"outdir\": \"$OUT\"}" \
      >> docs/measurements/r5_retry.jsonl
    # fresh verdict file per pass: the relay just answered, so stale
    # down-verdicts from an earlier pass must not short-circuit this one
    rm -f "$PROBE_CACHE"
    export BENCH_PROBE_CACHE="$PROBE_CACHE"
    # first ViT-family stage pays the cold compile (docs/PERF.md ~25 min)
    run_stage bench_vit_tp    3200 python bench.py --config vit_tiny_cifar_tp --deadline 3000
    run_stage bench_vit_uly   1800 python bench.py --config vit_tiny_cifar_ulysses --deadline 1700
    run_stage bench_vit_ring  1800 python bench.py --config vit_tiny_cifar_ring --deadline 1700
    run_stage bench_vit_moe   1800 python bench.py --config vit_tiny_cifar_moe --deadline 1700
    run_stage bench_vit_pp    1800 python bench.py --config vit_tiny_cifar_pp --deadline 1700
    run_stage bench_vit_flash 1800 python bench.py --config vit_tiny_cifar_flash --deadline 1700
    run_stage bench_vit_ring_flash 1800 python bench.py --config vit_tiny_cifar_ring_flash --deadline 1700
    run_stage bench_vit_uly_flash 1800 python bench.py --config vit_tiny_cifar_ulysses_flash --deadline 1700
    # subsystem bench modes (same one-JSON-line contract as the configs)
    run_stage bench_serve     900 python bench.py --serve --deadline 800
    run_stage bench_serve_fleet 900 python bench.py --serve --fleet --deadline 800
    run_stage bench_serve_autoscale 900 python bench.py --serve --autoscale --deadline 800
    run_stage bench_serve_longctx 900 python bench.py --serve --longctx --deadline 800
    run_stage bench_serve_quant 900 python bench.py --serve --quant --deadline 800
    # bigger budget: the paged+int8 capacity trio (see measure_all.sh)
    run_stage bench_serve_decode 1500 python bench.py --serve --decode --requests 64 --concurrency 16 --deadline 1400
    run_stage bench_kernels  900 python bench.py --kernels --deadline 800
    run_stage bench_input     900 python bench.py --input --steps 200 --deadline 800
    run_stage bench_memory    900 python bench.py --memory --deadline 800
    run_stage bench_faults    900 python bench.py --faults --deadline 800
    run_stage bench_elastic   900 python bench.py --faults --elastic --deadline 800
    run_stage bench_ckpt      900 python bench.py --ckpt --deadline 800
    run_stage bench_coldstart 900 python bench.py --coldstart --deadline 800
    run_stage bench_overlap   900 python bench.py --overlap --deadline 800
    run_stage bench_tune      900 python bench.py --tune --deadline 800
    run_stage step_ablation   1800 python scripts/step_ablation.py
    run_stage vit_probe       3600 python scripts/vit_probe.py
    run_stage perf_sweep      1800 python scripts/perf_sweep.py
    # needs >=8 chips; on this 1-chip box it records its structured
    # "cannot form mesh" line, completing the battery record honestly
    run_stage pp_probe        1800 python scripts/pp_probe.py
    run_stage longctx_probe   1800 python scripts/longctx_probe.py
    echo "catch-up pass complete -> $OUT"
    grep -h "$METRIC_RE" "$OUT"/*.log | head -40
    exit 0
  fi
  echo "probe $i: relay down ($(date -u +%H:%M:%SZ)); sleeping 900s"
  sleep 900
done
echo "relay never answered in $ROUNDS probes"
exit 1
