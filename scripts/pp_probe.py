#!/usr/bin/env python
"""Pipeline-parallel overhead measurement (VERDICT r3 weak 4 / next 5).

Times the SAME ViT stack five ways on one 8-device mesh and prints a JSON
line per variant plus the predicted-vs-measured overhead summary:

  dp             plain scanned stack, all 8 devices on `data` (the thing
                 PP competes with when params fit)
  gpipe          block_pipeline=4 (data=2 x pipe=4), GPipe schedule
  circular       block_pipeline=4, pipeline_circular=3 (data=2 x pipe=4)
  gpipe_skip     gpipe with fill/drain stage compute lax.cond'd away
  circular_skip  circular, ditto (pipeline.py skip_bubble)

Tick math (parallel/pipeline.py): per microbatch-stage of compute, the
whole-batch cost on the SAME chip count is
  dp        M * S / n_pipe_equiv      (every device does useful work)
  gpipe     (M + S - 1) * v_chunks    -> inflation (M+S-1)/M over dp
  circular  M*v + S - 1 chunk-ticks   -> inflation (M*v+S-1)/(M*v)
At M=8, S=4, v=3: gpipe 1.375x, circular 1.125x — the bubble shrinks by v.
PP still pays the schedule inflation; its value is fitting params/
activations that DP cannot, so the honest metric is how CLOSE each
schedule gets to the dp floor.

CPU smoke: JAX_PLATFORMS=cpu + XLA_FLAGS=--xla_force_host_platform_device_
count=8 runs the full comparison on the fake mesh. There the `loss_sanity`
equality across variants is the meaningful output (all five variants
compute the same function); the TIME ratios are NOT — the 8 fake devices
share one physical core, so cross-mesh walltime comparisons are artifacts
(measured on this box: DP reads 5x slower than GPipe, the opposite of the
tick math — ignore CPU ratios). The predicted-vs-measured comparison needs
>= 8 real chips; on a 1-chip TPU box the pipe mesh cannot form and the
script exits with a JSON line saying so.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--depth", type=int, default=12)
    ap.add_argument("--dim", type=int, default=64)
    args = ap.parse_args()

    from bench import probe_or_exit

    probe_or_exit("pp_probe")

    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops.losses import softmax_cross_entropy

    n_dev = jax.device_count()
    if n_dev % 8:
        emitted = {"script": "pp_probe",
                   "error": f"need an 8-device mesh (data=2 x pipe=4), "
                            f"have {n_dev}"}
        print(json.dumps(emitted), flush=True)
        return 1

    s_stages, v_chunks, m_micro = 4, 3, 8
    kw = dict(depth=args.depth, dim=args.dim, heads=4, patch=8, pool="mean",
              dropout_rate=0.0, scan_blocks=True,
              compute_dtype=jnp.float32)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(args.batch, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (args.batch,)), jnp.int32)

    variants = {
        "dp": (get_model("vit_tiny", **kw), MeshSpec(data=8)),
        "gpipe": (get_model("vit_tiny", block_pipeline=s_stages,
                            pipeline_microbatches=m_micro, **kw),
                  MeshSpec(data=2, pipe=s_stages)),
        "circular": (get_model("vit_tiny", block_pipeline=s_stages,
                               pipeline_circular=v_chunks,
                               pipeline_microbatches=m_micro, **kw),
                     MeshSpec(data=2, pipe=s_stages)),
        # skip-bubble twins: fill/drain ticks lax.cond away the stage
        # compute — measures whether XLA rewards the branch or loses more
        # to inhibited compute/ppermute overlap (pipeline.py skip_bubble)
        "gpipe_skip": (get_model("vit_tiny", block_pipeline=s_stages,
                                 pipeline_microbatches=m_micro,
                                 pipeline_skip_bubble=True, **kw),
                       MeshSpec(data=2, pipe=s_stages)),
        "circular_skip": (get_model("vit_tiny", block_pipeline=s_stages,
                                    pipeline_circular=v_chunks,
                                    pipeline_microbatches=m_micro,
                                    pipeline_skip_bubble=True, **kw),
                          MeshSpec(data=2, pipe=s_stages)),
    }
    predicted = {
        "dp": 1.0,
        "gpipe": (m_micro + s_stages - 1) / m_micro,
        "circular": (m_micro * v_chunks + s_stages - 1)
        / (m_micro * v_chunks),
    }
    # skip does NOT change the predicted wall: the bubble is a dependency
    # -chain property (rank s+1's tick t+1 needs rank s's tick t), and
    # garbage ticks fill otherwise-IDLE ranks — they were never on the
    # critical path. Expect skip ~== unskipped wall; the win is FLOPs/
    # energy/HBM traffic. A skip slower than its twin = the cond's cost
    # (lost compute/ppermute overlap), which is what this measures.
    predicted["gpipe_skip"] = predicted["gpipe"]
    predicted["circular_skip"] = predicted["circular"]

    results = {}
    for name, (model, spec) in variants.items():
        mesh = make_mesh(spec)
        params, state = model.init(jax.random.PRNGKey(0), x)

        def loss_fn(p):
            logits, _ = model.apply(p, state, x, train=False)
            return softmax_cross_entropy(logits, y)

        with activate(mesh):
            step = jax.jit(jax.value_and_grad(loss_fn))
            loss, grads = step(params)  # compile + warmup
            # device_get stop-clock (docs/PERF.md timing methodology)
            float(jax.device_get(loss))
            t0 = time.monotonic()
            for _ in range(args.iters):
                loss, grads = step(params)
            last = float(jax.device_get(loss))
        dt = (time.monotonic() - t0) / args.iters
        results[name] = dt
        print(json.dumps({
            "script": "pp_probe", "variant": name,
            "ms_per_fwd_bwd": round(dt * 1e3, 2),
            "loss_sanity": round(last, 4),
            "predicted_schedule_inflation": round(predicted[name], 3),
        }), flush=True)

    dp = results["dp"]
    backend = jax.default_backend()
    print(json.dumps({
        "script": "pp_probe",
        "backend": backend,
        "summary": {
            name: {
                "measured_vs_dp": round(results[name] / dp, 3),
                "predicted_vs_dp": round(predicted[name], 3),
            } for name in ("gpipe", "circular", "gpipe_skip",
                           "circular_skip")
        },
        "note": (
            "CPU fake mesh: devices share one core — time ratios are "
            "ARTIFACTS; only loss_sanity equality is meaningful here"
            if backend == "cpu" else
            "measured includes psum-broadcast + masked fill/drain compute "
            "on top of the tick math; circular should sit between dp and "
            "gpipe"
        ),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
