#!/usr/bin/env python
"""Long-context attention engine comparison on ONE chip (SURVEY §5.7).

Times fwd+bwd through each single-device attention engine at growing
sequence lengths and prints one JSON line per (engine, S) point plus a
summary — the measured basis for the long-context engine choice the docs
currently argue from design (ring/ulysses cover the multi-device axis;
this probe covers the single-device kernel axis they compose with):

  xla        ops/nn.dot_product_attention — HBM [B,H,S,S] score tensor
  flash      Pallas kernel, full K/V resident per q tile (block_k=None)
  flash_bk   Pallas kernel, online-softmax streaming (block_k=512)

Also records each engine's compile-time per-device temp memory
(memory_analysis) so the HBM-score-tensor vs VMEM-tiles claim is a
measured number, not prose. Geometry: B=4, H=8, D=64 (bf16) — a realistic
long-context attention slice; S sweeps 1k..8k (the full-K kernel's
documented ceiling) and the streaming path continues to 16k where only it
can run without sequence sharding.

CPU smoke: loss-parity across engines is the meaningful output (time
ratios are interpreter artifacts — the Pallas interpreter is orders of
magnitude slower than compiled XLA on CPU; ignore). On the real chip the
time and memory columns are the result. Bounded probe first: on a dead
relay this exits with a structured JSON error line instead of hanging
(scripts/measure_all.sh stage discipline).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--block_k", type=int, default=512)
    ap.add_argument("--max_s", type=int, default=16384)
    args = ap.parse_args()

    from bench import probe_or_exit

    probe_or_exit("longctx_probe")

    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dist_mnist_tpu.ops.nn import dot_product_attention
    from dist_mnist_tpu.ops.pallas.flash_attention import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    engines = {
        "xla": dot_product_attention,
        "flash": lambda q, k, v: flash_attention(q, k, v),
        "flash_bk": lambda q, k, v: flash_attention(
            q, k, v, block_k=args.block_k),
    }
    # the full-K kernel's documented resident ceiling; past it, only the
    # streaming path runs single-device (the xla path's S x S score tensor
    # has usually OOM'd HBM earlier at real batch sizes)
    ceiling = {"xla": 8192, "flash": 8192, "flash_bk": args.max_s}

    results = {}
    s = 1024
    while s <= args.max_s:
        rng = np.random.default_rng(s)
        mk = lambda: jnp.asarray(
            rng.normal(size=(args.batch, s, args.heads, args.dim)), dtype)
        q, k, v = mk(), mk(), mk()
        for name, fn in engines.items():
            if s > ceiling[name]:
                continue
            # grads w.r.t. ALL of q/k/v — dropping k/v would let DCE
            # delete the dK/dV backward (flash's dkv kernels, xla's
            # einsum grads) and bias the engine comparison (code review)
            step = jax.jit(jax.value_and_grad(
                lambda qq, kk, vv, f=fn: jnp.sum(
                    f(qq, kk, vv).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2)))
            try:
                lowered = step.lower(q, k, v).compile()
                # memory_analysis() is best-effort: some backends/versions
                # return None (or raise) instead of CompiledMemoryStats. A
                # missing memory column must not masquerade as an engine
                # failure — the timing below is the probe's primary result
                try:
                    mem = lowered.memory_analysis()
                except Exception:
                    mem = None
                loss, g = lowered(q, k, v)  # compile already paid; warmup
                float(jax.device_get(loss))
                t0 = time.monotonic()
                for _ in range(args.iters):
                    loss, g = lowered(q, k, v)
                final = float(jax.device_get(loss))
                dt = (time.monotonic() - t0) / args.iters
            except Exception as e:  # OOM/VMEM overflow is a RESULT here
                print(json.dumps({
                    "script": "longctx_probe", "engine": name, "s": s,
                    "error": f"{type(e).__name__}: {str(e)[:160]}",
                }), flush=True)
                continue
            results[(name, s)] = (dt, final)
            row = {
                "script": "longctx_probe", "engine": name, "s": s,
                "ms_fwd_bwd": round(dt * 1e3, 2),
                "loss_sanity": round(final, 4),
            }
            if mem is not None and getattr(mem, "temp_size_in_bytes", None) is not None:
                row["temp_mem_mb"] = round(mem.temp_size_in_bytes / 2**20, 1)
            print(json.dumps(row), flush=True)
        s *= 2

    # parity check: at each S every engine that ran must agree on the loss
    parity = {}
    for (name, s), (_, loss) in results.items():
        parity.setdefault(s, {})[name] = loss
    mismatch = {
        s: v for s, v in parity.items()
        if max(v.values()) - min(v.values())
        > 2e-2 * max(abs(x) for x in v.values())
    }
    print(json.dumps({
        "script": "longctx_probe", "backend": jax.default_backend(),
        "summary": {
            f"{name}@{s}": round(dt * 1e3, 2)
            for (name, s), (dt, _) in sorted(results.items(),
                                             key=lambda kv: kv[0][1])
        },
        "loss_parity_ok": not mismatch,
        "note": ("CPU: time ratios are interpreter artifacts; parity is "
                 "the output of record" if jax.default_backend() == "cpu"
                 else "device_get stop-clock; temp_mem from XLA "
                      "memory_analysis"),
    }), flush=True)
    return 0 if not mismatch else 1


if __name__ == "__main__":
    sys.exit(main())
