#!/usr/bin/env python
"""Step-time attribution for the headline LeNet-5 config (docs/PERF.md).

Times progressively larger slices of the scanned training step, all as
chunk-of-100 `lax.scan` programs with the device_get stop-clock
(dist_mnist_tpu/utils/timing.py — block_until_ready is not trusted on this
image's axon relay):

  fwd               forward + loss only, fixed resident batch
  fwd_bwd           + value_and_grad (train mode: dropout included)
  fwd_bwd_adam      + optimizer update + param apply (fixed batch)
  full              the real fused step (adds the in-program batch gather,
                    metrics, and per-step rng/step bookkeeping)
  full_nodropout    full with dropout_rate=0 (isolates the dropout mask)

Deltas between rows attribute per-step time to backward, optimizer,
sampling+metrics (full − fwd_bwd_adam: both run dropout, so the delta is
the gather/metrics/bookkeeping cost), and dropout (full − full_nodropout).
JSON line per row.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timed_scan(body, carry, chunk: int, n_chunks: int):
    """carry -> carry scans, compiled once; returns per-step seconds.
    Same device_get stop-clock discipline as utils/timing.timed_chunks
    (these bodies have no out["loss"], so the fetch is the carry leaf)."""

    @jax.jit
    def run(c):
        return jax.lax.scan(lambda cc, _: (body(cc), None), c, None,
                            length=chunk)[0]

    carry = run(carry)
    jax.device_get(jax.tree.leaves(carry)[0])  # warmup + real sync
    t0 = time.monotonic()
    for _ in range(n_chunks):
        carry = run(carry)
    jax.device_get(jax.tree.leaves(carry)[0])
    return (time.monotonic() - t0) / (chunk * n_chunks)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=200)
    ap.add_argument("--chunk", type=int, default=100)
    ap.add_argument("--chunks", type=int, default=20)
    args = ap.parse_args()

    # probe + platform override preamble shared with bench (bench.py):
    # bounds the down-tunnel hang and pins the backend the probe validated
    from bench import probe_or_exit

    probe_or_exit("step_ablation")

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import DeviceDataset, load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.ops import losses
    from dist_mnist_tpu.optim.base import apply_updates
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.train.step import make_scanned_train_fn
    from dist_mnist_tpu.utils.timing import timed_chunks

    mesh = make_mesh(MeshSpec(data=-1))
    ds = load_dataset("mnist", "/tmp/mnist-data", seed=0)
    model = get_model("lenet5")
    optimizer = optim.adam(1e-3)

    with activate(mesh):
        state = shard_train_state(
            create_train_state(model, optimizer, jax.random.PRNGKey(0),
                               ds.train_images[:1]),
            mesh,
        )
        dd = DeviceDataset(ds, mesh)
        fixed = dd.sample(jax.random.PRNGKey(1), args.batch)
        x_fixed = fixed["image"].astype(jnp.float32) / 255.0
        y_fixed = fixed["label"]
        results = {}

        def emit(name, secs):
            results[name] = secs
            print(json.dumps({"variant": name, "us_per_step":
                              round(secs * 1e6, 1)}), flush=True)

        def time_full(name, a_model, a_state):
            """The real fused step via the shared stop-clock helper."""
            run = make_scanned_train_fn(a_model, optimizer, mesh, dd,
                                        args.batch, args.chunk)
            dt, _, _ = timed_chunks(run, a_state, args.chunks)
            emit(name, dt / (args.chunk * args.chunks))

        key = jax.random.PRNGKey(2)

        # --- fwd: forward + loss on a fixed batch; carry = a scalar so the
        # scan has a data dependency chain without touching params.
        # train=True with the SAME fixed rng as the grad slices, so the
        # fwd/fwd_bwd delta isolates ONLY the backward pass (dropout's
        # forward cost would otherwise be double-counted into "backward").
        # The carry is folded into the INPUT (x + 1e-30*acc): the forward is
        # then not loop-invariant, so while-loop LICM cannot hoist it out of
        # the scan and time an empty loop (ADVICE r3 #2).
        def fwd_body(acc):
            x = x_fixed + 1e-30 * acc
            logits, _ = model.apply(state.params, state.model_state, x,
                                    train=True, rng=key)
            return acc + losses.softmax_cross_entropy(logits, y_fixed)

        emit("fwd", timed_scan(fwd_body, jnp.zeros(()), args.chunk,
                               args.chunks))
        # hoist-detector: per-step time must be chunk-length-invariant; a
        # hoisted (loop-invariant) body would show ~chunk x inflation here
        half = max(1, args.chunk // 2)
        secs_half = timed_scan(fwd_body, jnp.zeros(()), half, 2)
        ratio = secs_half / max(results["fwd"], 1e-12)
        print(json.dumps({"variant": "fwd_sanity_half_chunk",
                          "us_per_step": round(secs_half * 1e6, 1),
                          "ratio_vs_fwd": round(ratio, 2),
                          "ok": bool(0.5 < ratio < 1.5)}), flush=True)
        # upper bound 1.5, NOT 2.0: a hoisted (empty) loop times the same
        # wall per chunk regardless of length, so its half-chunk per-step
        # ratio sits at exactly 2.0 — the window must exclude it

        # --- fwd_bwd: + grad; carry = params so bwd output feeds the chain
        def loss_of(params, key):
            logits, _ = model.apply(params, state.model_state, x_fixed,
                                    train=True, rng=key)
            return losses.softmax_cross_entropy(logits, y_fixed)

        def fwd_bwd_body(params):
            g = jax.grad(loss_of)(params, key)
            # fold the grads back in, scaled by a tiny NONZERO constant: the
            # chain stays honest and `- 0.0 * g` can't be algebraically
            # simplified into dead-coding the backward (ADVICE r3 #2)
            return jax.tree.map(lambda p, gg: p - 1e-30 * gg, params, g)

        emit("fwd_bwd", timed_scan(fwd_bwd_body, state.params, args.chunk,
                                   args.chunks))

        # --- fwd_bwd_adam: + the real optimizer pipeline on a fixed batch
        def adam_body(carry):
            params, opt_state = carry
            g = jax.grad(loss_of)(params, key)
            updates, opt_state = optimizer.update(g, opt_state, params)
            return apply_updates(params, updates), opt_state

        emit("fwd_bwd_adam",
             timed_scan(adam_body, (state.params, state.opt_state),
                        args.chunk, args.chunks))

        # --- the real fused step, with and without the dropout mask
        time_full("full", model, state)
        model_nd = get_model("lenet5", dropout_rate=0.0)
        state_nd = shard_train_state(
            create_train_state(model_nd, optimizer, jax.random.PRNGKey(0),
                               ds.train_images[:1]),
            mesh,
        )
        time_full("full_nodropout", model_nd, state_nd)

        # --- fsdp comm exposure: the same fused step under ZeRO sharding,
        # serial (barriered — all gathers/flushes on the critical path) vs
        # overlapped (parallel/overlap.py bucket schedule). The delta is
        # the communication the overlap removed. Needs >1 chip on `data`.
        if mesh.shape["data"] > 1:
            from dist_mnist_tpu.parallel.overlap import OverlapConfig
            from dist_mnist_tpu.parallel.sharding import FSDP_RULES

            for name, serial in (("fsdp_serial", True),
                                 ("fsdp_overlap", False)):
                # fresh state per variant: the scanned step donates its
                # input buffers, so one state cannot feed two timed runs
                state_f = shard_train_state(
                    create_train_state(model, optimizer,
                                       jax.random.PRNGKey(0),
                                       ds.train_images[:1]),
                    mesh, FSDP_RULES,
                )
                run = make_scanned_train_fn(
                    model, optimizer, mesh, dd, args.batch, args.chunk,
                    rules=FSDP_RULES,
                    overlap=OverlapConfig(serial=serial))
                dt, _, _ = timed_chunks(run, state_f, args.chunks)
                emit(name, dt / (args.chunk * args.chunks))
        else:
            print(json.dumps({"variant": "fsdp_serial",
                              "skipped": "single-chip mesh: no fsdp "
                                         "communication to attribute"}),
                  flush=True)

    d = {k: v * 1e6 for k, v in results.items()}
    attribution = {
        "forward": round(d["fwd"], 1),
        "backward": round(d["fwd_bwd"] - d["fwd"], 1),
        "optimizer": round(d["fwd_bwd_adam"] - d["fwd_bwd"], 1),
        "sampling+metrics": round(d["full"] - d["fwd_bwd_adam"], 1),
        "dropout_only": round(d["full"] - d["full_nodropout"], 1),
        "full_step": round(d["full"], 1),
    }
    if "fsdp_serial" in d:
        attribution["fsdp_comm_exposed"] = round(
            d["fsdp_serial"] - d["fsdp_overlap"], 1)
    print(json.dumps({"attribution_us": attribution}))


if __name__ == "__main__":
    main()
