#!/usr/bin/env python
"""Chip-day helper: fold a measure_all.sh output dir into PERF_ANCHOR.json.

Reads every `bench_*.log` in the given directory, takes the LAST parseable
bench JSON line of each, and keeps only real measurements (value > 0, no
`error` field — outage lines never become anchors). Prints the merged
anchor document; `--write` saves it to docs/PERF_ANCHOR.json. The anchor
file must only change together with docs/PERF.md (the regression-guard
contract, docs/PERF.md "Regression guard") — this tool therefore prints a
reminder diff of which metrics changed and by how much instead of touching
PERF.md itself.

Usage: python scripts/update_anchors.py /tmp/measure_r4 [--write]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

ANCHOR_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "PERF_ANCHOR.json")


def harvest(outdir: str) -> dict:
    """metric -> {value, device_kind} from the last good line per log."""
    found = {}
    for name in sorted(os.listdir(outdir)):
        if not (name.startswith("bench_") and name.endswith(".log")):
            continue
        best = None
        with open(os.path.join(outdir, name)) as fh:
            for line in fh:
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) and "metric" in obj:
                    best = obj
        if not best:
            continue
        if best.get("error") or not best.get("value"):
            print(f"# {name}: outage/zero line — NOT an anchor "
                  f"({str(best.get('error'))[:80]})", file=sys.stderr)
            continue
        kind = (best.get("extra") or {}).get("device_kind")
        if not kind:
            print(f"# {name}: no device_kind — skipped", file=sys.stderr)
            continue
        metric = best["metric"]
        if metric in found:
            # bench_headline.log and bench_lenet5.log BOTH emit the
            # headline metric (bench.py with/without --config); the
            # headline run is the metric of record and sorts first —
            # keep the first, loudly
            print(f"# {name}: duplicate {metric} — keeping the earlier "
                  "log's value (headline run is the metric of record)",
                  file=sys.stderr)
            continue
        found[metric] = {"value": best["value"], "device_kind": kind}
    return found


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("outdir")
    ap.add_argument("--write", action="store_true")
    ap.add_argument("--allow-kind-change", action="store_true",
                    help="permit replacing an anchor with one measured on "
                         "DIFFERENT hardware (default: refuse — a CPU "
                         "smoke run must never overwrite TPU anchors)")
    args = ap.parse_args()

    new = harvest(args.outdir)
    if not new:
        print("no usable bench lines found — nothing to do", file=sys.stderr)
        return 1

    with open(ANCHOR_PATH) as fh:
        doc = json.load(fh)
    # the document's prevailing hardware: NEW metrics must match it too —
    # a CPU smoke must not seed CPU anchors that later block real TPU runs
    kinds = [v.get("device_kind") for k, v in doc.items()
             if isinstance(v, dict) and v.get("device_kind")]
    prevailing = max(set(kinds), key=kinds.count) if kinds else None
    accepted = 0
    for metric, entry in new.items():
        old_entry = doc.get(metric, {})
        old = old_entry.get("value")
        expect_kind = old_entry.get("device_kind") or prevailing
        if expect_kind and expect_kind != entry["device_kind"] \
                and not args.allow_kind_change:
            # the same cross-hardware guard bench._anchor_fields applies:
            # a ratio across device kinds is meaningless, and a CPU smoke
            # must not pollute the committed TPU regression baseline
            print(f"# {metric}: measured on {entry['device_kind']!r} but "
                  f"the anchor baseline is {expect_kind!r} — REFUSED (pass "
                  "--allow-kind-change for a real hardware migration)",
                  file=sys.stderr)
            continue
        delta = (f" ({(entry['value'] - old) / old:+.1%} vs {old})"
                 if old and old_entry.get("device_kind") == entry["device_kind"]
                 else " (new)")
        print(f"# {metric}: {entry['value']}{delta}", file=sys.stderr)
        doc[metric] = entry
        accepted += 1
    if not accepted:
        print("# no metric accepted — anchors unchanged, nothing written",
              file=sys.stderr)
        return 1
    doc["_measured"] = (
        f"{datetime.date.today().isoformat()}, device_get stop-clock, "
        f"measure_all battery ({os.path.basename(args.outdir)})"
    )
    out = json.dumps(doc, indent=2, ensure_ascii=False)
    print(out)
    if args.write:
        with open(ANCHOR_PATH, "w") as fh:
            fh.write(out + "\n")
        print(f"# wrote {ANCHOR_PATH} — now update docs/PERF.md's tables "
              "in the same commit", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
