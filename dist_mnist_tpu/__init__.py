"""dist_mnist_tpu — a TPU-native SPMD training framework.

A ground-up rebuild of the capabilities of `leo-mao/dist-mnist` (TensorFlow's
gRPC parameter-server MNIST trainer: ClusterSpec / tf.train.Server /
replica_device_setter / SyncReplicasOptimizer / MonitoredTrainingSession —
see SURVEY.md for the full structural analysis of that stack) designed
TPU-first rather than ported:

- The ps/worker multi-process topology collapses into ONE jit-compiled SPMD
  program over a `jax.sharding.Mesh` (SURVEY.md §2.5 rows 21-28 are replaced
  by XLA + libtpu; §2.2 rows 3-5 by `cluster/` + `parallel/`).
- Gradient push/pull over gRPC (RecvTensor RPC, worker.h:85) becomes an XLA
  all-reduce over ICI compiled into the step (`parallel/`).
- SyncReplicasOptimizer's accumulator + token-queue barrier
  (sync_replicas_optimizer.py:215-338) becomes in-step `psum` plus
  gradient accumulation for `replicas_to_aggregate < N` (`optim/sync.py`).
- MonitoredTrainingSession + SessionRunHooks (monitored_session.py:427-609,
  basic_session_run_hooks.py) become a functional `TrainLoop` with the same
  hook lifecycle (`train/`, `hooks/`).
- Saver/checkpoint (saver.py:1186) becomes Orbax-backed restore-or-init
  (`checkpoint/`).

Public surface is re-exported here; see each subpackage for the mapping to
the reference component it replaces.
"""

from dist_mnist_tpu.cluster import ClusterConfig, make_mesh, initialize_distributed
from dist_mnist_tpu.configs import Config, get_config, CONFIGS
from dist_mnist_tpu.train.state import TrainState
from dist_mnist_tpu.train.loop import TrainLoop, StopSignal
from dist_mnist_tpu.train.step import make_train_step, make_eval_step

__version__ = "0.1.0"

__all__ = [
    "ClusterConfig",
    "make_mesh",
    "initialize_distributed",
    "Config",
    "get_config",
    "CONFIGS",
    "TrainState",
    "TrainLoop",
    "StopSignal",
    "make_train_step",
    "make_eval_step",
    "__version__",
]
