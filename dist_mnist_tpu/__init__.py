"""dist_mnist_tpu — a TPU-native SPMD training framework.

A ground-up rebuild of the capabilities of `leo-mao/dist-mnist` (TensorFlow's
gRPC parameter-server MNIST trainer: ClusterSpec / tf.train.Server /
replica_device_setter / SyncReplicasOptimizer / MonitoredTrainingSession —
see SURVEY.md for the full structural analysis of that stack) designed
TPU-first rather than ported:

- The ps/worker multi-process topology collapses into ONE jit-compiled SPMD
  program over a `jax.sharding.Mesh` (SURVEY.md §2.5 rows 21-28 are replaced
  by XLA + libtpu; §2.2 rows 3-5 by `cluster/` + `parallel/`).
- Gradient push/pull over gRPC (RecvTensor RPC, worker.h:85) becomes an XLA
  all-reduce over ICI compiled into the step (`parallel/`).
- SyncReplicasOptimizer's accumulator + token-queue barrier
  (sync_replicas_optimizer.py:215-338) becomes in-step `psum` plus
  gradient accumulation for `replicas_to_aggregate < N` (`optim/sync.py`).
- MonitoredTrainingSession + SessionRunHooks (monitored_session.py:427-609,
  basic_session_run_hooks.py) become a functional `TrainLoop` with the same
  hook lifecycle (`train/`, `hooks/`).
- Saver/checkpoint (saver.py:1186) becomes Orbax-backed restore-or-init
  (`checkpoint/`).

Public surface is re-exported here; see each subpackage for the mapping to
the reference component it replaces.

Re-exports resolve lazily (PEP 562): the process SUPERVISOR
(`cli/launch.py`) imports this package but must stay jax-free — it spawns
and buries whole jax processes, and every elastic generation boundary
would otherwise pay the multi-second jax import in the supervisor itself.
Eagerly importing `cluster`/`train` here would drag jax in.
"""

from __future__ import annotations

_EXPORTS = {
    "ClusterConfig": "dist_mnist_tpu.cluster.mesh",
    "make_mesh": "dist_mnist_tpu.cluster.mesh",
    "initialize_distributed": "dist_mnist_tpu.cluster.coordination",
    "Config": "dist_mnist_tpu.configs",
    "get_config": "dist_mnist_tpu.configs",
    "CONFIGS": "dist_mnist_tpu.configs",
    "TrainState": "dist_mnist_tpu.train.state",
    "TrainLoop": "dist_mnist_tpu.train.loop",
    "StopSignal": "dist_mnist_tpu.train.loop",
    "make_train_step": "dist_mnist_tpu.train.step",
    "make_eval_step": "dist_mnist_tpu.train.step",
}

__version__ = "0.1.0"


def __getattr__(name: str):
    import importlib

    module = _EXPORTS.get(name)
    if module is not None:
        return getattr(importlib.import_module(module), name)
    # plain submodule access (`dist_mnist_tpu.configs` after a bare
    # `import dist_mnist_tpu`) — the eager-init behavior callers may rely on
    try:
        return importlib.import_module(f"{__name__}.{name}")
    except ModuleNotFoundError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None

__all__ = [
    "ClusterConfig",
    "make_mesh",
    "initialize_distributed",
    "Config",
    "get_config",
    "CONFIGS",
    "TrainState",
    "TrainLoop",
    "StopSignal",
    "make_train_step",
    "make_eval_step",
    "__version__",
]
