"""The hooked train loop — MonitoredTrainingSession, functional.

Maps the reference session-wrapper stack (SURVEY.md §2.4 rows 13-16, §3.2/3.3)
onto plain control flow:

- `_HookedSession`'s before/after_run merge (:1414-1508) -> hook calls
  around the compiled step.
- `_CoordinatedSession` + Coordinator (:1347-1411; coordinator.py) ->
  `StopSignal` (request_stop / should_stop / stored exception).
- `_RecoverableSession`'s preemption ring (:1238-1344, retrying only
  `_PREEMPTION_ERRORS` = Aborted/Unavailable, :43-45) -> `max_recoveries` +
  restore-from-checkpoint on a matching error class. In SPMD there is no
  session to rebuild; recovery = reload last checkpoint and continue, which
  is exactly what SessionManager.recover_session did for the chief (§3.2).
"""

from __future__ import annotations

import collections
import logging
import sys
import time
from typing import Iterable, Sequence

import jax

from dist_mnist_tpu.faults.goodput import GoodputClock
from dist_mnist_tpu.hooks.base import Hook
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.obs.hist import StreamingHistogram
from dist_mnist_tpu.train.state import TrainState

log = logging.getLogger(__name__)


class PreemptionError(RuntimeError):
    """Raise-able stand-in for a preempted device/host (tests inject it, the
    way upstream injected AbortedError into _RecoverableSession — §4)."""


#: Exceptions treated as recoverable, mirroring _PREEMPTION_ERRORS
#: (monitored_session.py:43-45). jax surfaces device loss as XlaRuntimeError
#: (a subclass of JaxRuntimeError); we match by name (anywhere in the MRO)
#: to stay version-proof. Type is checked FIRST, and only then the status
#: substrings: an application ValueError whose message happens to contain
#: "preempt" must not buy a silent restore.
def _is_preemption(exc: BaseException) -> bool:
    if isinstance(exc, PreemptionError):
        return True
    mro_names = {c.__name__ for c in type(exc).__mro__}
    if not mro_names & {"XlaRuntimeError", "JaxRuntimeError"}:
        return False
    return any(s in str(exc) for s in ("UNAVAILABLE", "ABORTED", "preempt"))


class StopSignal:
    """Coordinator analogue (coordinator.py:28-400), minus the threads: the
    loop is single-threaded per process, but hooks and outer code still need
    a cooperative stop + exception channel."""

    def __init__(self):
        self._stop = False
        self.reason: str | None = None
        self.exception: BaseException | None = None

    def request_stop(self, reason: str | None = None,
                     exc: BaseException | None = None) -> None:
        if not self._stop:
            self._stop = True
            self.reason = reason
            self.exception = exc

    def should_stop(self) -> bool:
        return self._stop

    def raise_requested_exception(self) -> None:
        if self.exception is not None:
            raise self.exception


class TrainLoop:
    """Run `state = step_fn(state, batch)` over `batches` with hooks.

    `checkpoint_manager` (checkpoint/manager.py) enables preemption
    recovery: on a recoverable error the loop restores the latest
    checkpoint and continues, up to `max_recoveries` times.
    """

    def __init__(
        self,
        step_fn,
        state: TrainState,
        batches: Iterable,
        hooks: Sequence[Hook] = (),
        *,
        checkpoint_manager=None,
        max_recoveries: int = 0,
        steps_per_call: int = 1,
        runahead: int = 0,
        preemption=None,
        health=None,
        span_steps: int = 0,
    ):
        self.step_fn = step_fn
        self.state = state
        self.batches = batches
        self.hooks = list(hooks)
        self.stop = StopSignal()
        self.checkpoint_manager = checkpoint_manager
        self.max_recoveries = max_recoveries
        # preemption handshake (faults/preemption.py): a PreemptionNotice
        # checked at each step boundary — checkpoint, then stop cleanly
        # with `preempted_at` set, so the process can exit 0.
        self.preemption = preemption
        self.preempted_at: int | None = None
        # goodput attribution (faults/goodput.py): every second of run()'s
        # wall clock lands in a productive/restore/replay/stall bucket.
        self.goodput = GoodputClock()
        # live /healthz state machine (obs/exporter.HealthState or None):
        # training while the loop runs, preempted on a consumed notice,
        # stopped/failed on exit.
        self.health = health
        # per-step wall time in ms, scrape-able live via the registry and
        # summarized by StepTimeHook / bench.py --faults
        self.step_time_hist = StreamingHistogram()
        # >1 when step_fn executes a compiled CHUNK of steps (lax.scan —
        # train/step.make_scanned_train_fn): hooks fire once per chunk at
        # the post-chunk step number; cadences/stops round up to the chunk.
        self.steps_per_call = steps_per_call
        # dispatch-runahead bound: keep at most `runahead` step outputs
        # in flight and wait on the OLDEST before dispatching the next
        # call — bounds host runahead (and the HBM held by undonated
        # in-flight buffers) without a per-step sync. 0 = unbounded.
        self.runahead = runahead
        self._inflight: collections.deque = collections.deque()
        # input-stall attribution, cumulative seconds (hooks read these —
        # hooks/builtin.InputPipelineHook): time blocked pulling the next
        # batch, and time blocked on the runahead bound.
        self.feed_wait_s = 0.0
        self.runahead_wait_s = 0.0
        self.initial_step = state.step_int
        self._host_step = self.initial_step  # host mirror of state.step:
        # tracks the global step without a device sync per step
        self._first_step_emitted = False  # first_step journal latch
        # correlated step tracing: every `span_steps` steps, journal one
        # `span` event per phase (input_wait / dispatch / h2d) with the
        # step's host-side timings. The (host, gen, step) triple the
        # journal stamps makes the spans line up across hosts in
        # scripts/fleet_trace.py. 0 = off; timings come from clocks the
        # loop already reads, so the gate costs nothing when idle.
        self.span_steps = int(span_steps)
        self._next_span = (self.initial_step + self.span_steps
                           if self.span_steps else None)
        self._h2d_base = 0

    def request_stop(self, reason: str | None = None) -> None:
        self.stop.request_stop(reason)

    def _emit_spans(self, dt_feed: float, dt_step: float) -> None:
        """One sampled step's phase spans into the journal. `dur_ms`
        spans become chrome-trace complete events (start reconstructed
        as ts - dur); the h2d span has no duration signal — only the
        byte counter from the prefetch ring — so it journals as a
        counter sample and renders as an instant."""
        step = self._host_step
        events.emit("span", name="input_wait", step=step,
                    dur_ms=round(dt_feed * 1e3, 3))
        events.emit("span", name="dispatch", step=step,
                    dur_ms=round(dt_step * 1e3, 3))
        stats_fn = getattr(self.batches, "stats", None)
        if callable(stats_fn):
            h2d = int(stats_fn().get("h2d_bytes", 0))
            base, self._h2d_base = self._h2d_base, h2d
            events.emit("span", name="h2d", step=step,
                        bytes=max(0, h2d - base))

    def _honor_preemption(self) -> None:
        """Consume a preemption notice at a step boundary: persist state
        durably, record `preempted_at`, and stop cleanly — hooks and the
        prefetch worker drain through run()'s normal finally path. The
        reference had no such handshake: SIGTERM mid-step simply killed
        the worker and the next start replayed from the last checkpoint."""
        step = self._host_step
        if self.checkpoint_manager is not None:
            self.checkpoint_manager.save(self.state)
            self.checkpoint_manager.wait()  # durable BEFORE the process exits
        self.preempted_at = step
        log.warning(
            "preemption notice (%s) honored at step boundary %d; "
            "checkpoint %s — stopping cleanly",
            getattr(self.preemption, "reason", None), step,
            "saved" if self.checkpoint_manager is not None else "skipped",
        )
        events.emit(
            "preemption", step=step,
            reason=getattr(self.preemption, "reason", None),
            checkpoint_saved=self.checkpoint_manager is not None,
        )
        if self.health is not None:
            self.health.set("preempted", f"step={step}")
        self.request_stop(f"preempted@step={step}")

    def run(self) -> TrainState:
        for h in self.hooks:
            h.begin(self)
        recoveries = 0
        it = iter(self.batches)
        g = self.goodput
        g.start()
        if self.health is not None:
            self.health.set("training")
        try:
            while not self.stop.should_stop():
                # preemption handshake: consumed only at step boundaries,
                # so the saved checkpoint is always a whole-step state
                if self.preemption is not None and self.preemption.requested():
                    self._honor_preemption()
                    break
                t_feed = time.monotonic()
                try:
                    batch = next(it)
                except StopIteration:
                    self.request_stop("data exhausted")
                    break
                dt_feed = time.monotonic() - t_feed
                self.feed_wait_s += dt_feed
                g.add_stall(dt_feed)
                try:
                    # runahead bound: before dispatching this call, wait on
                    # the OLDEST in-flight output — one wait per step, never
                    # a sync on the step just dispatched
                    if self.runahead and len(self._inflight) >= self.runahead:
                        t_wait = time.monotonic()
                        jax.block_until_ready(self._inflight.popleft())
                        dt_wait = time.monotonic() - t_wait
                        self.runahead_wait_s += dt_wait
                        g.add_stall(dt_wait)
                    # step number BEFORE the step executes == the step being
                    # run; hooks see the post-step number like global_step
                    # reads did after the AssignAdd (§3.3).
                    t_step = time.monotonic()
                    for h in self.hooks:
                        h.before_step(self._host_step)
                    new_state, outputs = self.step_fn(self.state, batch)
                    self.state = new_state
                    self._host_step += self.steps_per_call
                    if self.runahead:
                        self._inflight.append(outputs)
                    # synchronous compile / executable-store load time the
                    # wrapper just spent (train/step._lazy_jit) — charged to
                    # the goodput compile bucket BEFORE after_step fires, so
                    # StartupHook publishes a truthful compile attribution
                    compile_s = 0.0
                    consume = getattr(self.step_fn, "consume_compile_s", None)
                    if consume is not None:
                        compile_s = consume()
                        if compile_s:
                            g.add_compile(compile_s)
                    for h in self.hooks:
                        h.after_step(self._host_step, self.state, outputs)
                    # hook-side checkpoint save time (blocking write on the
                    # sync path, fork+dispatch + attributed stall on the
                    # async snapshot path) — split into the save_s bucket
                    # and OUT of productive, exactly like compile_s
                    save_s = 0.0
                    for h in self.hooks:
                        consume_save = getattr(h, "consume_save_s", None)
                        if consume_save is not None:
                            save_s += consume_save()
                    if save_s:
                        g.add_save(save_s)
                    dt_step = max(0.0, time.monotonic() - t_step - compile_s
                                  - save_s)
                    # per-STEP wall time even when step_fn runs a chunk
                    self.step_time_hist.observe(
                        dt_step * 1e3 / self.steps_per_call)
                    if (self._next_span is not None
                            and self._host_step >= self._next_span):
                        self._next_span = self._host_step + self.span_steps
                        self._emit_spans(dt_feed, dt_step)
                    if g.in_replay:
                        # catching back up to the pre-failure step: correct
                        # work, but no NEW progress — charged to replay, and
                        # to the open recovery event's latency
                        g.note_replay(dt_step, self.steps_per_call,
                                      at_step=self._host_step)
                    else:
                        g.add_productive(dt_step)
                    if not self._first_step_emitted:
                        # one journal mark per process run: closes the
                        # supervisor-level failure->frontier window that
                        # faults.goodput.elastic_summary measures across
                        # generations
                        self._first_step_emitted = True
                        events.emit("first_step", step=self._host_step,
                                    process=jax.process_index())
                except Exception as exc:  # noqa: BLE001 — classified below
                    # in-flight outputs reference pre-failure buffers;
                    # waiting on them after a restore could resurface the
                    # same device error
                    self._inflight.clear()
                    if not (
                        _is_preemption(exc)
                        and self.checkpoint_manager is not None
                        and recoveries < self.max_recoveries
                    ):
                        raise
                    recoveries += 1
                    log.warning(
                        "recoverable failure (%s); restore attempt %d/%d",
                        exc, recoveries, self.max_recoveries,
                    )
                    t_restore = time.monotonic()
                    restored = self.checkpoint_manager.restore(self.state)
                    if restored is None:
                        raise
                    self.state = restored
                    failed_at = self._host_step
                    self._host_step = self.state.step_int
                    # re-seek the input stream to the restored step so the
                    # recovered trajectory equals the uninterrupted one
                    # (batches consumed between checkpoint and failure must
                    # be replayed, not skipped)
                    if hasattr(self.batches, "at_step"):
                        if hasattr(it, "close"):
                            it.close()  # drain a prefetch worker promptly
                        self.batches = self.batches.at_step(self._host_step)
                        it = iter(self.batches)
                    restore_s = time.monotonic() - t_restore
                    g.begin_recovery(
                        failed_at_step=failed_at,
                        restored_step=self._host_step,
                        restore_s=restore_s,
                    )
                    events.emit(
                        "restore", failed_at_step=failed_at,
                        restored_step=self._host_step,
                        restore_ms=round(restore_s * 1e3, 3),
                        recovery=recoveries,
                    )
        finally:
            if self.health is not None and self.health.state != "preempted":
                if sys.exc_info()[0] is not None:
                    self.health.set("failed")
                else:
                    self.health.set("stopped", self.stop.reason)
            g.close()
            self._inflight.clear()
            # generators (incl. DevicePrefetcher streams) drain their
            # resources here — on normal exit AND on an escaping exception
            if hasattr(it, "close"):
                it.close()
            for h in self.hooks:
                try:
                    h.end(self.state)
                except Exception:  # noqa: BLE001 — end() must not mask body
                    log.exception("hook %s.end failed", type(h).__name__)
        return self.state
