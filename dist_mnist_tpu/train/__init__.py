"""Training core: state pytree, compiled SPMD step, hooked loop.

Replaces the reference's session/lifecycle layer (SURVEY.md §2.4): the
entire §3.3 per-step stack (client session -> Master RunStep -> partitioned
executors -> rendezvous RecvTensor) becomes ONE jit-compiled XLA program
(`step.py`), and MonitoredTrainingSession's wrapper/hook machinery becomes
`TrainLoop` (`loop.py`) + the hook protocol (`hooks/`).
"""

from dist_mnist_tpu.train.state import TrainState, create_train_state
from dist_mnist_tpu.train.step import (
    make_train_step,
    make_fused_train_step,
    make_eval_step,
    evaluate,
)
from dist_mnist_tpu.train.loop import TrainLoop, StopSignal

__all__ = [
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_fused_train_step",
    "make_eval_step",
    "evaluate",
    "TrainLoop",
    "StopSignal",
]
