"""The compiled SPMD training step.

This single function replaces the reference's entire per-step distributed
machinery (SURVEY.md §3.3): forward, backward, gradient all-reduce,
optimizer update, and global_step increment are ONE XLA program. The
weight-pull/grad-push that crossed gRPC every step (RecvTensor, worker.h:85)
is the all-reduce XLA inserts over ICI when the batch is sharded on the
`data` mesh axis and params are replicated (GSPMD); with TP rules the same
mechanism inserts the Megatron reduce in the matmuls. No hand-written
collectives needed on this path — parallel/collectives.py has the explicit
shard_map variant for cases that want manual control.
"""

from __future__ import annotations

from typing import Callable

import chex
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dist_mnist_tpu.data.pipeline import batch_sharding
from dist_mnist_tpu.ops import losses, metrics
from dist_mnist_tpu.optim.base import Optimizer, apply_updates, global_norm
from dist_mnist_tpu.parallel.sharding import ShardingRules, DP_RULES, tree_sharding
from dist_mnist_tpu.train.state import TrainState

LossFn = Callable[..., jax.Array]

# Named rematerialization policies (`Config.remat_policy`). All are
# numerically identical — they trade backward-pass recompute FLOPs against
# activation HBM differently:
#   dots_no_batch  save weight-matmul outputs, recompute BATCHED dots (the
#                  O(S^2) attention score/apply einsums) — the flash-style
#                  default; lowest memory of the dot-saving family
#   save_attn      dots_no_batch PLUS the tensors tagged
#                  `checkpoint_name("attn_out")` (the per-block attention
#                  context, ops/nn.py + models/vit.py) — stops recomputing
#                  the whole O(S^2) chain at the cost of one [B,S,D] save
#                  per block; the ViT-MFU attribution's candidate fix
#   dots           save ALL dot outputs incl. batched (scores+apply saved)
#   nothing        recompute everything (maximum memory savings)
REMAT_POLICIES = {
    "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "save_attn": jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        jax.checkpoint_policies.save_only_these_names("attn_out"),
    ),
    "dots": jax.checkpoint_policies.dots_saveable,
    "nothing": jax.checkpoint_policies.nothing_saveable,
}


def resolve_remat_policy(name: str):
    if name not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat_policy {name!r}; use one of "
            f"{sorted(REMAT_POLICIES)}"
        )
    return REMAT_POLICIES[name]


def model_aux_loss(model_state):
    """THE aux-objective contract: any top-level SCALAR entry of
    `model_state` whose key ends in ``_aux`` (e.g. the MoE load-balance
    term ``moe_aux``, models/vit.py) is an auxiliary loss the model wants
    added to the training objective, already weighted by the model. Every
    step implementation (the GSPMD core here AND
    parallel/collectives.make_explicit_dp_step) sums aux terms through
    this one helper so the objectives cannot silently diverge. Returns
    None when there are none."""
    if not isinstance(model_state, dict):
        return None
    terms = [v for k, v in model_state.items()
             if k.endswith("_aux") and getattr(v, "ndim", None) == 0]
    return sum(terms[1:], terms[0]) if terms else None


def _train_core(model, optimizer, loss_fn, state: TrainState, batch,
                dropout_key, *, with_grad_norm: bool = False,
                remat: bool = False, augment: bool = False,
                remat_policy: str = "dots_no_batch", param_gather=None):
    """The shared fwd+bwd+update body every step variant compiles.

    `remat=True` wraps the forward in `jax.checkpoint`: activations are
    recomputed in the backward pass instead of living in HBM across it —
    the FLOPs-for-bandwidth trade deep models need to fit a chip (e.g. ViT
    on long token sequences). `remat_policy` selects WHAT is saved vs
    recomputed (REMAT_POLICIES above); the default recomputes the batched
    attention dots, `save_attn` keeps them.

    `param_gather` (parallel/overlap.build_param_gather) is the explicit
    fsdp gather boundary: a value-level identity that bucket-gathers the
    sharded params ahead of use and flushes grad reduce-scatters per bucket
    in its custom backward. It must run INSIDE the loss closure — under
    `value_and_grad` — so the backward owns the flush schedule; None keeps
    GSPMD's implicit gather-on-use (bit-identical either way).
    """
    # Structural guards (SURVEY.md §5.2): trace-time only — zero runtime
    # cost under jit. The reference's analogue was graph finalization +
    # the accumulator's staleness check; in a pure program the remaining
    # race class is feeding a malformed batch.
    chex.assert_rank(batch["image"], 4)  # NHWC
    chex.assert_rank(batch["label"], 1)
    chex.assert_type(batch["label"], int)
    chex.assert_equal_shape_prefix([batch["image"], batch["label"]], 1)
    img = batch["image"]
    if augment:
        # on the sharded uint8 batch, inside jit: each device augments its
        # own slice, zero host work (data/augment.py)
        from dist_mnist_tpu.data.augment import random_crop_flip

        aug_key, dropout_key = jax.random.split(dropout_key)
        img = random_crop_flip(aug_key, img)
    x = img.astype(jnp.float32) / 255.0
    y = batch["label"]

    def forward(params, model_state, xb):
        return model.apply(params, model_state, xb, train=True,
                           rng=dropout_key)

    if remat:
        forward = jax.checkpoint(
            forward, policy=resolve_remat_policy(remat_policy)
        )

    def loss_of(params):
        if param_gather is not None:
            params = param_gather(params)
        logits, new_model_state = forward(params, state.model_state, x)
        loss = loss_fn(logits, y)
        # auxiliary objectives the model emits ride in model_state and
        # join the loss HERE, inside the grad (contract: model_aux_loss)
        aux = model_aux_loss(new_model_state)
        if aux is not None:
            loss = loss + aux
        return loss, (logits, new_model_state)

    (loss, (logits, new_model_state)), grads = jax.value_and_grad(
        loss_of, has_aux=True
    )(state.params)
    updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
    new_state = TrainState(
        step=state.step + 1,
        params=apply_updates(state.params, updates),
        model_state=new_model_state,
        opt_state=new_opt_state,
        rng=state.rng,
    )
    out = {
        "loss": loss.astype(jnp.float32),
        "accuracy": metrics.accuracy(logits, y),
    }
    # Metric contract (sibling of the `_aux` contract above): any top-level
    # `_metric` entry of model_state is a health statistic the model wants
    # SURFACED, not optimized — e.g. MoE dropped-token fraction / per-expert
    # load (parallel/moe.py). Copied into the step outputs (suffix
    # stripped), where LoggingHook prints them and SummaryHook histograms
    # the vector-valued ones for free.
    if isinstance(new_model_state, dict):
        for k, v in new_model_state.items():
            if k.endswith("_metric"):
                out[k[: -len("_metric")]] = v
    if with_grad_norm:
        out["grad_norm"] = global_norm(grads)
        # per-leaf norms as ONE vector: SummaryHook histograms it (the
        # grad-distribution summary the reference wrote as histogram protos)
        out["grad_norms"] = jnp.stack(
            [jnp.linalg.norm(g.ravel()) for g in jax.tree.leaves(grads)]
        )
    return new_state, out


def _fused_one_step(model, optimizer, loss_fn, device_dataset, batch_size,
                    remat: bool = False, augment: bool = False,
                    remat_policy: str = "dots_no_batch", param_gather=None):
    """One step with batch sampling inside the program (fused-input body).
    The resident dataset arrays arrive as EXPLICIT args (`data`), never as
    closed-over constants — a multi-process global array may not be
    captured by a jit (it spans non-addressable devices)."""

    def one_step(state: TrainState, data):
        images, labels = data
        sample_key, dropout_key = jax.random.split(
            jax.random.fold_in(state.rng, state.step)
        )
        batch = device_dataset.sample_arrays(sample_key, batch_size,
                                             images, labels)
        return _train_core(model, optimizer, loss_fn, state, batch,
                           dropout_key, remat=remat, augment=augment,
                           remat_policy=remat_policy,
                           param_gather=param_gather)

    return one_step


def _lazy_jit(step, mesh, rules, donate, n_args=1, bound_data=None,
              store=None, key=None):
    """jit on first call, deriving state shardings from the live state.

    `bound_data`: resident arrays (e.g. a DeviceDataset's) passed as the
    step's second argument ON EVERY CALL, with their own shardings — an
    explicit arg, never a closed-over constant, because a multi-process
    global array may not be captured by a jit (it spans non-addressable
    devices). Callers of the returned wrapper then pass only `state`.

    `store` + `key` (compilecache/store.py) switch the first call to the
    WARM-START path: AOT-compile (`lower(...).compile()`), trying the
    executable store first — a prior process's serialized executable
    deserializes in milliseconds where a cold compile costs seconds — and
    saving after a fresh compile so the next process warm-starts. The
    wrapper records the outcome in `wrapper.cache_stats` (tier
    disk|fresh, load/compile ms) and surfaces the synchronous
    compile-or-load seconds through `wrapper.consume_compile_s()` for the
    loop's goodput/startup attribution. Without a store the jit stays
    lazy and shape-polymorphic, exactly as before.
    """
    import time as _time

    compiled: dict = {}
    #: warm-start outcome of the first call; tier None until then
    cache_stats: dict = {"tier": None, "compile_ms": 0.0, "load_ms": 0.0,
                         "key": key}
    _pending_compile_s = [0.0]

    def _args(rest):
        return (bound_data,) if bound_data is not None else rest

    def _ensure_jit(state, rest=()):
        if "fn" in compiled:
            return
        shd = tree_sharding(state, mesh, rules)
        if bound_data is not None:
            extra_shd = (tuple(a.sharding for a in bound_data),)
        elif n_args == 2:
            extra_shd = ({"image": batch_sharding(mesh),
                          "label": batch_sharding(mesh)},)
        else:
            extra_shd = ()
        jitted = jax.jit(
            step, in_shardings=(shd,) + extra_shd,
            out_shardings=(shd, None),
            donate_argnums=(0,) if donate else (),
        )
        if store is None or key is None:
            compiled["fn"] = jitted
            return
        t0 = _time.perf_counter()
        exe = store.load(key)
        if exe is not None:
            dt = _time.perf_counter() - t0
            compiled["fn"], compiled["aot"] = exe, True
            cache_stats.update(tier="disk", load_ms=dt * 1e3)
            _pending_compile_s[0] += dt
            return
        exe = jitted.lower(state, *_args(rest)).compile()
        dt = _time.perf_counter() - t0
        compiled["fn"], compiled["aot"] = exe, True
        cache_stats.update(tier="fresh", compile_ms=dt * 1e3)
        _pending_compile_s[0] += dt
        store.save(key, exe, meta={"compile_ms": dt * 1e3})

    def _aot_or_lowered(state, rest):
        """A Compiled for the analysis helpers: the AOT executable when the
        warm-start path built one, else lower+compile (hits XLA's cache
        when the step has already run)."""
        _ensure_jit(state, rest)
        if compiled.get("aot"):
            return compiled["fn"]
        return compiled["fn"].lower(state, *_args(rest)).compile()

    def wrapper(state, *rest):
        _ensure_jit(state, rest)
        return compiled["fn"](state, *_args(rest))

    def consume_compile_s() -> float:
        """Synchronous compile-or-load seconds accumulated since the last
        call — the loop drains this into the goodput `compile` bucket."""
        s, _pending_compile_s[0] = _pending_compile_s[0], 0.0
        return s

    def cost_analysis(state, *rest):
        """XLA's cost analysis (flops, bytes accessed) for ONE invocation —
        the MFU numerator (utils/flops.py). lower+compile only (never
        EXECUTES, so donated-buffer steps are safe to query before the
        first real call); hits XLA's compilation cache when the step has
        already run. Pass any args with the right shapes/shardings (e.g.
        the step's own output state). None when the backend has no cost
        model."""
        try:
            ca = _aot_or_lowered(state, rest).cost_analysis()
        except Exception:  # noqa: BLE001 — metrics aid, never fail a run
            return None
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else None
        return ca

    def memory_analysis(state, *rest):
        """XLA's compiled-memory analysis for ONE invocation — per-device
        peak / argument / output / temp bytes, the HBM side of the
        attribution story (`bench.py --memory`). Same lower+compile-only
        contract as `cost_analysis`: never executes, safe before the first
        donated call, None when the backend doesn't report it."""
        try:
            return _aot_or_lowered(state, rest).memory_analysis()
        except Exception:  # noqa: BLE001 — metrics aid, never fail a run
            return None

    def compiled_text(state, *rest):
        """Compiled HLO text of the step (post-GSPMD), for tests that
        assert WHICH collectives the partitioner inserted (e.g. fsdp must
        show an all-gather on param use; dp must not). None when the
        backend can't render it."""
        try:
            return _aot_or_lowered(state, rest).as_text()
        except Exception:  # noqa: BLE001
            return None

    wrapper.cost_analysis = cost_analysis
    wrapper.memory_analysis = memory_analysis
    wrapper.compiled_text = compiled_text
    wrapper.cache_stats = cache_stats
    wrapper.consume_compile_s = consume_compile_s
    return wrapper


def _overlap_gather(mesh, rules, overlap):
    """OverlapConfig -> param-gather callable (None passes through).
    Validation (overlap needs an fsdp rule set) happens HERE, at step-build
    time — before any compile or data work."""
    if overlap is None:
        return None
    from dist_mnist_tpu.parallel.overlap import build_param_gather

    return build_param_gather(mesh, rules, overlap)


def make_train_step(
    model,
    optimizer: Optimizer,
    mesh: Mesh,
    *,
    loss_fn: LossFn = losses.softmax_cross_entropy,
    rules: ShardingRules = DP_RULES,
    donate: bool = True,
    with_grad_norm: bool = False,
    remat: bool = False,
    augment: bool = False,
    remat_policy: str = "dots_no_batch",
    overlap=None,
    store=None,
    cache_key: str | None = None,
):
    """Build `step(state, batch) -> (state, metrics)` jitted over `mesh`.

    - `donate=True` aliases the input state's buffers into the output
      (in-place param update in HBM — the analogue of the reference's
      mutable PS variables, without the mutation).
    - batch["image"] is uint8 NHWC sharded on `data`; normalization to
      [0,1] f32 runs on-device post-shard (4x less host->device traffic).
    - `overlap` (parallel/overlap.OverlapConfig): explicit bucketed fsdp
      param-gather/grad-flush schedule (needs `rules` with an fsdp_axis);
      None = GSPMD's implicit gather-on-use. Bit-identical trajectories
      either way.
    - `store`/`cache_key` (compilecache/): warm-start from a serialized
      AOT executable when a prior process saved one under this key.
    """
    gather = _overlap_gather(mesh, rules, overlap)

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        dropout_key = jax.random.fold_in(state.rng, state.step)
        return _train_core(model, optimizer, loss_fn, state, batch,
                           dropout_key, with_grad_norm=with_grad_norm,
                           remat=remat, augment=augment,
                           remat_policy=remat_policy, param_gather=gather)

    return _lazy_jit(step, mesh, rules, donate, n_args=2,
                     store=store, key=cache_key)


def make_fused_train_step(
    model,
    optimizer: Optimizer,
    mesh: Mesh,
    device_dataset,
    batch_size: int,
    *,
    loss_fn: LossFn = losses.softmax_cross_entropy,
    rules: ShardingRules = DP_RULES,
    remat: bool = False,
    augment: bool = False,
    remat_policy: str = "dots_no_batch",
    overlap=None,
    store=None,
    cache_key: str | None = None,
):
    """`step(state) -> (state, metrics)` with BATCH SAMPLING INSIDE the
    compiled program (data/pipeline.DeviceDataset): the host does zero
    per-step work — no feed_dict, no device_put, no gRPC anything (§3.3's
    entire per-step wire traffic is gone, not just moved). This is the
    bench-path step; semantics = with-replacement sampling (vs the hooked
    loop's shuffled epochs)."""
    one_step = _fused_one_step(model, optimizer, loss_fn, device_dataset,
                               batch_size, remat=remat, augment=augment,
                               remat_policy=remat_policy,
                               param_gather=_overlap_gather(mesh, rules,
                                                            overlap))
    return _lazy_jit(one_step, mesh, rules, donate=True,
                     bound_data=device_dataset.arrays,
                     store=store, key=cache_key)


def make_scanned_train_fn(
    model,
    optimizer: Optimizer,
    mesh: Mesh,
    device_dataset,
    batch_size: int,
    chunk: int,
    *,
    loss_fn: LossFn = losses.softmax_cross_entropy,
    rules: ShardingRules = DP_RULES,
    remat: bool = False,
    augment: bool = False,
    remat_policy: str = "dots_no_batch",
    overlap=None,
    store=None,
    cache_key: str | None = None,
):
    """`run(state) -> (state, metrics)` executing `chunk` fused steps in ONE
    XLA program via `lax.scan` — zero per-step Python dispatch, the
    logical endpoint of collapsing §3.3's per-step client->master->worker
    round-trip: not even a host->device command per step remains. Metrics
    are the mean over the chunk. Small models are dispatch-bound in the
    per-step loop; this removes that ceiling."""

    one_step = _fused_one_step(model, optimizer, loss_fn, device_dataset,
                               batch_size, remat=remat, augment=augment,
                               remat_policy=remat_policy,
                               param_gather=_overlap_gather(mesh, rules,
                                                            overlap))

    def run_chunk(state: TrainState, data):
        state, outs = jax.lax.scan(
            lambda s, _: one_step(s, data), state, None, length=chunk
        )
        return state, jax.tree.map(jnp.mean, outs)

    return _lazy_jit(run_chunk, mesh, rules, donate=True,
                     bound_data=device_dataset.arrays,
                     store=store, key=cache_key)


def make_eval_step(model, mesh: Mesh, *, store=None, cache_key: str | None = None):
    """`eval_step(state, batch) -> (sum_loss, correct_count, n)` — summable
    partial results so full-test-set eval streams in fixed-size batches.

    Lazily jitted against `mesh`: state in_shardings are read off the LIVE
    state's own placements on the first call, and the batch is pinned to
    the mesh's `data` sharding. A bare `@jax.jit` here silently RESHARDED
    a TP/FSDP-sharded state to replicated for eval — an all-gather of
    params+slots per eval batch, defeating resident sharding exactly when
    memory headroom matters.

    `store`/`cache_key` (compilecache/): like the train step, the first
    call AOT-compiles and round-trips the executable store so restarts
    skip the eval compile too. Eval batches keep one shape (evaluate()
    pads the tail), so pinning to the first call's shape loses nothing."""

    compiled: dict = {}

    def _eval_core(state: TrainState, batch):
        x = batch["image"].astype(jnp.float32) / 255.0
        y = batch["label"]
        logits, _ = model.apply(state.params, state.model_state, x, train=False)
        # Padding rows carry label -1: one_hot(-1) is the zero row, so their
        # loss contribution is exactly 0, and argmax (>=0) never equals -1,
        # so they count 0 correct. n counts only real rows.
        loss_sum = losses.softmax_cross_entropy(logits, y, reduction="sum")
        correct = metrics.correct_count(logits, y)
        n = jnp.sum((y >= 0).astype(jnp.int32))
        return loss_sum, correct, n

    def eval_step(state: TrainState, batch):
        if "fn" not in compiled:
            state_shd = jax.tree.map(
                lambda x: getattr(x, "sharding", None), state
            )
            batch_shd = {"image": batch_sharding(mesh),
                         "label": batch_sharding(mesh)}
            compiled["shardings"] = (state_shd, batch_shd)
            jitted = jax.jit(
                _eval_core, in_shardings=(state_shd, batch_shd)
            )
            if store is not None and cache_key is not None:
                exe = store.load(cache_key)
                if exe is None:
                    import time as _time

                    t0 = _time.perf_counter()
                    exe = jitted.lower(state, batch).compile()
                    store.save(cache_key, exe, meta={
                        "compile_ms": (_time.perf_counter() - t0) * 1e3})
                compiled["fn"] = exe
            else:
                compiled["fn"] = jitted
        return compiled["fn"](state, batch)

    # For tests: the (state, batch) in_shardings captured at first call,
    # or None before it.
    eval_step.captured_shardings = lambda: compiled.get("shardings")
    return eval_step


def evaluate(eval_step, state, images, labels, mesh: Mesh, batch_size: int = 1000):
    """Full-dataset eval: pads to a batch multiple, masks the padding.

    The per-batch partials STAY ON DEVICE (tiny async scalar adds) and are
    fetched with ONE `device_get` at the end — the per-batch `float()` sync
    was a host round-trip per batch (~8 ms each on the axon relay), the
    exact cost the fused step engineered away (VERDICT r3 weak 8)."""
    import numpy as np

    from dist_mnist_tpu.cluster.mesh import DATA_AXIS
    from dist_mnist_tpu.data.pipeline import shard_batch

    data_axis = mesh.shape[DATA_AXIS]
    n_proc, pid = jax.process_count(), jax.process_index()
    quantum = np.lcm(data_axis, n_proc)
    batch_size = ((batch_size + quantum - 1) // quantum) * quantum
    local_bs = batch_size // n_proc
    n = images.shape[0]
    totals = None  # (loss_sum, correct, n) device scalars, accumulated async
    for i in range(0, n, batch_size):
        img = images[i : i + batch_size]
        lab = labels[i : i + batch_size]
        if img.shape[0] < batch_size:  # pad tail; label -1 marks padding
            pad = batch_size - img.shape[0]
            img = np.concatenate([img, np.zeros((pad, *img.shape[1:]), img.dtype)])
            lab = np.concatenate([lab, np.full((pad,), -1, lab.dtype)])
        # shard_batch expects each process's LOCAL slice of the global batch
        img = img[pid * local_bs : (pid + 1) * local_bs]
        lab = lab[pid * local_bs : (pid + 1) * local_bs]
        batch = shard_batch({"image": img, "label": lab}, mesh)
        part = eval_step(state, batch)
        totals = part if totals is None else tuple(
            t + p for t, p in zip(totals, part)
        )
    # lint: ok[host-sync] the ONE batched end-of-eval fetch the docstring promises
    total_loss, total_correct, total_n = jax.device_get(totals)
    return {
        "loss": float(total_loss) / int(total_n),  # lint: ok[host-sync] numpy scalar math post-fetch
        "accuracy": int(total_correct) / int(total_n),
        "n": int(total_n),
    }
