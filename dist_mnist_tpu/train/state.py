"""TrainState — the single pytree that *is* the training job's state.

Subsumes what the reference scattered across processes: PS-resident
variables + optimizer slots (SURVEY.md §2.3 rows 6-8), the global_step
variable (§2.4 row 20, training_util.py:165-255), and per-worker RNG.
Checkpointing this one pytree (checkpoint/manager.py) replaces Saver's
graph-embedded SaveV2/RestoreV2 of the same set (§2.4 row 19).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array  # int32 scalar — the global_step (§2.4 row 20)
    params: Any  # f32 master weights
    model_state: Any  # BN running stats etc.; {} for stateless models
    opt_state: Any  # optimizer slots (Adam m/v + count)
    rng: jax.Array  # base PRNG key; per-step keys are fold_in(rng, step)

    @property
    def step_int(self) -> int:
        # every caller is a cold path (checkpoint save, restore seek, log)
        # lint: ok[host-sync] one explicit scalar fetch on those cold paths
        return int(jax.device_get(self.step))


def _per_device_nbytes(leaf) -> int:
    """Bytes ONE device holds for `leaf` — its shard, not the global array.

    Computed from `sharding.shard_shape` (pure metadata: no transfer, no
    sync), so it is exact for any placement: a replicated leaf costs its
    full nbytes per device, an FSDP leaf 1/axis-size of it."""
    if not isinstance(leaf, jax.Array):
        return 0
    try:
        shard_shape = leaf.sharding.shard_shape(leaf.shape)
    except Exception:  # committed-elsewhere / abstract: fall back to global
        shard_shape = leaf.shape
    n = 1
    for d in shard_shape:
        n *= d
    return n * leaf.dtype.itemsize


def state_memory_bytes(state: TrainState) -> dict:
    """Per-device resident-state HBM attribution (the `bench.py --memory` /
    MemoryHook number): bytes one device holds for params, optimizer slots,
    and model_state under the state's ACTUAL shardings. This is the
    quantity ZeRO/FSDP shrinks — under `dp` every chip holds full replicas
    (params + 2x Adam slots), under `fsdp` 1/data-th of each sharded leaf."""
    out = {
        "param_bytes": sum(_per_device_nbytes(x)
                           for x in jax.tree.leaves(state.params)),
        "opt_state_bytes": sum(_per_device_nbytes(x)
                               for x in jax.tree.leaves(state.opt_state)),
        "model_state_bytes": sum(_per_device_nbytes(x)
                                 for x in jax.tree.leaves(state.model_state)),
    }
    out["total_bytes"] = sum(out.values())
    return out


def create_train_state(model, optimizer, rng: jax.Array, sample_input) -> TrainState:
    """Build the initial state. Unlike the reference — where ONLY the chief
    ran init_op and workers blocked in wait_for_session (§3.2,
    session_manager.py:259,419) — every process derives identical initial
    params from the same seed; there is nothing to wait for."""
    init_key, loop_key = jax.random.split(rng)
    params, model_state = model.init(init_key, sample_input)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        model_state=model_state,
        opt_state=optimizer.init(params),
        rng=loop_key,
    )
