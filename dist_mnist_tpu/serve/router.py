"""Fleet router: N `InferenceServer` replicas behind one `submit()` that
survives a replica kill, a slow replica, and a live weight rollout.

A single `InferenceServer` is one process — one admission queue, one
batcher, one set of weights; any crash is a full outage and any weight
update is downtime. The router puts the serving SLO above replicas the
way the elastic supervisor puts the training run above hosts
(docs/RESILIENCE.md): individual replicas are expendable, the fleet's
latency-sensitive tier is not.

Three mechanisms, layered on the per-replica contracts that already
exist (health states, quiesce, the admission error types):

1. **SLO-tiered admission.** Every request carries a class —
   `latency_sensitive` or `best_effort`. Under backlog the router sheds
   best-effort FIRST (a structured `ShedError` at submit, before any
   replica queue sees the request): best-effort sheds at a configurable
   backlog fraction and when its own deadline is hopeless against the
   currently observed latency; latency-sensitive sheds only when every
   queue is full. Rejecting cheap traffic early is what keeps the
   expensive tier's p99 flat through an incident.

2. **Replica lifecycle robustness.** Routing is least-loaded over
   replicas a health probe (and the error stream) says are serving; a
   `draining` replica stops receiving new work but finishes its queue.
   Failed attempts are classified TYPE-FIRST (serve/errors.py):
   retryables back off exponentially and try again (deadline-bounded),
   replica-fatal errors mark the replica down and requeue the in-flight
   request on a live replica immediately. Latency-sensitive requests
   additionally hedge: once enough samples exist, a duplicate attempt is
   dispatched to a second replica after the observed-p99-derived timeout,
   the first result wins, and the loser is withdrawn (admission
   cancel_event — a queued loser never occupies a batch slot). Request
   ids guard completion: exactly one result per request reaches the
   client, no matter how many attempts raced.

3. **Zero-downtime weight hot-swap.** A `CheckpointWatcher` polls the
   training run's commit markers (checkpoint/manager.py
   `commits/<step>.committed` — the only steps safe to serve) and rolls
   the fleet replica-by-replica: drain (stop routing, quiesce the
   pipeline so in-flight requests finish on the old weights) -> swap
   (`InferenceEngine.swap_weights`: a device_put, never a compile) ->
   rewarm (memory-tier cache hits; a restarted replica's disk tier keeps
   it in load-not-compile time) -> serve. One replica swaps while the
   rest carry traffic, so the roll drops nothing.

4. **Replica membership as a control variable.** `add_replica` /
   `remove_replica` let a controller (serve/autoscale.py) grow and
   shrink the fleet under traffic: admission is gated on a warm-up
   probe (a ``starting`` view is never routed to), removal drains via
   the same quiesce machinery the weight roll uses, and every internal
   walk of the view set snapshots under the lock, so churn is safe
   against probes, timers, and rolls in flight.

Thread inventory (all named ``Router*`` for the conftest leak-check, all
joined by `close()`): RouterHealth (probe loop), RouterTimer (retry
backoff + hedge timers), RouterWatcher (commit-marker poll),
RouterHttp-* (HTTP replica transport pool).
"""

from __future__ import annotations

import dataclasses
import heapq
import io
import itertools
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future, ThreadPoolExecutor
from pathlib import Path

import numpy as np

from dist_mnist_tpu.obs import events
from dist_mnist_tpu.obs.hist import StreamingHistogram
from dist_mnist_tpu.serve.admission import (
    DeadlineExceededError,
    InferenceResult,
    QueueFullError,
    ShuttingDownError,
)
from dist_mnist_tpu.serve.errors import (
    REPLICA_FATAL,
    RETRYABLE,
    TERMINAL,
    AllReplicasDownError,
    ReplicaKilledError,
    ShedError,
    classify_failure,
)

log = logging.getLogger(__name__)

LATENCY_SENSITIVE = "latency_sensitive"
BEST_EFFORT = "best_effort"
REQUEST_CLASSES = (LATENCY_SENSITIVE, BEST_EFFORT)

#: What each request class optimizes for on the DECODE path
#: (serve/decode.py): the same two classes the router sheds by map onto
#: autoregressive SLOs — latency_sensitive requests jump the admission
#: queue to minimize time-to-first-token, best_effort requests ride the
#: in-flight batch for per-token throughput. Keyed here, beside the
#: class constants, so the router and the decode scheduler can never
#: disagree about what a class means.
DECODE_SLO_TARGETS = {
    LATENCY_SENSITIVE: "ttft_ms",
    BEST_EFFORT: "tokens_per_s",
}

# conftest leak registry: every started-but-unclosed router is a leak (its
# health/timer threads would outlive the test).
_LIVE_ROUTERS: list = []


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    # -- tiered shedding ----------------------------------------------------
    #: backlog fraction (queued+inflight over total capacity of serving
    #: replicas) at which best_effort submits shed
    be_shed_at: float = 0.5
    #: latency_sensitive sheds only when effectively every queue is full
    ls_shed_at: float = 1.0
    #: above this fraction, a best_effort deadline shorter than the observed
    #: p50 latency is hopeless and sheds immediately (deadline-aware tier)
    deadline_guard_at: float = 0.25
    # -- retry / failover ---------------------------------------------------
    retry_max_attempts: int = 4
    retry_base_ms: float = 2.0
    retry_max_ms: float = 50.0
    # -- hedging ------------------------------------------------------------
    #: fixed hedge timeout; None = derive from the live latency_sensitive
    #: p99 once `hedge_min_samples` completions exist (disabled before that)
    hedge_after_ms: float | None = None
    hedge_min_samples: int = 50
    hedge_floor_ms: float = 5.0
    # -- lifecycle ----------------------------------------------------------
    health_interval_s: float = 0.2
    swap_quiesce_timeout_s: float = 30.0


class RouterMetrics:
    """Thread-safe fleet-level accounting: per-class counters + latency
    ladders, retry/hedge/failover counters, and the replica_down ->
    first-rerouted-response recovery samples."""

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = {c: 0 for c in REQUEST_CLASSES}
        self.completed = {c: 0 for c in REQUEST_CLASSES}
        self.shed = {c: 0 for c in REQUEST_CLASSES}
        self.failed = {c: 0 for c in REQUEST_CLASSES}
        self.retries = 0
        self.requeues = 0
        self.hedges = 0
        self.hedge_losses = 0
        self.replica_downs = 0
        self.replica_ups = 0
        self.replica_drains = 0
        self.replica_adds = 0
        self.replica_removes = 0
        self.swaps = 0
        self.swap_failures = 0
        self.latency_ms = {c: StreamingHistogram() for c in REQUEST_CLASSES}
        self.recovery_ms: list[float] = []

    def attach_to(self, registry) -> None:
        """Expose the live per-class ladders on a MetricRegistry; the
        `fleet/` prefix matches PR 9's cross-host series so one /metrics
        scrape shows training and serving fleet views side by side."""
        for cls in REQUEST_CLASSES:
            registry.attach_histogram(f"fleet/latency_ms_{cls}",
                                      self.latency_ms[cls])

    def record_submitted(self, cls: str) -> None:
        with self._lock:
            self.submitted[cls] += 1

    def record_completed(self, cls: str, latency_ms: float) -> None:
        self.latency_ms[cls].observe(latency_ms)
        with self._lock:
            self.completed[cls] += 1

    def record_shed(self, cls: str) -> None:
        with self._lock:
            self.shed[cls] += 1

    def record_failed(self, cls: str) -> None:
        with self._lock:
            self.failed[cls] += 1

    def record_recovery(self, ms: float) -> None:
        with self._lock:
            self.recovery_ms.append(ms)

    def bump(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def latency_pct(self, cls: str, pct: str) -> float | None:
        s = self.latency_ms[cls].snapshot()
        return s[pct] if s["count"] else None

    def observed_p50_ms(self) -> float | None:
        """Merged-class p50 — the shed policy's 'what latency should a
        request expect right now' estimate."""
        merged = StreamingHistogram()
        for h in self.latency_ms.values():
            merged.merge(h)
        s = merged.snapshot()
        return s["p50"] if s["count"] else None

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "submitted": dict(self.submitted),
                "completed": dict(self.completed),
                "shed": dict(self.shed),
                "failed": dict(self.failed),
                "retries": self.retries,
                "requeues": self.requeues,
                "hedges": self.hedges,
                "hedge_losses": self.hedge_losses,
                "replica_downs": self.replica_downs,
                "replica_ups": self.replica_ups,
                "replica_drains": self.replica_drains,
                "replica_adds": self.replica_adds,
                "replica_removes": self.replica_removes,
                "swaps": self.swaps,
                "swap_failures": self.swap_failures,
                "recovery_ms": [round(v, 3) for v in self.recovery_ms],
            }
        for cls in REQUEST_CLASSES:
            s = self.latency_ms[cls].snapshot()
            out[f"latency_{cls}"] = (
                {"p50_ms": s["p50"], "p95_ms": s["p95"], "p99_ms": s["p99"],
                 "mean_ms": s["mean"], "count": s["count"]}
                if s["count"] else {"count": 0}
            )
        return out


# -- replica handles ----------------------------------------------------------


class InProcessReplica:
    """One in-process `InferenceServer` replica with restart and hot-swap.

    `make_server` is a zero-arg factory returning a STARTED (or startable)
    InferenceServer — the factory, not a server instance, so `restart()`
    can rebuild the whole replica (fresh engine, fresh batcher thread)
    after a kill; a shared `CompiledModelCache` / disk store inside the
    factory keeps that restart in load-not-compile time. `load_weights`
    (step -> (params, model_state)) is the hot-swap source, typically a
    `load_for_serving` closure over the training run's checkpoint dir.
    """

    def __init__(self, replica_id: int, make_server, *, load_weights=None):
        self.id = replica_id
        self._make = make_server
        self._load = load_weights
        #: bumped by restart(); a router clears a down-mark only when it
        #: sees a HIGHER generation (a dead engine can still probe healthy)
        self.generation = 0
        self.server = None

    def start(self) -> "InProcessReplica":
        if self.server is None:
            self.server = self._make()
            if not self.server._started:
                self.server.start()
        return self

    def submit(self, image, *, deadline_ms=None, cancel_event=None):
        if self.server is None:
            raise ReplicaKilledError(f"replica {self.id} is not running")
        return self.server.submit(image, deadline_ms=deadline_ms,
                                  cancel_event=cancel_event)

    @property
    def queue_depth(self) -> int:
        return self.server.queue_depth if self.server is not None else 0

    @property
    def capacity(self) -> int:
        return self.server.capacity if self.server is not None else 0

    def probe(self) -> dict:
        if self.server is None:
            return {"state": "stopped", "healthy": False,
                    "generation": self.generation}
        h = self.server.health
        if h is not None:
            snap = h.snapshot()
            return {"state": snap["state"], "healthy": snap["healthy"],
                    "generation": self.generation}
        state = ("stopped" if self.server._closed
                 else "serving" if self.server._started else "starting")
        return {"state": state, "healthy": state == "serving",
                "generation": self.generation}

    def quiesce(self, timeout: float = 30.0) -> bool:
        return self.server.quiesce(timeout=timeout)

    def swap_to(self, step: int) -> None:
        if self._load is None:
            raise RuntimeError(f"replica {self.id} has no weight loader")
        params, model_state = self._load(step)
        self.server.engine.swap_weights(params, model_state, version=step)

    def rewarm(self) -> float:
        """Re-touch every served grid cell post-swap; returns wall ms. Pure
        memory-tier hits for a live engine (executables survive the swap);
        the disk tier covers a restarted one. On a zoo engine (serve/zoo.py)
        prewarm defaults its heights to the full sequence grid, so this
        walks the whole 2-D (batch, height) grid, not just batch buckets."""
        t0 = time.perf_counter()
        eng = self.server.engine
        eng.prewarm([b for b in eng.buckets()
                     if b <= max(self.server.config.max_batch,
                                 eng.min_bucket)])
        return (time.perf_counter() - t0) * 1e3

    def restart(self) -> "InProcessReplica":
        old, self.server = self.server, None
        if old is not None:
            try:
                old.close(timeout=5.0)
            except Exception:  # noqa: BLE001 — a dead server may not close cleanly
                log.warning("replica %d: close of old server failed", self.id,
                            exc_info=True)
        self.server = self._make()
        if not self.server._started:
            self.server.start()
        self.generation += 1
        return self

    def close(self, timeout: float = 30.0) -> bool:
        if self.server is None:
            return True
        return self.server.close(timeout=timeout)


def _error_from_http(code: int, body: bytes) -> Exception:
    """Reconstruct the TYPED replica error from an HTTP status + JSON body
    so classify_failure treats remote replicas exactly like local ones."""
    try:
        payload = json.loads(body)
    except Exception:  # noqa: BLE001
        payload = {}
    msg = payload.get("message", f"replica returned HTTP {code}")
    if code == 429:
        return QueueFullError(msg)
    if code == 503:
        return ShuttingDownError(msg)
    if code == 504:
        return DeadlineExceededError(msg)
    if payload.get("error") == "ReplicaKilledError":
        return ReplicaKilledError(msg)
    return RuntimeError(msg)


class HttpReplica:
    """Replica handle over HTTP: one `cli/serve.py --serve_forever` process
    exposing POST /predict and /swap next to /healthz + /metrics
    (obs/exporter.py). The data plane is a small thread pool turning each
    submit into a blocking POST; connection-level failures surface as
    OSErrors, which classify as REPLICA_FATAL — a vanished process reads
    exactly like a killed in-process engine."""

    def __init__(self, replica_id: int, base_url: str, *, pool_size: int = 16,
                 timeout_s: float = 60.0, capacity_hint: int = 256):
        self.id = replica_id
        self.base = base_url.rstrip("/")
        self.generation = 0
        #: routing weight inputs; a scraper (obs/fleet.py) may refresh
        #: depth_hint from the replica's serve/queue_depth gauge
        self.depth_hint = 0
        self.capacity_hint = capacity_hint
        self._timeout = timeout_s
        self._pool = ThreadPoolExecutor(
            max_workers=pool_size, thread_name_prefix=f"RouterHttp-{replica_id}")

    def submit(self, image, *, deadline_ms=None, cancel_event=None) -> Future:
        # cancel_event is advisory here: an HTTP request already on the wire
        # cannot be withdrawn; the router discards the loser's result
        del cancel_event
        return self._pool.submit(self._predict, np.asarray(image), deadline_ms)

    def _predict(self, image: np.ndarray, deadline_ms) -> InferenceResult:
        buf = io.BytesIO()
        np.save(buf, image)
        query = f"?deadline_ms={deadline_ms}" if deadline_ms else ""
        req = urllib.request.Request(
            self.base + "/predict" + query, data=buf.getvalue(),
            headers={"Content-Type": "application/x-npy"})
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise _error_from_http(e.code, e.read()) from None
        # URLError wraps connection loss and IS an OSError -> REPLICA_FATAL
        logits = np.asarray(payload["logits"], dtype=np.float32)
        return InferenceResult(logits=logits, label=int(payload["label"]),
                               latency_ms=(time.monotonic() - t0) * 1e3)

    @property
    def queue_depth(self) -> int:
        return self.depth_hint

    @property
    def capacity(self) -> int:
        return self.capacity_hint

    def probe(self) -> dict:
        try:
            with urllib.request.urlopen(self.base + "/healthz",
                                        timeout=2.0) as r:
                snap = json.loads(r.read())
        except urllib.error.HTTPError as e:
            # 503 still carries the state machine in the body (draining etc.)
            try:
                snap = json.loads(e.read())
            except Exception:  # noqa: BLE001
                snap = {"state": "failed", "healthy": False}
        except OSError:
            return {"state": "stopped", "healthy": False,
                    "generation": self.generation}
        return {"state": snap.get("state", "unknown"),
                "healthy": bool(snap.get("healthy")),
                "generation": int(snap.get("generation", self.generation))}

    def quiesce(self, timeout: float = 30.0) -> bool:
        # the replica-side /swap handler quiesces its own pipeline; the
        # router only needs to have stopped routing first
        del timeout
        return True

    def swap_to(self, step: int) -> None:
        req = urllib.request.Request(f"{self.base}/swap?step={step}",
                                     data=b"", method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise _error_from_http(e.code, e.read()) from None

    def rewarm(self) -> float:
        return 0.0  # included in the replica-side swap

    def close(self) -> None:
        self._pool.shutdown(wait=True, cancel_futures=True)


# -- router internals ---------------------------------------------------------


class _Scheduler:
    """One timer thread for every delayed action (retry backoff, hedge
    checks): a heap of (due, seq, fn) under a condition variable. Cheaper
    and more inspectable than a threading.Timer per retry, and a single
    join point for close()."""

    def __init__(self, name: str = "RouterTimer"):
        self._heap: list = []
        self._cv = threading.Condition()
        self._stop = False
        self._seq = itertools.count()
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    def call_later(self, delay_s: float, fn) -> None:
        with self._cv:
            if self._stop:
                return
            heapq.heappush(self._heap,
                           (time.monotonic() + max(delay_s, 0.0),
                            next(self._seq), fn))
            self._cv.notify()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._stop and (
                        not self._heap
                        or self._heap[0][0] > time.monotonic()):
                    if self._stop:
                        break
                    wait = (self._heap[0][0] - time.monotonic()
                            if self._heap else 0.5)
                    self._cv.wait(timeout=max(0.001, min(wait, 0.5)))
                if self._stop:
                    return
                _, _, fn = heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # noqa: BLE001 — a retry must not kill the timer
                log.exception("scheduled router action failed")

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._heap.clear()
            self._cv.notify_all()
        self._thread.join(timeout=5)


class _Flight:
    """One client request's routing state: id, class, deadline, attempts.
    The `done` latch under `lock` is the at-most-once completion guard —
    however many attempts race (retries, requeues, hedges), exactly one
    settles the client future; the rest are discarded losers."""

    __slots__ = ("id", "image", "request_class", "deadline", "future",
                 "lock", "done", "attempts", "hedged", "tried", "pending",
                 "requeued_from", "t_submit")

    def __init__(self, fid: str, image: np.ndarray, request_class: str,
                 deadline: float | None):
        self.id = fid
        self.image = image
        self.request_class = request_class
        self.deadline = deadline
        self.future: Future = Future()
        self.lock = threading.Lock()
        self.done = False
        self.attempts = 0
        self.hedged = False
        self.tried: set = set()
        self.pending: list = []  # (replica_id, attempt future, cancel event)
        self.requeued_from: int | None = None
        self.t_submit = time.monotonic()

    def remaining_ms(self, now: float) -> float | None:
        if self.deadline is None:
            return None
        return max((self.deadline - now) * 1e3, 0.0)

    def settle(self) -> bool:
        """True exactly once — the caller owns the client future."""
        with self.lock:
            if self.done:
                return False
            self.done = True
            return True


@dataclasses.dataclass
class _View:
    """The router's opinion of one replica (its probe state can lag)."""

    replica: object
    state: str = "starting"  # serving | draining | swapping | down
    inflight: int = 0
    down_since: float | None = None
    down_generation: int = -1


class Router:
    """The fleet facade: `submit()` mirrors `InferenceServer.submit` plus a
    `request_class`, and everything else — spreading, shedding, retrying,
    hedging, failover, weight rolls — happens behind it."""

    def __init__(self, replicas, config: RouterConfig | None = None, *,
                 registry=None):
        self.config = config or RouterConfig()
        self.metrics = RouterMetrics()
        self._views: dict = {r.id: _View(replica=r) for r in replicas}
        if len(self._views) != len(list(replicas)):
            raise ValueError("duplicate replica ids")
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._flights: set = set()
        self._pending_recovery: dict = {}  # replica id -> down wall instant
        self._registry = registry
        if registry is not None:
            self.metrics.attach_to(registry)
        self.serving_step: int | None = None
        self._swap_lock = threading.Lock()
        self._scheduler: _Scheduler | None = None
        self._health_stop = threading.Event()
        self._health_thread: threading.Thread | None = None
        self._started = False
        self._closed = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Router":
        if self._started:
            return self
        self._started = True
        self._scheduler = _Scheduler()
        self._probe_all()  # seed states before the first submit
        self._health_thread = threading.Thread(
            target=self._health_loop, name="RouterHealth", daemon=True)
        self._health_thread.start()
        _LIVE_ROUTERS.append(self)
        events.emit("router_start", replicas=sorted(self._views))
        return self

    def close(self) -> None:
        """Stop the router's own threads and fail undispatched flights.
        Replicas are NOT closed — the router routes to them, it does not
        own them (the caller/CLI does)."""
        if self._closed or not self._started:
            self._closed = True
            return
        self._closed = True
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
        if self._scheduler is not None:
            self._scheduler.close()
        with self._lock:
            flights = list(self._flights)
        for flight in flights:
            self._fail(flight, ShuttingDownError("router closed"))
        if self in _LIVE_ROUTERS:
            _LIVE_ROUTERS.remove(self)
        events.emit("router_stop", **{
            k: v for k, v in self.metrics.snapshot().items()
            if isinstance(v, (int, float))})

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- admission (tiered shedding) ----------------------------------------
    def backlog_fraction(self) -> float:
        depth = cap = 0
        n = 0
        with self._lock:
            views = list(self._views.values())
        for v in views:
            if v.state != "serving":
                continue
            n += 1
            depth += v.replica.queue_depth + v.inflight
            cap += v.replica.capacity
        if n == 0:
            return 1.0
        return min(1.0, depth / max(cap, 1))

    def _maybe_shed(self, cls: str, deadline_ms: float | None) -> None:
        cfg = self.config
        with self._lock:
            any_serving = any(v.state == "serving"
                              for v in self._views.values())
        if not any_serving:
            # a failover/swap window, not backlog: the dispatch retry path
            # owns this (redispatch with backoff, AllReplicasDownError at
            # the attempt budget) — shedding here would drop LS traffic a
            # recovering replica could still serve in time
            return
        frac = self.backlog_fraction()
        threshold = cfg.be_shed_at if cls == BEST_EFFORT else cfg.ls_shed_at
        reason = None
        if frac >= threshold:
            reason = "backlog"
        elif (cls == BEST_EFFORT and deadline_ms is not None
              and frac >= cfg.deadline_guard_at):
            # deadline-aware tier: under pressure, a best-effort deadline
            # below the latency requests are OBSERVING right now is hopeless
            p50 = self.metrics.observed_p50_ms()
            if p50 is not None and deadline_ms < p50:
                reason = "deadline_hopeless"
        if reason is not None:
            self.metrics.record_shed(cls)
            events.emit("shed", request_class=cls, reason=reason,
                        backlog=round(frac, 3))
            raise ShedError(
                f"{cls} shed ({reason}, backlog {frac:.2f})")

    def submit(self, image, *, request_class: str = LATENCY_SENSITIVE,
               deadline_ms: float | None = None) -> Future:
        """One request -> Future[InferenceResult]. Never blocks; raises
        `ShedError` (tier policy) or `AllReplicasDownError` instead."""
        if request_class not in REQUEST_CLASSES:
            raise ValueError(
                f"unknown request class {request_class!r}; "
                f"one of {REQUEST_CLASSES}")
        if self._closed or not self._started:
            raise ShuttingDownError("router is not running")
        self.metrics.record_submitted(request_class)
        self._maybe_shed(request_class, deadline_ms)
        now = time.monotonic()
        flight = _Flight(
            f"req-{next(self._seq)}", np.asarray(image), request_class,
            now + deadline_ms / 1e3 if deadline_ms is not None else None)
        with self._lock:
            self._flights.add(flight)
        self._dispatch(flight)
        return flight.future

    # -- dispatch ------------------------------------------------------------
    def _pick(self, flight: _Flight, *, require_untried: bool = False):
        with self._lock:
            serving = [v for v in self._views.values()
                       if v.state == "serving"]
        fresh = [v for v in serving if v.replica.id not in flight.tried]
        pool = fresh if (fresh or require_untried) else serving
        if not pool:
            return None
        return min(pool, key=lambda v: (v.replica.queue_depth + v.inflight,
                                        v.replica.id))

    def _any_recoverable(self) -> bool:
        """Is any replica plausibly coming back (draining/swapping/starting,
        or down with a restart policy outside the router)? Down replicas
        count: the health loop re-admits them on a new generation."""
        with self._lock:
            return bool(self._views)

    def _dispatch(self, flight: _Flight, *, hedge: bool = False) -> None:
        if flight.done:
            return
        now = time.monotonic()
        if flight.deadline is not None and now > flight.deadline:
            self._fail(flight, DeadlineExceededError(
                f"{flight.id}: deadline passed before dispatch"))
            return
        view = self._pick(flight, require_untried=hedge)
        if view is None:
            if hedge:
                return  # nowhere to hedge to; the primary attempt stands
            self._retry_or_fail(
                flight, AllReplicasDownError("no serving replica"),
                retryable=self._any_recoverable())
            return
        cancel_ev = threading.Event()
        try:
            fut = view.replica.submit(flight.image,
                                      deadline_ms=flight.remaining_ms(now),
                                      cancel_event=cancel_ev)
        except Exception as err:  # noqa: BLE001 — classified below
            self._on_attempt_error(flight, view, err)
            return
        with self._lock:
            view.inflight += 1
        with flight.lock:
            flight.tried.add(view.replica.id)
            flight.pending.append((view.replica.id, fut, cancel_ev))
        fut.add_done_callback(
            lambda f, v=view: self._on_attempt_done(flight, v, f))
        if not hedge and flight.request_class == LATENCY_SENSITIVE:
            h_ms = self._hedge_after_ms()
            if h_ms is not None and self._scheduler is not None:
                self._scheduler.call_later(
                    h_ms / 1e3, lambda: self._maybe_hedge(flight, h_ms))

    def _hedge_after_ms(self) -> float | None:
        cfg = self.config
        if cfg.hedge_after_ms is not None:
            return cfg.hedge_after_ms
        h = self.metrics.latency_ms[LATENCY_SENSITIVE]
        if h.count < cfg.hedge_min_samples:
            return None  # not enough signal for a p99 yet
        return max(h.snapshot()["p99"], cfg.hedge_floor_ms)

    def _maybe_hedge(self, flight: _Flight, after_ms: float) -> None:
        with flight.lock:
            if flight.done or flight.hedged:
                return
            flight.hedged = True
        view = self._pick(flight, require_untried=True)
        if view is None:
            with flight.lock:
                flight.hedged = False  # nowhere to go; may re-arm later
            return
        self.metrics.bump("hedges")
        events.emit("request_hedged", request=flight.id,
                    to_replica=view.replica.id, after_ms=round(after_ms, 3))
        self._dispatch(flight, hedge=True)

    # -- attempt completion --------------------------------------------------
    def _on_attempt_done(self, flight: _Flight, view: _View, fut) -> None:
        with self._lock:
            view.inflight -= 1
        err = fut.exception()
        if err is None:
            self._on_attempt_success(flight, view, fut.result())
        else:
            self._on_attempt_error(flight, view, err)

    def _on_attempt_success(self, flight: _Flight, view: _View,
                            result) -> None:
        if not flight.settle():
            if flight.hedged:
                self.metrics.bump("hedge_losses")
            return
        latency_ms = (time.monotonic() - flight.t_submit) * 1e3
        self.metrics.record_completed(flight.request_class, latency_ms)
        self._cancel_losers(flight)
        self._note_recovery(flight)
        with self._lock:
            self._flights.discard(flight)
        # router-level latency (includes retries/hedges), replica's logits
        flight.future.set_result(InferenceResult(
            logits=result.logits, label=result.label, latency_ms=latency_ms))

    def _on_attempt_error(self, flight: _Flight, view: _View,
                          err: BaseException) -> None:
        disposition = classify_failure(err)
        if disposition == REPLICA_FATAL:
            # mark the replica down even when this flight already won via a
            # hedge — the ERROR is evidence about the replica either way
            self._mark_down(view, err)
        if flight.done:
            return
        if disposition == TERMINAL:
            self._fail(flight, err)
        elif disposition == REPLICA_FATAL:
            flight.requeued_from = view.replica.id
            if flight.attempts < self.config.retry_max_attempts:
                flight.attempts += 1
                self.metrics.bump("requeues")
                events.emit("request_requeued", request=flight.id,
                            from_replica=view.replica.id)
                self._dispatch(flight)  # immediate failover, no backoff
            else:
                self._fail(flight, err)
        else:
            self._retry_or_fail(flight, err, retryable=True)

    def _retry_or_fail(self, flight: _Flight, err: BaseException, *,
                       retryable: bool) -> None:
        if not retryable or flight.attempts >= self.config.retry_max_attempts:
            self._fail(flight, err)
            return
        backoff_s = min(self.config.retry_base_ms * (2 ** flight.attempts),
                        self.config.retry_max_ms) / 1e3
        flight.attempts += 1
        if (flight.deadline is not None
                and time.monotonic() + backoff_s > flight.deadline):
            self._fail(flight, err)
            return
        self.metrics.bump("retries")
        if self._scheduler is None:
            self._fail(flight, err)
            return
        self._scheduler.call_later(backoff_s, lambda: self._dispatch(flight))

    def _fail(self, flight: _Flight, err: BaseException) -> None:
        if not flight.settle():
            return
        self.metrics.record_failed(flight.request_class)
        self._cancel_losers(flight)
        with self._lock:
            self._flights.discard(flight)
        flight.future.set_exception(err)

    def _cancel_losers(self, flight: _Flight) -> None:
        with flight.lock:
            pending = list(flight.pending)
        for _rid, fut, ev in pending:
            if not fut.done():
                ev.set()  # dequeue-time drop; a mid-batch loser just finishes

    def _note_recovery(self, flight: _Flight) -> None:
        """replica_down -> first-rerouted-response: the recovery latency the
        bench reports. Sampled on the first completed flight that was
        requeued off the dead replica."""
        rid = flight.requeued_from
        if rid is None:
            return
        with self._lock:
            t0 = self._pending_recovery.pop(rid, None)
        if t0 is None:
            return
        ms = (time.monotonic() - t0) * 1e3
        self.metrics.record_recovery(ms)
        events.emit("failover_first_response", replica=rid,
                    recovery_ms=round(ms, 3), request=flight.id)

    # -- replica lifecycle ---------------------------------------------------
    def _mark_down(self, view: _View, err: BaseException | None) -> None:
        gen = getattr(view.replica, "generation", 0)
        with self._lock:
            if view.state == "down" and view.down_generation == gen:
                return
            view.state = "down"
            view.down_since = time.monotonic()
            view.down_generation = gen
            self._pending_recovery[view.replica.id] = view.down_since
        self.metrics.bump("replica_downs")
        reason = type(err).__name__ if err is not None else "probe"
        log.warning("replica %s marked down (%s)", view.replica.id, reason)
        events.emit("replica_down", replica=view.replica.id, reason=reason)

    def _health_loop(self) -> None:
        while not self._health_stop.wait(self.config.health_interval_s):
            self._probe_all()

    def _probe_all(self) -> None:
        for view in list(self._views.values()):
            try:
                snap = view.replica.probe()
            except Exception:  # noqa: BLE001 — an unprobeable replica is down
                snap = {"state": "stopped", "healthy": False,
                        "generation": -1}
            with self._lock:
                state = view.state
            if state == "swapping":
                continue  # router-owned window; the probe has no say
            if state == "down":
                if (snap["healthy"]
                        and snap.get("generation", 0) > view.down_generation):
                    with self._lock:
                        view.state = "serving"
                        view.down_since = None
                    self.metrics.bump("replica_ups")
                    events.emit("replica_up", replica=view.replica.id,
                                generation=snap.get("generation"))
            elif snap["state"] == "draining":
                if state != "draining":
                    with self._lock:
                        view.state = "draining"
                    self.metrics.bump("replica_drains")
                    events.emit("replica_drain", replica=view.replica.id)
            elif not snap["healthy"]:
                self._mark_down(view, None)
            else:  # healthy and not draining
                if state in ("starting", "draining"):
                    with self._lock:
                        view.state = "serving"
                    if state == "draining":
                        events.emit("replica_up", replica=view.replica.id,
                                    generation=snap.get("generation"))
                        self.metrics.bump("replica_ups")
        self._export_gauges()

    def _export_gauges(self) -> None:
        if self._registry is None:
            return
        with self._lock:
            states = [v.state for v in self._views.values()]
        self._registry.set_scalars({
            "fleet/replicas_total": len(states),
            "fleet/replicas_serving": states.count("serving"),
            "fleet/replicas_down": states.count("down"),
            "fleet/backlog_fraction": self.backlog_fraction(),
        }, step=0)

    def replica_states(self) -> dict:
        with self._lock:
            return {rid: v.state for rid, v in self._views.items()}

    # -- replica membership (the autoscaler's seam) ---------------------------
    # The replica set is NOT immutable after construction: serve/autoscale.py
    # adds and removes replicas while traffic flows. Everything that walks
    # the views already snapshots under `_lock` (`_pick`, `_probe_all`,
    # `backlog_fraction`, `_export_gauges`), scheduler timers capture _View
    # objects (alive after removal, so late attempt callbacks settle
    # harmlessly), and `roll_weights` re-looks ids up with `.get` — so
    # membership churn needs no further coordination than these two methods.

    def add_replica(self, replica, *, wait_serving_s: float = 30.0,
                    probe_interval_s: float = 0.05) -> bool:
        """Admit a new replica behind a warm-up gate.

        The view enters as ``starting`` — `_pick` never routes to it — and
        is promoted to ``serving`` only once the replica's own probe
        reports healthy. Returns True on admission; on a warm-up timeout
        the view is withdrawn and False returned (the caller still owns
        the replica and should reap it). Raises ValueError on a duplicate
        id and ShuttingDownError on a closed router."""
        if self._closed or not self._started:
            raise ShuttingDownError("router is not running")
        with self._lock:
            if replica.id in self._views:
                raise ValueError(f"duplicate replica id {replica.id}")
            view = _View(replica=replica)
            self._views[replica.id] = view
        self.metrics.bump("replica_adds")
        deadline = time.monotonic() + wait_serving_s
        while time.monotonic() < deadline and not self._closed:
            with self._lock:
                already = view.state == "serving"
            if already:
                break  # the health loop promoted it between our probes
            try:
                snap = replica.probe()
            except Exception:  # noqa: BLE001 — not warm yet
                snap = {"healthy": False}
            if snap.get("healthy"):
                with self._lock:
                    if view.state == "starting":
                        view.state = "serving"
                break
            time.sleep(probe_interval_s)
        with self._lock:
            admitted = view.state == "serving"
            if not admitted:
                self._views.pop(replica.id, None)
        if admitted:
            self.metrics.bump("replica_ups")
            events.emit("replica_up", replica=replica.id,
                        generation=getattr(replica, "generation", 0))
        return admitted

    def remove_replica(self, rid, *, quiesce_timeout_s: float = 30.0):
        """Drain a replica out of the fleet and return its handle.

        Marks it ``draining`` (no new routing; in-flight requests finish
        via the existing quiesce machinery), quiesces, then drops the view
        and any pending-recovery bookkeeping. The router never owned the
        replica's lifecycle, so the HANDLE is returned for the caller to
        close/reap. Raises KeyError for an unknown id."""
        with self._lock:
            view = self._views.get(rid)
            if view is None:
                raise KeyError(f"no replica {rid!r} in the fleet")
            view.state = "draining"
        self.metrics.bump("replica_drains")
        events.emit("replica_drain", replica=rid)
        try:
            drained = view.replica.quiesce(quiesce_timeout_s)
        except Exception:  # noqa: BLE001 — a dead replica still gets removed
            drained = False
        if not drained:
            log.warning("replica %s did not quiesce within %.1fs; removing "
                        "anyway", rid, quiesce_timeout_s)
        with self._lock:
            self._views.pop(rid, None)
            self._pending_recovery.pop(rid, None)
        self.metrics.bump("replica_removes")
        return view.replica

    # -- weight hot-swap -----------------------------------------------------
    def roll_weights(self, step: int) -> dict:
        """Roll `step`'s weights across the fleet, one replica at a time:
        stop routing to it (`swapping`), quiesce so every in-flight request
        finishes on the OLD weights, swap, rewarm, resume. A failed swap
        leaves that replica serving its old weights (engine.swap_weights is
        all-or-nothing) — a mixed-version fleet beats a smaller one."""
        with self._swap_lock:
            events.emit("weights_roll", step=step, phase="start")
            swapped: list = []
            failed: list = []
            for rid in sorted(self._views):
                view = self._views.get(rid)
                if view is None:
                    continue  # removed mid-roll (autoscale scale-down)
                with self._lock:
                    if view.state != "serving":
                        failed.append({"replica": rid,
                                       "reason": f"state={view.state}"})
                        continue
                    view.state = "swapping"
                t0 = time.perf_counter()
                rewarm_ms = 0.0
                try:
                    if not view.replica.quiesce(
                            self.config.swap_quiesce_timeout_s):
                        raise TimeoutError(
                            f"replica {rid} did not quiesce")
                    view.replica.swap_to(step)
                    rewarm_ms = view.replica.rewarm()
                except Exception as err:  # noqa: BLE001 — per-replica isolation
                    self.metrics.bump("swap_failures")
                    failed.append({"replica": rid,
                                   "reason": f"{type(err).__name__}: {err}"})
                    log.warning("replica %s swap to step %d failed", rid,
                                step, exc_info=True)
                    with self._lock:
                        view.state = "serving"  # old weights still good
                    events.emit("weights_swap", replica=rid, step=step,
                                ok=False, reason=type(err).__name__)
                    continue
                with self._lock:
                    view.state = "serving"
                self.metrics.bump("swaps")
                swapped.append(rid)
                events.emit(
                    "weights_swap", replica=rid, step=step, ok=True,
                    dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
                    rewarm_ms=round(rewarm_ms, 3))
            if swapped:
                self.serving_step = step
            events.emit("weights_roll", step=step, phase="end",
                        swapped=len(swapped), failed=len(failed))
            return {"step": step, "swapped": swapped, "failed": failed}


class CheckpointWatcher:
    """Polls a training run's commit markers (`<dir>/commits/<step>
    .committed` — checkpoint/manager.py's crash-consistency protocol) and
    calls `on_new_step(step)` — typically `Router.roll_weights` — whenever
    a NEWER committed step appears. Markers, not step directories: an
    uncommitted directory may be a torn write, and the manager only
    guarantees restore-eligibility for marked steps."""

    def __init__(self, checkpoint_dir, on_new_step, *,
                 poll_interval_s: float = 2.0,
                 initial_step: int | None = None):
        self._dir = Path(checkpoint_dir)
        self._on = on_new_step
        self._interval = poll_interval_s
        self._last = initial_step
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.polls = 0
        self.rolls = 0

    def latest_committed(self) -> int | None:
        commits = self._dir / "commits"
        if not commits.is_dir():
            return None
        steps = []
        for p in commits.glob("*.committed"):
            try:
                steps.append(int(p.stem))
            except ValueError:
                continue  # not a marker (tmp files, strays)
        return max(steps) if steps else None

    def poll_once(self) -> int | None:
        """One scan; returns the step rolled to, or None. Consumed even on
        a failed roll — a broken checkpoint must not be re-rolled every
        poll (the next COMMIT retriggers naturally)."""
        self.polls += 1
        step = self.latest_committed()
        if step is None or (self._last is not None and step <= self._last):
            return None
        self._last = step
        try:
            self._on(step)
            self.rolls += 1
        except Exception:  # noqa: BLE001 — the watcher outlives a bad roll
            log.exception("weight roll to step %d failed", step)
            return None
        return step

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.poll_once()

    def start(self) -> "CheckpointWatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="RouterWatcher", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
