"""Model-zoo serving: everything the trainer can produce, the engine can
serve — variable-length inputs, MoE checkpoints, and TP/fsdp-sharded
weights, under an explicit per-device memory budget.

This module is the PLANNING layer (no device transfers, no host syncs —
it is in scripts/check_host_sync.py's lint scope): it decides the
(batch-bucket, seq-bucket) grid, builds token masks, attributes per-device
resident bytes, and constructs a fully-wired `InferenceEngine`. The
execution surgery lives in serve/engine.py.

The four zoo problems and where each is solved:

1. **Variable length.** Requests shorter than the init-time native shape
   (fewer image rows -> fewer ViT patch tokens) are right-padded UP to a
   power-of-two height bucket and served with a token mask
   (`models/vit.py apply(mask=...)`), so a short request's logits equal
   running it unpadded — while the executable count stays
   O(log2(max_batch) * log2(native_h)) instead of one per request shape.
   The native bucket keeps the historical MASKLESS program, bit-identical
   to `make_eval_step` on the same checkpoint.
2. **MoE.** `moe_ffn_adaptive` already runs at inference; the zoo adds an
   inference-time capacity factor (`dataclasses.replace` on the frozen
   model — params are capacity-independent) and the engine returns the
   routed `moe_drop_fraction_metric` alongside the logits so expert
   overflow is a serve metric, never silent truncation. Capacity is a
   static function of the bucket's token count, so token imbalance can
   never change the compiled program.
3. **Sharding.** The engine pins its in_shardings off the LIVE weights'
   placements (the `make_eval_step` idiom) — a TP/fsdp/fsdp_tp restore
   serves resident-sharded instead of being silently replicated; the
   loader's `sharding_rules` override re-lands a checkpoint trained under
   one strategy onto another (`parallel/sharding.py` does the resharding
   by construction of the restore targets).
4. **Memory budget.** `per_device_state_bytes` (shard-shape metadata, the
   `state_memory_bytes` discipline) plus per-executable bytes are held
   under `--serve_memory_budget_mb` by the compiled-model cache's LRU
   tier; `prewarm` REFUSES a grid that cannot fit rather than thrashing.
"""

from __future__ import annotations

import dataclasses
import inspect
import logging

import numpy as np

from dist_mnist_tpu.serve.engine import CompiledModelCache, InferenceEngine

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# sequence (height) bucketing


@dataclasses.dataclass(frozen=True)
class SeqGrid:
    """The sequence-bucket axis of the 2-D serve grid.

    "Sequence length" for this zoo's image classifiers is the token count a
    ViT derives from the image HEIGHT: ceil(h / patch) patch-rows of
    (width / patch) tokens each, in row-major order — so right-padding
    image rows pads whole trailing patch tokens (patches never straddle
    the real/pad boundary: the patch conv is stride == kernel == patch),
    and the learned position table's leading rows are exactly the real
    tokens' positions. `heights` are the bucket ceilings, ascending,
    multiples of `patch`, with the init-time native height always last:
    the native bucket serves the maskless bit-parity program, every
    sub-native bucket serves the masked variant.

    The grid's shape (batch ceiling × these buckets) is a registered
    tunable (tune/spec.py `serve_grid`): the tuner scores candidate
    grids by replaying a seeded variable-height stream through this
    class's bucketing arithmetic, and `cli/serve.py --tuned=auto`
    applies the stored per-geometry winner.
    """

    native_height: int
    width: int
    channels: int
    patch: int
    heights: tuple[int, ...]

    def __post_init__(self):
        hs = tuple(sorted(set(int(h) for h in self.heights)))
        if not hs or hs[-1] != self.native_height:
            hs = tuple(h for h in hs if h < self.native_height) \
                + (self.native_height,)
        for h in hs:
            if h < 1 or h > self.native_height:
                raise ValueError(
                    f"seq bucket height {h} outside (0, native "
                    f"{self.native_height}]")
            if h % self.patch:
                raise ValueError(
                    f"seq bucket height {h} not a multiple of patch "
                    f"{self.patch} — a partial patch-row would drop real "
                    "pixels in the VALID patch conv")
        object.__setattr__(self, "heights", hs)

    @property
    def native_only(self) -> bool:
        return self.heights == (self.native_height,)

    def bucket_for(self, h: int) -> int:
        """Smallest bucket ceiling >= h; raises above native (the learned
        position table has no rows for unseen tokens)."""
        if h < 1:
            raise ValueError("empty image (height < 1)")
        for b in self.heights:
            if h <= b:
                return b
        raise ValueError(
            f"height {h} > native {self.native_height}: the checkpoint's "
            "position table ends there; retrain with a larger native shape")

    def n_tokens(self, h: int) -> int:
        """Patch tokens (excluding any CLS) for an image of height `h`."""
        return (-(-h // self.patch)) * (self.width // self.patch)

    def mask(self, real_heights, bucket_h: int) -> np.ndarray:
        """[B, n_tokens(bucket_h)] bool — True on each row's real patch
        tokens. Row-major patch order means row i's first
        `n_tokens(real_heights[i])` tokens are the real ones."""
        real_heights = np.asarray(real_heights, dtype=np.int64)
        s = self.n_tokens(bucket_h)
        real = np.array([self.n_tokens(int(h)) for h in real_heights])
        return (np.arange(s)[None, :] < real[:, None])


def default_seq_grid(image_shape, patch: int) -> SeqGrid:
    """Power-of-two height ladder: patch, 2*patch, 4*patch, ... up to (and
    always including) the native height."""
    native_h, width, channels = (int(d) for d in image_shape)
    heights, h = [], patch
    while h < native_h:
        heights.append(h)
        h *= 2
    heights.append(native_h)
    return SeqGrid(native_height=native_h, width=width, channels=channels,
                   patch=patch, heights=tuple(heights))


def parse_seq_buckets(spec: str | None, image_shape,
                      patch: int) -> SeqGrid | None:
    """CLI surface: None/"" -> no seq grid (native-only engine, exactly
    the pre-zoo behavior); "auto" -> `default_seq_grid`; "h1,h2,..." ->
    explicit bucket ceilings (native appended if missing)."""
    if not spec:
        return None
    if spec == "auto":
        return default_seq_grid(image_shape, patch)
    native_h, width, channels = (int(d) for d in image_shape)
    heights = tuple(int(tok) for tok in spec.split(","))
    return SeqGrid(native_height=native_h, width=width, channels=channels,
                   patch=patch, heights=heights)


def supports_mask(model) -> bool:
    """True when `model.apply` can honor a token mask: it takes a `mask`
    kwarg AND its attention path is maskable — "xla" (the -1e30
    pre-softmax einsum) or "flash" (the variable-length Pallas kernel,
    which turns the zoo's key-prefix masks into per-row lengths and SKIPS
    fully-padded key blocks — ops/pallas/flash_attention). The
    ring/ulysses kernels take no mask argument; models without mask
    support degenerate to the native-only grid."""
    try:
        if "mask" not in inspect.signature(model.apply).parameters:
            return False
    except (TypeError, ValueError):
        return False
    if getattr(model, "attention_impl", "xla") not in ("xla", "flash"):
        return False
    if getattr(model, "block_pipeline", 0):
        return False
    return True


# ---------------------------------------------------------------------------
# per-device memory attribution


def per_device_state_bytes(params, model_state) -> dict:
    """Bytes ONE device holds for the served weights under their ACTUAL
    placements — `train.state.state_memory_bytes`'s discipline (pure
    shard-shape metadata: no transfer, no sync), minus the optimizer slots
    serving never loads. This is the number fsdp shrinks: an fsdp-restored
    tree costs ~1/data-axis of the replicated dense baseline per device."""
    from dist_mnist_tpu.train.state import _per_device_nbytes

    import jax

    out = {
        "param_bytes": sum(_per_device_nbytes(x)
                           for x in jax.tree.leaves(params)),
        "model_state_bytes": sum(_per_device_nbytes(x)
                                 for x in jax.tree.leaves(model_state)),
    }
    out["total_bytes"] = out["param_bytes"] + out["model_state_bytes"]
    return out


# ---------------------------------------------------------------------------
# engine construction


def build_zoo_engine(
    bundle,
    mesh,
    *,
    model_name: str,
    max_bucket: int = 256,
    seq_buckets: str | SeqGrid | None = None,
    moe_capacity_factor: float | None = None,
    memory_budget_mb: float | None = None,
    store=None,
    cache: CompiledModelCache | None = None,
    quant: str | None = None,
) -> InferenceEngine:
    """One factory for every checkpoint the trainer can produce: wires the
    seq grid (when the model can honor masks), the inference-time MoE
    capacity override, the live-placement sharding pin, and the memory
    budget into an `InferenceEngine`. With every knob at its default this
    constructs exactly the pre-zoo engine.

    `bundle` is a `loader.ServingBundle` (or anything with .model/.params/
    .model_state/.image_shape/.rules).
    """
    model = bundle.model
    if moe_capacity_factor is not None:
        if not (dataclasses.is_dataclass(model)
                and any(f.name == "moe_capacity_factor"
                        for f in dataclasses.fields(model))):
            raise ValueError(
                f"--moe_capacity_factor given but model {model_name!r} has "
                "no moe_capacity_factor field")
        # params are capacity-independent: the factor only sizes the
        # routing buffers inside the traced program, so the restored
        # weights serve unchanged under the new capacity
        model = dataclasses.replace(
            model,
            moe_capacity_factor=float(  # lint: ok[host-sync] CLI scalar, no device
                moe_capacity_factor))

    grid = seq_buckets
    if isinstance(seq_buckets, str):
        grid = parse_seq_buckets(
            seq_buckets, bundle.image_shape, getattr(model, "patch", 1))
    if grid is not None and not supports_mask(model):
        if not grid.native_only:
            log.warning(
                "model %r cannot honor token masks (no mask kwarg, kernel "
                "attention, or block pipeline) — variable-length buckets "
                "%s collapse to the native-only grid",
                model_name, grid.heights)
        grid = SeqGrid(native_height=grid.native_height, width=grid.width,
                       channels=grid.channels, patch=grid.patch,
                       heights=(grid.native_height,))

    budget_bytes = (int(memory_budget_mb * 1024 * 1024)
                    if memory_budget_mb else None)
    # quant is a first-class grid variant: default to what the loader
    # already did to the bundle (quantized bundles serve quantized with no
    # extra plumbing); an explicit `quant` converts engine-side
    return InferenceEngine(
        model, bundle.params, bundle.model_state, mesh,
        model_name=model_name, image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=max_bucket, store=store, cache=cache,
        seq_grid=grid, memory_budget_bytes=budget_bytes,
        quant=quant or getattr(bundle, "quant", None),
        quant_report=getattr(bundle, "quant_report", None),
    )


# ---------------------------------------------------------------------------
# autoregressive decode grid (serve/decode.py executes it)


@dataclasses.dataclass(frozen=True)
class DecodeGrid:
    """The executable surface of the decode subsystem, planned up front.

    Two program families (docs/SERVING.md "Autoregressive decode"):

    - **prefill** cells, a (admit-bucket, prompt-bucket) grid exactly like
      the classifier's (batch, seq) grid: prompts are right-padded to the
      power-of-two ``prompt_buckets`` entry for THEIR OWN length (never
      the batch's max — a request's prefill program must not depend on
      who it was admitted with, or token streams would differ between
      scheduling modes), and batched up to ``admit_buckets``.
    - **decode cells**: the single-token step is compiled at the full
      slot capacity (+1 scratch row prefill padding lands in) and every
      step runs one — continuous batching admits/evicts by editing the
      per-slot token/position vectors, never by reshaping the batch.
      The dense layout has exactly one decode cell, ``("decode",)``.
      The paged layout compiles one ``("decode", p)`` cell per entry of
      ``decode_page_buckets`` (page-table widths): each step picks the
      smallest bucket covering the batch's live prefix, so attention
      cost tracks real lengths instead of max_seq. Float paged grids
      carry only the full-width bucket (truncation is not bitwise —
      models/causal_lm.py); int8 grids carry the power-of-two ladder.

    Prewarming every cell is what makes mixed prefill/decode traffic
    recompile-free (the acceptance bar bench.py --serve --decode holds).
    """

    max_slots: int = 8
    max_seq: int = 64
    prompt_buckets: tuple = ()
    admit_buckets: tuple = ()
    #: page-table width buckets for the paged decode cells; () = dense
    decode_page_buckets: tuple = ()

    def __post_init__(self):
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        pb = tuple(sorted({int(b) for b in self.prompt_buckets}))
        if not pb or any(b < 1 or b > self.max_seq for b in pb):
            raise ValueError(
                f"prompt buckets {pb} must be within [1, {self.max_seq}]")
        ab = tuple(sorted({int(b) for b in self.admit_buckets}))
        if not ab or any(b < 1 for b in ab):
            raise ValueError(f"admit buckets {ab} must be >= 1")
        dp = tuple(sorted({int(b) for b in self.decode_page_buckets}))
        if any(b < 1 for b in dp):
            raise ValueError(f"decode page buckets {dp} must be >= 1")
        object.__setattr__(self, "prompt_buckets", pb)
        object.__setattr__(self, "admit_buckets", ab)
        object.__setattr__(self, "decode_page_buckets", dp)

    @property
    def rows(self) -> int:
        """Device rows of the decode batch / KV cache: every slot plus
        the scratch row that absorbs prefill padding writes."""
        return self.max_slots + 1

    def prompt_bucket_for(self, length: int) -> int:
        """Smallest prompt bucket holding `length` — a function of the
        request alone (see class docstring)."""
        if length < 1:
            raise ValueError("empty prompt")
        for b in self.prompt_buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt length {length} > largest bucket "
            f"{self.prompt_buckets[-1]}")

    def admit_bucket_for(self, n: int) -> int:
        """Smallest admit (prefill batch) bucket holding `n` rows."""
        if n < 1:
            raise ValueError("empty admission")
        for b in self.admit_buckets:
            if b >= n:
                return b
        raise ValueError(
            f"admission of {n} > largest admit bucket "
            f"{self.admit_buckets[-1]}; chunk upstream")

    def decode_page_bucket_for(self, n_pages: int) -> int:
        """Smallest page-table-width bucket covering the live prefix of
        `n_pages` pages (paged layout only)."""
        if not self.decode_page_buckets:
            raise ValueError("grid has no decode page buckets (dense)")
        if n_pages < 1:
            raise ValueError("empty prefix")
        for b in self.decode_page_buckets:
            if b >= n_pages:
                return b
        raise ValueError(
            f"prefix of {n_pages} pages > widest decode bucket "
            f"{self.decode_page_buckets[-1]}")

    def cells(self) -> list:
        """Every compiled program: ('prefill', n, s) cells plus the
        decode cells — ('decode',) for dense, ('decode', p) per page
        bucket for paged."""
        out = [("prefill", n, s) for n in self.admit_buckets
               for s in self.prompt_buckets]
        if self.decode_page_buckets:
            out.extend(("decode", p) for p in self.decode_page_buckets)
        else:
            out.append(("decode",))
        return out


def default_decode_grid(model, *, max_slots: int = 8,
                        prompt_buckets=None) -> DecodeGrid:
    """Power-of-two prompt buckets up to the model's max_seq (floored at
    4 tokens — tinier programs aren't worth their cache slots), admit
    buckets up to the slot count. Paged models additionally get decode
    page buckets: the power-of-two ladder up to pages_per_slot when the
    KV is int8 (truncated cells live under the agreement gate), but only
    the full width for float KV — truncating the key axis re-tiles the
    XLA reduction and breaks the bitwise decode==dense contract."""
    max_seq = int(model.max_seq)
    if prompt_buckets is None:
        buckets, b = [], 4
        while b < max_seq:
            buckets.append(b)
            b *= 2
        buckets.append(max_seq)
    else:
        buckets = [int(b) for b in prompt_buckets]
    admits, a = [], 1
    while a < max_slots:
        admits.append(a)
        a *= 2
    admits.append(max_slots)
    pages = []
    if getattr(model, "cache_layout", "dense") == "paged":
        pps = model.pages_per_slot
        if getattr(model, "kv_quant", "none") == "int8":
            p = 1
            while p < pps:
                pages.append(p)
                p *= 2
        pages.append(pps)
    return DecodeGrid(max_slots=max_slots, max_seq=max_seq,
                      prompt_buckets=tuple(buckets),
                      admit_buckets=tuple(admits),
                      decode_page_buckets=tuple(pages))


def build_decode_engine(
    mesh,
    *,
    model_name: str = "causal_tiny",
    seed: int = 0,
    max_slots: int = 8,
    prompt_buckets=None,
    store=None,
    cache: CompiledModelCache | None = None,
    **model_overrides,
):
    """Construct a fully-wired `serve/decode.DecodeEngine` for a registry
    causal model — the decode-side sibling of `build_zoo_engine`. Params
    are fresh-initialized from `seed` (the synthetic-token decode workload
    has no checkpoint lineage yet; `loader.init_lm_for_serving` is the
    seam a restore would slot into)."""
    from dist_mnist_tpu.serve.decode import DecodeEngine
    from dist_mnist_tpu.serve.loader import init_lm_for_serving

    model, params = init_lm_for_serving(model_name, seed=seed,
                                        **model_overrides)
    grid = default_decode_grid(model, max_slots=max_slots,
                               prompt_buckets=prompt_buckets)
    return DecodeEngine(model, params, mesh, model_name=model_name,
                        grid=grid, store=store, cache=cache)
