"""Online inference serving: continuous dynamic batching over the data-axis
mesh, an AOT compiled-model cache, and admission control.

The training side of this repo answers "how fast can the mesh learn"; this
package answers "how fast can the trained model answer", reusing the same
building blocks — `parallel/sharding.py` placement, `cluster/mesh.py`
meshes, `checkpoint/manager.py` weights, `obs/` metric writers — so a model
serves exactly where it trained. docs/SERVING.md is the architecture note.

Layering (each module depends only on those above it):

    metrics.py    counters + latency/occupancy reservoirs -> obs writers
    engine.py     CompiledModelCache + InferenceEngine (bucketing, AOT)
    admission.py  bounded queue, deadlines, explicit rejection
    batcher.py    the coalescing loop (one daemon thread)
    loader.py     checkpoint -> (model, params, model_state), no optimizer
    zoo.py        model-zoo planning: sequence grids, capacity overrides,
                  maskability probes, per-device byte accounting
    server.py     InferenceServer facade wiring all of the above
    errors.py     failure taxonomy: retryable / terminal / replica-fatal
    router.py     fleet facade: N replicas, tiered shedding, failover,
                  hedging, zero-downtime weight hot-swap
    loadgen.py    deterministic load generators: closed-loop (bench +
                  tests) and trace-driven open-loop arrival processes
    autoscale.py  the capacity control loop: FleetSignals -> ScalePolicy
                  -> Autoscaler actuating Router add/remove_replica
    decode.py     autoregressive decode serving: prefill/decode split,
                  sharded KV cache, continuous batching
"""

from dist_mnist_tpu.serve.admission import (
    AdmissionQueue,
    DeadlineExceededError,
    QueueFullError,
    ShuttingDownError,
)
from dist_mnist_tpu.serve.autoscale import (
    Autoscaler,
    Decision,
    FleetSignals,
    FleetSignalSource,
    PolicyState,
    ScalePolicy,
)
from dist_mnist_tpu.serve.decode import (
    DecodeEngine,
    DecodeResult,
    DecodeScheduler,
)
from dist_mnist_tpu.serve.engine import (
    CompiledModelCache,
    InferenceEngine,
    ServeMemoryBudgetError,
)
from dist_mnist_tpu.serve.errors import (
    AllReplicasDownError,
    ReplicaKilledError,
    ShedError,
    classify_failure,
)
from dist_mnist_tpu.serve.loader import (
    init_lm_for_serving,
    load_for_serving,
    quantize_for_serving,
)
from dist_mnist_tpu.serve.loadgen import (
    burst_trace,
    diurnal_trace,
    flash_crowd_trace,
    make_prompts,
    run_decode_loadgen,
    run_fleet_loadgen,
    run_loadgen,
    run_longctx_loadgen,
    run_trace_loadgen,
)
from dist_mnist_tpu.serve.metrics import DecodeMetrics, ServeMetrics
from dist_mnist_tpu.serve.router import (
    BEST_EFFORT,
    DECODE_SLO_TARGETS,
    LATENCY_SENSITIVE,
    CheckpointWatcher,
    HttpReplica,
    InProcessReplica,
    Router,
    RouterConfig,
)
from dist_mnist_tpu.serve.server import InferenceServer, ServeConfig
from dist_mnist_tpu.serve.zoo import (
    DecodeGrid,
    SeqGrid,
    build_decode_engine,
    build_zoo_engine,
    default_decode_grid,
    default_seq_grid,
    parse_seq_buckets,
    supports_mask,
)

__all__ = [
    "AdmissionQueue",
    "AllReplicasDownError",
    "Autoscaler",
    "BEST_EFFORT",
    "CheckpointWatcher",
    "CompiledModelCache",
    "DECODE_SLO_TARGETS",
    "DeadlineExceededError",
    "Decision",
    "DecodeEngine",
    "DecodeGrid",
    "DecodeMetrics",
    "DecodeResult",
    "DecodeScheduler",
    "FleetSignalSource",
    "FleetSignals",
    "HttpReplica",
    "InProcessReplica",
    "InferenceEngine",
    "InferenceServer",
    "LATENCY_SENSITIVE",
    "PolicyState",
    "QueueFullError",
    "ReplicaKilledError",
    "Router",
    "RouterConfig",
    "ScalePolicy",
    "SeqGrid",
    "ServeConfig",
    "ServeMemoryBudgetError",
    "ServeMetrics",
    "ShedError",
    "ShuttingDownError",
    "build_decode_engine",
    "build_zoo_engine",
    "burst_trace",
    "classify_failure",
    "default_decode_grid",
    "default_seq_grid",
    "diurnal_trace",
    "flash_crowd_trace",
    "init_lm_for_serving",
    "load_for_serving",
    "make_prompts",
    "parse_seq_buckets",
    "quantize_for_serving",
    "run_decode_loadgen",
    "run_fleet_loadgen",
    "run_loadgen",
    "run_longctx_loadgen",
    "run_trace_loadgen",
    "supports_mask",
]
