"""Fleet autoscaler: replica count as a control variable.

The router (serve/router.py) made the latency-sensitive SLO survive a
replica dying; this module makes it survive TRAFFIC — diurnal waves and
10x flash crowds — by closing the loop from the fleet's own signals back
into capacity. Three pieces, layered so each is testable alone:

- ``FleetSignalSource`` merges the control inputs into one immutable
  ``FleetSignals`` sample per tick: backlog fraction (queued + inflight
  over serving capacity), the best-effort shed RATE (sheds/sec since the
  previous tick — the first structural symptom of saturation, because
  the router sheds BE before LS p99 moves), and the live LS p99 against
  its SLO. In-process fleets read the Router's own metrics; a subprocess
  fleet hands the source a ``FleetScraper`` (obs/fleet.py) and queue
  depth comes from the merged ``serve_queue_depth`` scrape instead.

- ``ScalePolicy`` is the deterministic, hysteresis-damped decision
  function: scale UP when BE shedding starts, LS p99 eats its headroom,
  or backlog crosses the trigger; scale DOWN only after a SUSTAINED idle
  window. Separate up/down cooldowns, min/max clamps, and an
  at-most-one-in-flight-resize guard make flapping structurally
  impossible rather than merely unlikely. Pure function of
  ``(FleetSignals, PolicyState)`` — the unit-test matrix drives it with
  canned signals and an advancing fake clock, no replicas involved.

- ``Autoscaler`` is the actuator thread (named ``Autoscaler`` for the
  conftest leak-check): each tick it samples, decides, and — on a
  decision — resizes through the Router's replica-lifecycle seam.
  Scale-up spawns a COLD replica through the caller's ``spawn`` factory,
  which loads weights from the live bundle/peer ring and prewarms
  through the SHARED compile cache; `Router.add_replica` admits it to
  routing only after its warm-up probe passes. The journaled
  ``replica_scale_up`` event carries the warm-start receipts: StartupClock
  restore-vs-compile attribution plus the shared cache's compile-seconds
  and miss deltas across the spawn — a scale-up that compiled anything
  is visible (and `bench.py --serve --autoscale` asserts it is ~zero).
  Scale-down picks the highest-id serving replica, drains it via
  `Router.remove_replica` (quiesce — in-flight requests finish), then
  hands it to the caller's ``reap`` to close.

Actuation is synchronous on the Autoscaler's own thread, so "at most one
in-flight resize" is structural: a second decision cannot fire while a
spawn or drain is still running. Stdlib + numpy only; the jax-touching
parts live behind the caller's spawn/reap closures.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time

from dist_mnist_tpu.compilecache.startup import StartupClock
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.serve.router import BEST_EFFORT, LATENCY_SENSITIVE

log = logging.getLogger(__name__)

#: decision actions — strings, not enums, so journal payloads read plainly
HOLD = "hold"
SCALE_UP = "up"
SCALE_DOWN = "down"


@dataclasses.dataclass(frozen=True)
class FleetSignals:
    """One tick's merged control inputs (immutable: a decision is a pure
    function of this sample plus the policy state)."""

    t: float                    # sample instant (the policy's clock)
    serving_replicas: int
    total_replicas: int
    backlog_fraction: float     # queued+inflight over serving capacity
    be_shed_rate: float         # best_effort sheds/sec since last sample
    ls_p99_ms: float | None     # live LS p99; None before any samples


class FleetSignalSource:
    """Merge Router metrics (and, when given, FleetScraper state) into
    ``FleetSignals`` samples. Shed counts and LS p99 always come from the
    router — shedding is a router-level act, replicas never see the
    traffic — while queue depth prefers the scraper's merged
    ``serve_queue_depth`` gauges when a subprocess fleet is scraped."""

    def __init__(self, router, *, scraper=None, clock=time.monotonic):
        self._router = router
        self._scraper = scraper
        self._clock = clock
        self._prev_shed: int | None = None
        self._prev_t: float | None = None

    def _scraped_backlog(self) -> float | None:
        snap = self._scraper.snapshot() if self._scraper is not None else None
        if snap is None:
            return None
        depth = cap = 0.0
        seen = False
        with self._scraper._lock:
            views = list(self._scraper._hosts.values())
        for view in views:
            if not view.reachable:
                continue
            d = view.scalars.get("serve_queue_depth")
            if d is None:
                continue
            seen = True
            depth += d
            cap += view.scalars.get("serve_queue_capacity", 0.0)
        if not seen:
            return None
        return min(1.0, depth / max(cap, 1.0))

    def signals(self) -> FleetSignals:
        now = self._clock()
        snap = self._router.metrics.snapshot()
        shed = snap["shed"][BEST_EFFORT]
        if self._prev_t is None:
            rate = 0.0
        else:
            dt = max(now - self._prev_t, 1e-6)
            rate = max(0, shed - self._prev_shed) / dt
        self._prev_shed, self._prev_t = shed, now
        states = list(self._router.replica_states().values())
        backlog = self._scraped_backlog()
        if backlog is None:
            backlog = self._router.backlog_fraction()
        return FleetSignals(
            t=now,
            serving_replicas=states.count("serving"),
            total_replicas=len(states),
            backlog_fraction=backlog,
            be_shed_rate=rate,
            ls_p99_ms=self._router.metrics.latency_pct(
                LATENCY_SENSITIVE, "p99"),
        )


@dataclasses.dataclass
class PolicyState:
    """Mutable hysteresis state the policy threads between decisions."""

    last_up_t: float = -math.inf
    last_down_t: float = -math.inf
    idle_since: float | None = None


@dataclasses.dataclass(frozen=True)
class Decision:
    action: str          # HOLD | SCALE_UP | SCALE_DOWN
    reason: str
    target_replicas: int


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """Deterministic hysteresis-damped scaling policy.

    Up triggers (any one, subject to max clamp + up cooldown):
    ``be_shed_rate >= be_shed_rate_up`` (the router started shedding
    best-effort — saturation's first symptom), ``ls_p99 >= headroom *
    slo_p99_ms`` (the expensive tier's headroom collapsed), or
    ``backlog_fraction >= backlog_up``. Down requires the fleet to look
    idle (backlog under ``idle_backlog``, zero BE shedding)
    CONTINUOUSLY for ``idle_window_s``, plus both cooldowns — one busy
    sample resets the idle clock, which is what keeps an oscillating
    load from flapping the fleet."""

    min_replicas: int = 1
    max_replicas: int = 8
    slo_p99_ms: float = 500.0
    #: scale up when ls_p99 crosses this fraction of the SLO
    headroom: float = 0.7
    #: best_effort sheds/sec that count as "shedding started"
    be_shed_rate_up: float = 0.5
    #: backlog fraction up-trigger; below the router's be_shed_at so the
    #: fleet grows BEFORE the tier policy must throw traffic away
    backlog_up: float = 0.45
    #: below this backlog (and with zero shedding) a sample counts idle
    idle_backlog: float = 0.10
    idle_window_s: float = 5.0
    up_cooldown_s: float = 2.0
    down_cooldown_s: float = 10.0

    def __post_init__(self):
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")

    def _up_reason(self, sig: FleetSignals) -> str | None:
        if sig.be_shed_rate >= self.be_shed_rate_up:
            return "be_shedding"
        if (sig.ls_p99_ms is not None
                and sig.ls_p99_ms >= self.headroom * self.slo_p99_ms):
            return "ls_headroom_collapse"
        if sig.backlog_fraction >= self.backlog_up:
            return "backlog"
        return None

    def decide(self, sig: FleetSignals, state: PolicyState) -> Decision:
        """One decision; mutates only ``state`` (the idle clock)."""
        n = sig.serving_replicas
        idle = (sig.backlog_fraction < self.idle_backlog
                and sig.be_shed_rate == 0.0)
        if idle:
            if state.idle_since is None:
                state.idle_since = sig.t
        else:
            state.idle_since = None
        up_reason = self._up_reason(sig)
        if up_reason is not None:
            if n >= self.max_replicas:
                return Decision(HOLD, "at_max", n)
            if sig.t - state.last_up_t < self.up_cooldown_s:
                return Decision(HOLD, "up_cooldown", n)
            return Decision(SCALE_UP, up_reason, n + 1)
        if (idle and state.idle_since is not None
                and sig.t - state.idle_since >= self.idle_window_s):
            if n <= self.min_replicas:
                return Decision(HOLD, "at_min", n)
            if sig.t - state.last_down_t < self.down_cooldown_s:
                return Decision(HOLD, "down_cooldown", n)
            if sig.t - state.last_up_t < self.down_cooldown_s:
                # fresh capacity: do not tear down what just scaled up
                return Decision(HOLD, "down_cooldown", n)
            return Decision(SCALE_DOWN, "sustained_idle", n - 1)
        return Decision(HOLD, "steady", n)


class Autoscaler:
    """Control-loop thread actuating `ScalePolicy` decisions through the
    Router's `add_replica` / `remove_replica` seam.

    ``spawn(replica_id, startup)`` must return a started replica handle,
    noting its weight-load and prewarm time into ``startup`` (a
    `StartupClock`) under the ``restore`` / ``compile`` phases.
    ``reap(replica)`` owns disposal of a drained (or failed-admission)
    replica — the router never closes replicas, and neither does the
    autoscaler. ``cache`` (optional, a `CompiledModelCache`) provides the
    compile-seconds/miss deltas that turn the warm-start promise into a
    journaled, assertable number."""

    def __init__(self, router, source, spawn, *, reap=None,
                 policy: ScalePolicy | None = None,
                 interval_s: float = 0.25, registry=None, cache=None,
                 warmup_timeout_s: float = 60.0,
                 drain_timeout_s: float = 30.0,
                 clock=time.monotonic):
        self._router = router
        self._source = source
        self._spawn = spawn
        self._reap = reap if reap is not None else self._default_reap
        self.policy = policy if policy is not None else ScalePolicy()
        self.interval_s = interval_s
        self._registry = registry
        self._cache = cache
        self._warmup_timeout_s = warmup_timeout_s
        self._drain_timeout_s = drain_timeout_s
        self._clock = clock
        self.state = PolicyState()
        self.scale_ups = 0
        self.scale_downs = 0
        self.failed_scale_ups = 0
        self.ticks = 0
        #: (t, serving_replica_count) after every membership change plus
        #: one seed sample at start() — the bench integrates this into
        #: replica-seconds for the chip-economics headline
        self.timeline: list = []
        #: per-resize receipts (dicts mirroring the journal payloads)
        self.history: list = []
        self._resizing = threading.Lock()
        self._next_id: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @staticmethod
    def _default_reap(replica) -> None:
        close = getattr(replica, "close", None)
        if close is not None:
            close()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self.timeline.append((self._clock(), self._serving_count()))
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="Autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the control loop must survive
                log.exception("autoscaler tick failed")

    def close(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=max(10.0, self._warmup_timeout_s))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- control loop --------------------------------------------------------
    def _serving_count(self) -> int:
        states = list(self._router.replica_states().values())
        return states.count("serving")

    def _pick_next_id(self) -> int:
        """Monotonic fresh replica id: above every id the router has ever
        shown us, never reused after a remove (a reused id would alias the
        router's down-generation and recovery bookkeeping)."""
        highest = max(self._router.replica_states(), default=-1)
        if self._next_id is None or self._next_id <= highest:
            self._next_id = highest + 1
        rid = self._next_id
        self._next_id += 1
        return rid

    def tick(self) -> Decision:
        """One sample -> decision -> (maybe) resize. Public so the policy
        tests and the bench can drive the loop without the thread."""
        sig = self._source.signals()
        if not self._resizing.acquire(blocking=False):
            # a resize from a concurrent tick() is still in flight
            return Decision(HOLD, "resize_in_flight",
                            sig.serving_replicas)
        try:
            decision = self.policy.decide(sig, self.state)
            self.ticks += 1
            self._export_gauges(decision)
            if decision.action == SCALE_UP:
                self._scale_up(sig, decision)
            elif decision.action == SCALE_DOWN:
                self._scale_down(sig, decision)
            return decision
        finally:
            self._resizing.release()

    def _export_gauges(self, decision: Decision) -> None:
        if self._registry is None:
            return
        self._registry.set_scalars({
            "fleet/target_replicas": decision.target_replicas,
            "fleet/scale_ups": self.scale_ups,
            "fleet/scale_downs": self.scale_downs,
        }, step=self.ticks)

    def _emit_decision(self, sig: FleetSignals, decision: Decision) -> None:
        events.emit(
            "autoscale_decision", action=decision.action,
            reason=decision.reason, serving=sig.serving_replicas,
            target=decision.target_replicas,
            backlog=round(sig.backlog_fraction, 3),
            be_shed_rate=round(sig.be_shed_rate, 3),
            ls_p99_ms=(round(sig.ls_p99_ms, 3)
                       if sig.ls_p99_ms is not None else None))

    # -- actuation -----------------------------------------------------------
    def _scale_up(self, sig: FleetSignals, decision: Decision) -> None:
        self._emit_decision(sig, decision)
        rid = self._pick_next_id()
        startup = StartupClock()
        cache0 = self._cache.stats() if self._cache is not None else None
        t0 = time.monotonic()
        # cooldown starts at the ATTEMPT: a failing spawn must not be
        # retried at tick cadence
        self.state.last_up_t = sig.t
        try:
            replica = self._spawn(rid, startup)
        except Exception:  # noqa: BLE001 — a failed spawn must not kill the loop
            self.failed_scale_ups += 1
            log.exception("scale-up spawn of replica %d failed", rid)
            return
        admitted = False
        try:
            admitted = self._router.add_replica(
                replica, wait_serving_s=self._warmup_timeout_s)
        except Exception:  # noqa: BLE001
            log.exception("scale-up admission of replica %d failed", rid)
        if not admitted:
            self.failed_scale_ups += 1
            log.warning("replica %d failed its warm-up probe within %.1fs; "
                        "reaping", rid, self._warmup_timeout_s)
            self._reap(replica)
            return
        startup.first_step_done()
        total_ms = (time.monotonic() - t0) * 1e3
        self.scale_ups += 1
        self.timeline.append((self._clock(), self._serving_count()))
        receipt = {
            "replica": rid,
            "reason": decision.reason,
            "total_ms": round(total_ms, 3),
        }
        snap = startup.snapshot()
        # load-vs-compile attribution: restore_ms is the weight/engine
        # build, compile_ms the prewarm wall (shared-cache hits)
        receipt["restore_ms"] = round(snap.get("restore_ms", 0.0), 3)
        receipt["compile_ms"] = round(snap.get("compile_ms", 0.0), 3)
        if cache0 is not None:
            cache1 = self._cache.stats()
            receipt["cache_compile_ms"] = round(
                (cache1["compile_secs"] - cache0["compile_secs"]) * 1e3, 3)
            receipt["cache_misses"] = cache1["misses"] - cache0["misses"]
            receipt["cache_hits_memory"] = (cache1["hits_memory"]
                                            - cache0["hits_memory"])
            receipt["cache_hits_disk"] = (cache1["hits_disk"]
                                          - cache0["hits_disk"])
        self.history.append({"action": SCALE_UP, **receipt})
        events.emit("replica_scale_up", **receipt)

    def _scale_down(self, sig: FleetSignals, decision: Decision) -> None:
        self._emit_decision(sig, decision)
        serving = [rid for rid, s in self._router.replica_states().items()
                   if s == "serving"]
        if len(serving) <= self.policy.min_replicas:
            return  # membership moved under us since the sample
        victim = max(serving)
        self.state.last_down_t = sig.t
        t0 = time.monotonic()
        try:
            replica = self._router.remove_replica(
                victim, quiesce_timeout_s=self._drain_timeout_s)
        except KeyError:
            return  # removed concurrently (e.g. a failed replica reaped)
        drain_ms = (time.monotonic() - t0) * 1e3
        self._reap(replica)
        self.scale_downs += 1
        self.timeline.append((self._clock(), self._serving_count()))
        receipt = {
            "replica": victim,
            "reason": decision.reason,
            "drain_ms": round(drain_ms, 3),
        }
        self.history.append({"action": SCALE_DOWN, **receipt})
        events.emit("replica_scale_down", **receipt)

    # -- reporting -----------------------------------------------------------
    def replica_seconds(self, *, until: float | None = None,
                        floor: int | None = None) -> float:
        """Integrate the membership timeline into replica-seconds (the
        chip-economics numerator, before the chips-per-replica factor).
        ``floor`` clamps each segment's count from below — a fleet never
        bills less than its minimum provisioning."""
        if not self.timeline:
            return 0.0
        end = until if until is not None else self._clock()
        total = 0.0
        for (t0, n), (t1, _n_next) in zip(
                self.timeline, self.timeline[1:] + [(end, 0)]):
            seg_n = max(n, floor) if floor is not None else n
            total += max(0.0, t1 - t0) * seg_n
        return total

    def snapshot(self) -> dict:
        return {
            "ticks": self.ticks,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "failed_scale_ups": self.failed_scale_ups,
            "timeline": [(round(t, 3), n) for t, n in self.timeline],
            "history": list(self.history),
        }
