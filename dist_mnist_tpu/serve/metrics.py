"""Serve-side metrics: latency percentiles, batch occupancy, queue depth,
admission counters.

Host-side and lock-guarded (the batcher thread and every client thread
record concurrently); nothing here touches a device. Emission goes through
the existing `obs.writers.MetricWriter` protocol so serve metrics land in
the same CSV/TensorBoard sinks as training metrics.
"""

from __future__ import annotations

import collections
import threading

import numpy as np

# bounded reservoirs: a long-lived server must not grow memory with request
# count. 65536 most-recent samples bounds the p99 estimate error well below
# anything a BENCH round can resolve.
_RESERVOIR = 65536


class ServeMetrics:
    """Thread-safe accumulator for one server's lifetime.

    Counters:  admitted, completed, rejected_queue_full, rejected_deadline,
               rejected_shutdown, failed.
    Reservoirs: request latency (ms, submit->result), executed batch sizes
               (real rows), bucket occupancy (real rows / padded bucket).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.admitted = 0
        self.completed = 0
        self.rejected_queue_full = 0
        self.rejected_deadline = 0
        self.rejected_shutdown = 0
        self.failed = 0
        self._latency_ms = collections.deque(maxlen=_RESERVOIR)
        self._batch_sizes = collections.deque(maxlen=_RESERVOIR)
        self._occupancy = collections.deque(maxlen=_RESERVOIR)

    def record_admitted(self):
        with self._lock:
            self.admitted += 1

    def record_rejected(self, reason: str):
        with self._lock:
            if reason == "queue_full":
                self.rejected_queue_full += 1
            elif reason == "deadline":
                self.rejected_deadline += 1
            elif reason == "shutdown":
                self.rejected_shutdown += 1
            else:
                raise ValueError(f"unknown rejection reason {reason!r}")

    def record_failed(self, n: int = 1):
        with self._lock:
            self.failed += n

    def record_batch(self, n_real: int, bucket: int):
        """One executed batch: `n_real` genuine requests padded to `bucket`."""
        with self._lock:
            self._batch_sizes.append(n_real)
            self._occupancy.append(n_real / bucket)

    def record_latency(self, ms: float, n: int = 1):
        with self._lock:
            self._latency_ms.append(ms)
            self.completed += n

    def latency_percentiles(self) -> dict[str, float]:
        with self._lock:
            lat = np.asarray(self._latency_ms, dtype=np.float64)
        if lat.size == 0:
            return {"p50_ms": float("nan"), "p99_ms": float("nan"),
                    "mean_ms": float("nan")}
        return {
            "p50_ms": float(np.percentile(lat, 50)),
            "p99_ms": float(np.percentile(lat, 99)),
            "mean_ms": float(lat.mean()),
        }

    def snapshot(self) -> dict:
        """Point-in-time summary (plain floats/ints — JSON-safe for bench)."""
        pct = self.latency_percentiles()
        with self._lock:
            sizes = np.asarray(self._batch_sizes, dtype=np.float64)
            occ = np.asarray(self._occupancy, dtype=np.float64)
            out = {
                "admitted": self.admitted,
                "completed": self.completed,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_deadline": self.rejected_deadline,
                "rejected_shutdown": self.rejected_shutdown,
                "failed": self.failed,
                "n_batches": int(sizes.size),
            }
        out.update(pct)
        out["mean_batch_size"] = float(sizes.mean()) if sizes.size else 0.0
        out["mean_occupancy"] = float(occ.mean()) if occ.size else 0.0
        return out

    def emit(self, writer, step: int, *, queue_depth: int | None = None,
             cache: dict | None = None) -> None:
        """Write the snapshot through an obs MetricWriter. `serve/` prefix
        keeps the tags clear of training scalars in a shared logdir."""
        snap = self.snapshot()
        for tag in ("p50_ms", "p99_ms", "mean_ms"):
            v = snap[tag]
            if v == v:  # skip NaN (no completed requests yet)
                writer.scalar(f"serve/latency_{tag}", v, step)
        for tag in ("admitted", "completed", "rejected_queue_full",
                    "rejected_deadline", "rejected_shutdown", "failed"):
            writer.scalar(f"serve/{tag}", snap[tag], step)
        writer.scalar("serve/mean_batch_size", snap["mean_batch_size"], step)
        writer.scalar("serve/mean_occupancy", snap["mean_occupancy"], step)
        if queue_depth is not None:
            writer.scalar("serve/queue_depth", queue_depth, step)
        if cache:
            writer.scalar("serve/cache_hits", cache.get("hits", 0), step)
            writer.scalar("serve/cache_misses", cache.get("misses", 0), step)
        with self._lock:
            sizes = list(self._batch_sizes)
            occ = list(self._occupancy)
        if sizes:
            writer.histogram("serve/batch_size", sizes, step)
            writer.histogram("serve/batch_occupancy", occ, step)
        writer.flush()
