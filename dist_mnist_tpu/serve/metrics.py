"""Serve-side metrics: latency percentiles, batch occupancy, queue depth,
admission counters.

Host-side and lock-guarded (the batcher thread and every client thread
record concurrently); nothing here touches a device. Emission goes through
the existing `obs.writers.MetricWriter` protocol so serve metrics land in
the same CSV/TensorBoard sinks as training metrics.

Percentiles come from `obs.hist.StreamingHistogram` ladders instead of
the old sample reservoirs: O(buckets) memory forever, mergeable across
replicas, and attachable to a `MetricRegistry` so a live `/metrics`
scrape sees the same distribution the final snapshot reports.
"""

from __future__ import annotations

import math
import threading

from dist_mnist_tpu.obs.hist import StreamingHistogram


class ServeMetrics:
    """Thread-safe accumulator for one server's lifetime.

    Counters:   admitted, completed, rejected_queue_full, rejected_deadline,
                rejected_shutdown, failed, cancelled.
    Histograms: request latency (ms, submit->result), executed batch sizes
                (real rows), bucket occupancy (real rows / padded bucket).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.admitted = 0
        self.completed = 0
        self.rejected_queue_full = 0
        self.rejected_deadline = 0
        self.rejected_shutdown = 0
        self.failed = 0
        self.cancelled = 0
        # own ladders per signal: latency spans µs..minutes; batch size is
        # small integers; occupancy lives in (0, 1]
        self.latency_ms = StreamingHistogram()
        self.batch_size = StreamingHistogram()
        self.batch_occupancy = StreamingHistogram()
        # zoo serving (serve/zoo.py): sequence-padding waste per executed
        # group (real tokens / padded tokens) and MoE routed-overflow drops
        # — both empty forever on a native-only dense engine
        self.seq_occupancy = StreamingHistogram()
        self.moe_drop_fraction = StreamingHistogram()
        # int8 weight-only serving (ops/quant.py): per-leaf max-abs
        # quantization errors of the load-time conversion, recorded once
        # per report; empty forever on a float engine
        self.quant_error = StreamingHistogram()
        self.quant_error_max: float | None = None

    def attach_to(self, registry) -> None:
        """Expose the live ladders on a MetricRegistry (-> /metrics)."""
        registry.attach_histogram("serve/latency_ms", self.latency_ms)
        registry.attach_histogram("serve/batch_size", self.batch_size)
        registry.attach_histogram("serve/batch_occupancy",
                                  self.batch_occupancy)
        registry.attach_histogram("serve/seq_occupancy", self.seq_occupancy)
        registry.attach_histogram("serve/moe_drop_fraction",
                                  self.moe_drop_fraction)
        registry.attach_histogram("serve/quant_error", self.quant_error)

    def record_quant_report(self, report: dict) -> None:
        """Fold an `ops.quant.error_report` in: one histogram observation
        per quantized leaf (max abs error), plus the scalar max. Called at
        server construction and again on each quantized hot-swap."""
        leaves = (report or {}).get("leaves", {})
        with self._lock:
            for stats in leaves.values():
                self.quant_error.observe(stats["max_abs_err"])
            m = (report or {}).get("max_abs_err")
            if m is not None:
                self.quant_error_max = max(self.quant_error_max or 0.0, m)

    def record_admitted(self):
        with self._lock:
            self.admitted += 1

    def record_rejected(self, reason: str):
        with self._lock:
            if reason == "queue_full":
                self.rejected_queue_full += 1
            elif reason == "deadline":
                self.rejected_deadline += 1
            elif reason == "shutdown":
                self.rejected_shutdown += 1
            else:
                raise ValueError(f"unknown rejection reason {reason!r}")

    def record_failed(self, n: int = 1):
        with self._lock:
            self.failed += n

    def record_cancelled(self, n: int = 1):
        with self._lock:
            self.cancelled += n

    @property
    def inflight(self) -> int:
        """Admitted requests whose futures have not settled yet (queued or
        mid-batch) — the quantity `InferenceServer.quiesce` waits on.
        Admission-level rejections never count as admitted, so the four
        settle paths (completed / expired / failed / cancelled) are
        exhaustive."""
        with self._lock:
            return self.admitted - (self.completed + self.rejected_deadline
                                    + self.failed + self.cancelled)

    def record_batch(self, n_real: int, bucket: int,
                     seq_occupancy: float | None = None,
                     moe_drop_fraction: float | None = None):
        """One executed batch: `n_real` genuine requests padded to `bucket`.
        `seq_occupancy` (real tokens / padded tokens, serve/zoo.py seq
        buckets) and `moe_drop_fraction` (routed-overflow drops of an MoE
        forward) ride along when the engine produces them."""
        self.batch_size.observe(n_real)
        self.batch_occupancy.observe(n_real / bucket)
        if seq_occupancy is not None:
            self.seq_occupancy.observe(seq_occupancy)
        if moe_drop_fraction is not None:
            self.moe_drop_fraction.observe(moe_drop_fraction)

    def record_latency(self, ms: float, n: int = 1):
        self.latency_ms.observe(ms)
        with self._lock:
            self.completed += n

    def latency_percentiles(self) -> dict[str, float]:
        s = self.latency_ms.snapshot()
        if not s["count"]:
            return {"p50_ms": float("nan"), "p95_ms": float("nan"),
                    "p99_ms": float("nan"), "mean_ms": float("nan")}
        return {"p50_ms": s["p50"], "p95_ms": s["p95"], "p99_ms": s["p99"],
                "mean_ms": s["mean"]}

    def snapshot(self) -> dict:
        """Point-in-time summary (plain floats/ints — JSON-safe for bench)."""
        pct = self.latency_percentiles()
        sizes = self.batch_size.snapshot()
        occ = self.batch_occupancy.snapshot()
        with self._lock:
            out = {
                "admitted": self.admitted,
                "completed": self.completed,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_deadline": self.rejected_deadline,
                "rejected_shutdown": self.rejected_shutdown,
                "failed": self.failed,
                "cancelled": self.cancelled,
                "n_batches": int(sizes["count"]),
            }
        out.update(pct)
        out["mean_batch_size"] = sizes["mean"] if sizes["count"] else 0.0
        out["mean_occupancy"] = occ["mean"] if occ["count"] else 0.0
        seq = self.seq_occupancy.snapshot()
        if seq["count"]:
            out["mean_seq_occupancy"] = seq["mean"]
        drop = self.moe_drop_fraction.snapshot()
        if drop["count"]:
            out["mean_moe_drop_fraction"] = drop["mean"]
            out["max_moe_drop_fraction"] = drop.get("max", drop["mean"])
        if self.quant_error_max is not None:
            out["quant_error_max"] = self.quant_error_max
        return out

    def emit(self, writer, step: int, *, queue_depth: int | None = None,
             cache: dict | None = None) -> None:
        """Write the snapshot through an obs MetricWriter. `serve/` prefix
        keeps the tags clear of training scalars in a shared logdir. All
        scalars go out as ONE batched `scalars()` call (the hook
        convention — one writer call per cadence, not ~12)."""
        snap = self.snapshot()
        vals: dict[str, float] = {}
        for tag in ("p50_ms", "p95_ms", "p99_ms", "mean_ms"):
            v = snap[tag]
            if not math.isnan(v):
                vals[f"serve/latency_{tag}"] = v
        for tag in ("admitted", "completed", "rejected_queue_full",
                    "rejected_deadline", "rejected_shutdown", "failed",
                    "cancelled"):
            vals[f"serve/{tag}"] = snap[tag]
        vals["serve/mean_batch_size"] = snap["mean_batch_size"]
        vals["serve/mean_occupancy"] = snap["mean_occupancy"]
        if "mean_seq_occupancy" in snap:
            vals["serve/mean_seq_occupancy"] = snap["mean_seq_occupancy"]
        if "mean_moe_drop_fraction" in snap:
            vals["serve/mean_moe_drop_fraction"] = \
                snap["mean_moe_drop_fraction"]
        if "quant_error_max" in snap:
            vals["serve/quant_error_max"] = snap["quant_error_max"]
        if queue_depth is not None:
            vals["serve/queue_depth"] = queue_depth
        if cache:
            vals["serve/cache_hits"] = cache.get("hits", 0)
            vals["serve/cache_misses"] = cache.get("misses", 0)
            if cache.get("evictions"):
                vals["serve/cache_evictions"] = cache["evictions"]
            if cache.get("resident_bytes"):
                vals["serve/resident_bytes"] = cache["resident_bytes"]
                # which tier the budget is spending on: the weights floor
                # vs the evictable executable set (PR12's combined gauge
                # hid the split)
                vals["serve/resident_bytes_weights"] = \
                    cache.get("resident_bytes_weights", 0)
                vals["serve/resident_bytes_executables"] = \
                    cache.get("resident_bytes_executables", 0)
        batch_write = getattr(writer, "scalars", None)
        if callable(batch_write):
            batch_write(vals, step)
        else:
            for k, v in vals.items():
                writer.scalar(k, v, step)
        if self.batch_size.count:
            writer.histogram("serve/batch_size",
                             self.batch_size.representative_values(), step)
            writer.histogram("serve/batch_occupancy",
                             self.batch_occupancy.representative_values(),
                             step)
        writer.flush()


class DecodeMetrics:
    """Thread-safe accumulator for one `serve.decode.DecodeScheduler`.

    Decode serving's two SLOs get their own signals (docs/OBSERVABILITY.md
    `serve/decode_*` rows): **TTFT** (submit -> first token, the
    latency_sensitive target) and **per-token throughput** (tokens /
    generation wall time, the best_effort target). Slot occupancy per
    decode step shows how full continuous batching keeps the machine —
    the static baseline's tail-off between batches is visible here.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.submitted_latency_sensitive = 0
        self.completed = 0
        self.rejected_queue_full = 0
        self.rejected_shutdown = 0
        self.failed = 0
        self.steps = 0
        self.tokens_out = 0
        self.ttft_ms = StreamingHistogram()
        self.tokens_per_s = StreamingHistogram()
        self.active_slots = StreamingHistogram()

    def attach_to(self, registry) -> None:
        """Expose the live ladders on a MetricRegistry (-> /metrics)."""
        registry.attach_histogram("serve/decode_ttft_ms", self.ttft_ms)
        registry.attach_histogram("serve/decode_tokens_per_s",
                                  self.tokens_per_s)
        registry.attach_histogram("serve/decode_active_slots",
                                  self.active_slots)

    def record_submitted(self, request_class: str):
        with self._lock:
            self.submitted += 1
            if request_class == "latency_sensitive":
                self.submitted_latency_sensitive += 1

    def record_rejected(self, reason: str):
        with self._lock:
            if reason == "queue_full":
                self.rejected_queue_full += 1
            elif reason == "shutdown":
                self.rejected_shutdown += 1
            else:
                raise ValueError(f"unknown rejection reason {reason!r}")

    def record_admitted(self, ttft_ms: float, request_class: str):
        self.ttft_ms.observe(ttft_ms)

    def record_completed(self, latency_ms: float, n_tokens: int,
                         tokens_per_s: float):
        self.tokens_per_s.observe(tokens_per_s)
        with self._lock:
            self.completed += 1
            self.tokens_out += n_tokens

    def record_failed(self, n: int = 1):
        with self._lock:
            self.failed += n

    def record_step(self, n_active: int):
        """One decode step with `n_active` live slots (of max_slots)."""
        self.active_slots.observe(n_active)
        with self._lock:
            self.steps += 1

    def snapshot(self) -> dict:
        """Point-in-time summary (plain floats/ints — JSON-safe for bench)."""
        ttft = self.ttft_ms.snapshot()
        tps = self.tokens_per_s.snapshot()
        act = self.active_slots.snapshot()
        with self._lock:
            out = {
                "submitted": self.submitted,
                "submitted_latency_sensitive":
                    self.submitted_latency_sensitive,
                "completed": self.completed,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_shutdown": self.rejected_shutdown,
                "failed": self.failed,
                "steps": self.steps,
                "tokens_out": self.tokens_out,
            }
        if ttft["count"]:
            out["ttft_p50_ms"] = ttft["p50"]
            out["ttft_p99_ms"] = ttft["p99"]
            out["ttft_mean_ms"] = ttft["mean"]
        if tps["count"]:
            out["tokens_per_s_p50"] = tps["p50"]
            out["tokens_per_s_mean"] = tps["mean"]
        out["mean_active_slots"] = act["mean"] if act["count"] else 0.0
        return out

    def emit(self, writer, step: int, *, queue_depth: int | None = None,
             cache: dict | None = None, kv: dict | None = None) -> None:
        """Write the snapshot through an obs MetricWriter — one batched
        `scalars()` call, same cadence convention as `ServeMetrics.emit`.
        `kv` is a `DecodeEngine.kv_stats()` dict; when given, the paged
        KV residency gauges (`serve/decode_kv_*`) ride along."""
        snap = self.snapshot()
        vals: dict[str, float] = {}
        vals["serve/decode_submitted"] = snap["submitted"]
        vals["serve/decode_completed"] = snap["completed"]
        vals["serve/decode_rejected_queue_full"] = \
            snap["rejected_queue_full"]
        vals["serve/decode_rejected_shutdown"] = snap["rejected_shutdown"]
        vals["serve/decode_failed"] = snap["failed"]
        vals["serve/decode_steps"] = snap["steps"]
        vals["serve/decode_tokens_out"] = snap["tokens_out"]
        vals["serve/decode_mean_active_slots"] = snap["mean_active_slots"]
        if "ttft_p50_ms" in snap:
            vals["serve/decode_ttft_p50_ms"] = snap["ttft_p50_ms"]
            vals["serve/decode_ttft_p99_ms"] = snap["ttft_p99_ms"]
        if "tokens_per_s_mean" in snap:
            vals["serve/decode_tokens_per_s"] = snap["tokens_per_s_mean"]
        if queue_depth is not None:
            vals["serve/decode_queue_depth"] = queue_depth
        if cache:
            vals["serve/cache_hits"] = cache.get("hits", 0)
            vals["serve/cache_misses"] = cache.get("misses", 0)
        if kv:
            vals["serve/decode_kv_pages_pinned"] = kv["kv_pages_pinned"]
            vals["serve/decode_kv_bytes_pinned"] = kv["kv_bytes_pinned"]
            vals["serve/decode_kv_bytes_pool"] = kv["kv_bytes_pool"]
        batch_write = getattr(writer, "scalars", None)
        if callable(batch_write):
            batch_write(vals, step)
        else:
            for k, v in vals.items():
                writer.scalar(k, v, step)
        if self.ttft_ms.count:
            writer.histogram("serve/decode_ttft_ms",
                             self.ttft_ms.representative_values(), step)
            writer.histogram("serve/decode_active_slots",
                             self.active_slots.representative_values(), step)
        writer.flush()
