"""InferenceServer: the facade wiring admission -> batcher -> engine and
owning the shutdown order.

Lifecycle contract (the part worth being strict about):

    start():  prewarm every bucket (optional but default — a compile inside
              live traffic is a p99 hole), then start the batcher thread.
    submit(): admission only; raises QueueFullError / ShuttingDownError
              rather than ever blocking a client.
    close():  (1) close admission — new submits rejected with a clear
              shutdown signal; (2) drain — the batcher finishes every
              already-admitted request; (3) emit final metrics. In-flight
              work is never dropped on the floor: a client holding a Future
              from a successful submit() WILL get a result (or an engine
              error), shutdown or not.
"""

from __future__ import annotations

import dataclasses
import logging

from dist_mnist_tpu.serve.admission import AdmissionQueue
from dist_mnist_tpu.serve.batcher import DynamicBatcher
from dist_mnist_tpu.serve.engine import InferenceEngine
from dist_mnist_tpu.serve.metrics import ServeMetrics

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    max_batch: int = 64  # coalesce ceiling; beyond engine max_bucket the
    # batcher splits the window into bucket-sized executions
    max_wait_ms: float = 2.0  # coalesce window opened by the first request
    queue_depth: int = 256  # admission bound; beyond it -> QueueFullError
    default_deadline_ms: float | None = None  # per-request override wins
    prewarm: bool = True  # compile the (batch, height) grid before serving
    prewarm_async: bool = False  # warm the grid on a background
    # "ZooPrewarm" thread while traffic is already served: first requests
    # may pay an on-demand compile, but startup latency stays flat as the
    # 2-D zoo grid multiplies the cell count (serve/zoo.py). The thread is
    # joined by close(); a budget refusal surfaces in stats()["prewarm_error"]


class InferenceServer:
    def __init__(self, engine: InferenceEngine, config: ServeConfig | None = None,
                 *, writer=None, health=None):
        self.engine = engine
        self.config = config or ServeConfig()
        self.metrics = ServeMetrics()
        # a quantized engine's load-time error report becomes the
        # serve/quant_error* metrics surface right away
        report = getattr(engine, "quant_report", None)
        if report:
            self.metrics.record_quant_report(report)
        self.writer = writer
        # live /healthz state machine (obs/exporter.HealthState or None):
        # serving after start(), draining during close() — so a router can
        # stop sending to this replica before it disappears
        self.health = health
        self._admission = AdmissionQueue(self.config.queue_depth, self.metrics)
        self._batcher = DynamicBatcher(
            engine, self._admission, self.metrics,
            max_batch=self.config.max_batch,
            max_wait_ms=self.config.max_wait_ms,
        )
        self._started = False
        self._closed = False
        self._prewarm_thread: "threading.Thread | None" = None
        self._prewarm_error: Exception | None = None

    # -- lifecycle -----------------------------------------------------------
    def _prewarm_buckets(self) -> list[int]:
        return [b for b in self.engine.buckets()
                if b <= max(self.config.max_batch, self.engine.min_bucket)]

    def _prewarm(self) -> None:
        try:
            n = self.engine.prewarm(self._prewarm_buckets())
            log.info("prewarmed %d executable(s) over buckets %s", n,
                     self.engine.buckets())
        except Exception as err:  # surface via stats(); keep serving dense
            log.exception("background prewarm failed")
            self._prewarm_error = err

    def start(self) -> "InferenceServer":
        if self._started:
            return self
        if self.config.prewarm:
            if self.config.prewarm_async:
                import threading

                self._prewarm_thread = threading.Thread(
                    target=self._prewarm, name="ZooPrewarm", daemon=True)
                self._prewarm_thread.start()
            else:
                n = self.engine.prewarm(self._prewarm_buckets())
                log.info("prewarmed %d executable(s) over buckets %s", n,
                         self.engine.buckets())
        self._batcher.start()
        self._started = True
        if self.health is not None:
            self.health.set("serving")
        from dist_mnist_tpu.obs import events

        events.emit("serve_start", prewarm=self.config.prewarm,
                    max_batch=self.config.max_batch)
        return self

    def close(self, *, timeout: float = 30.0) -> bool:
        """Reject-new, finish-old; idempotent. Returns drain success."""
        if self._closed:
            return True
        from dist_mnist_tpu.obs import events

        if self.health is not None:
            self.health.set("draining")
        if self._prewarm_thread is not None:
            # bounded join: an in-flight compile finishes, then the thread
            # exits — close() never leaks a ZooPrewarm thread past itself
            self._prewarm_thread.join(timeout=timeout)
            self._prewarm_thread = None
        self._admission.close()
        ok = self._batcher.drain(timeout=timeout) if self._started else True
        if not ok:
            log.error("batcher did not drain within %.1fs", timeout)
        self._closed = True
        if self.writer is not None:
            self.emit_metrics(self.writer)
        if self.health is not None:
            self.health.set("stopped", "drained" if ok else "drain timeout")
        events.emit("serve_stop", drained=ok,
                    completed=self.metrics.completed)
        return ok

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- serving -------------------------------------------------------------
    def submit(self, image, *, deadline_ms: float | None = None,
               cancel_event=None):
        """One request -> Future[InferenceResult]. Never blocks."""
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        return self._admission.submit(image, deadline_ms=deadline_ms,
                                      cancel_event=cancel_event)

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Wait (bounded) until every ADMITTED request has settled, without
        closing anything — the hot-swap drain step (serve/router.py's
        drain->swap->rewarm) needs an empty pipeline while the server stays
        open for the traffic that resumes after the swap. The caller must
        stop submitting first (the router stops routing to a `swapping`
        replica); otherwise new admissions keep the pipeline non-idle and
        this simply times out. Returns True when idle."""
        import time as _t

        deadline = _t.monotonic() + timeout
        while _t.monotonic() < deadline:
            if self.metrics.inflight == 0 and self.queue_depth == 0:
                return True
            _t.sleep(0.002)
        return False

    # -- observability -------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return self._admission.depth

    @property
    def capacity(self) -> int:
        """Admission bound — the denominator of a router's backlog fraction."""
        return self._admission.maxsize

    def stats(self) -> dict:
        out = self.metrics.snapshot()
        out["queue_depth"] = self.queue_depth
        out["cache"] = self.engine.cache.stats()
        if getattr(self.engine, "quant", None):
            out["quant"] = self.engine.quant
        if self._prewarm_error is not None:
            out["prewarm_error"] = repr(self._prewarm_error)
        return out

    def emit_metrics(self, writer, step: int = 0) -> None:
        self.metrics.emit(writer, step, queue_depth=self.queue_depth,
                          cache=self.engine.cache.stats())
