"""Admission control: the bounded queue between clients and the batcher.

Overload policy is *reject-new, finish-old*: a full queue refuses the new
request immediately (`QueueFullError`) instead of growing an unbounded
backlog whose tail would time out anyway — the client gets a clear signal
to back off NOW, and every admitted request still has a bounded wait. This
is the serving analogue of the training side's bounded host->device
prefetch (data/pipeline.py): memory use is fixed, pressure is explicit.

Deadlines are per-request and checked at *dequeue* time by the batcher: a
request that waited past its deadline is expired (its future raises
`DeadlineExceededError`) rather than executed — computing an answer the
client has already abandoned wastes a batch slot someone live could use.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future

import numpy as np


class QueueFullError(RuntimeError):
    """Rejected at admission: the bounded queue is full — back off."""


class ShuttingDownError(RuntimeError):
    """Rejected at admission: the server is draining and accepts no new work."""


class DeadlineExceededError(TimeoutError):
    """Admitted, but expired in queue before execution."""


@dataclasses.dataclass
class Request:
    image: np.ndarray
    future: Future
    t_submit: float  # time.monotonic() at admission
    deadline: float | None  # absolute monotonic instant, None = no deadline
    # optional threading.Event a router sets to withdraw the request (hedge
    # loser cancellation); checked at dequeue like the deadline
    cancel_event: threading.Event | None = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    @property
    def cancelled(self) -> bool:
        return self.cancel_event is not None and self.cancel_event.is_set()


@dataclasses.dataclass
class InferenceResult:
    logits: np.ndarray  # [classes]
    label: int
    latency_ms: float


class AdmissionQueue:
    """Bounded MPSC queue: many client threads submit, one batcher drains."""

    def __init__(self, depth: int, metrics):
        if depth < 1:
            raise ValueError("queue depth must be >= 1")
        self._q: queue.Queue[Request] = queue.Queue(maxsize=depth)
        self._metrics = metrics
        self._closed = threading.Event()

    def submit(self, image: np.ndarray, *,
               deadline_ms: float | None = None,
               cancel_event: threading.Event | None = None) -> Future:
        """Admit one request; returns a Future resolving to an
        InferenceResult. Raises instead of blocking when the server is
        draining or the queue is full — admission never stalls a client."""
        if self._closed.is_set():
            self._metrics.record_rejected("shutdown")
            raise ShuttingDownError("server is draining; request rejected")
        now = time.monotonic()
        req = Request(
            image=np.asarray(image),
            future=Future(),
            t_submit=now,
            deadline=now + deadline_ms / 1e3 if deadline_ms is not None else None,
            cancel_event=cancel_event,
        )
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._metrics.record_rejected("queue_full")
            raise QueueFullError(
                f"admission queue full ({self._q.maxsize}); back off"
            ) from None
        self._metrics.record_admitted()
        return req.future

    def get(self, timeout: float) -> Request | None:
        """One request, or None after `timeout` seconds of empty queue."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def get_nowait(self) -> Request | None:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def close(self) -> None:
        """Stop admitting. Already-queued requests stay and will be drained."""
        self._closed.set()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    @property
    def depth(self) -> int:
        return self._q.qsize()

    @property
    def maxsize(self) -> int:
        return self._q.maxsize
