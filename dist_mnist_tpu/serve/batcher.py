"""The continuous dynamic batcher: one daemon thread coalescing admitted
single-example requests into padded engine batches.

Policy (continuous batching, not fixed-window): the FIRST request out of
the queue opens a coalesce window; the batcher then drains whatever else
is already queued and keeps waiting for stragglers until either
`max_batch` requests are in hand or `max_wait_ms` has elapsed since the
window opened — so an idle server answers a lone request with ~zero added
latency (the window closes the moment the queue is empty AND the deadline
passed), while a loaded server fills big buckets back-to-back without any
fixed ticking cadence. Expired requests are dropped at dequeue (admission
.py's deadline contract) and never occupy a batch slot.

Single consumer by design: the device executes one batch at a time anyway
(per mesh), so one thread removes every locking question from the hot
path. Failure isolation: an engine exception fails the *batch's* futures,
not the server — the loop keeps serving.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import CancelledError

import numpy as np

from dist_mnist_tpu.serve.admission import (
    AdmissionQueue,
    DeadlineExceededError,
    InferenceResult,
    Request,
)

log = logging.getLogger(__name__)

# how long the idle loop blocks on an empty queue before re-checking the
# stop flag; latency-invisible (a request arriving mid-block wakes the get)
_IDLE_POLL_SECS = 0.05


class DynamicBatcher:
    def __init__(self, engine, admission: AdmissionQueue, metrics, *,
                 max_batch: int = 64, max_wait_ms: float = 2.0):
        # max_batch MAY exceed the engine's max_bucket: an oversized
        # coalesce window is split into max_bucket-sized engine batches at
        # execution (engine.bucket_for's raise remains for DIRECT predict
        # calls that exceed the ceiling in one go)
        self.engine = engine
        self.admission = admission
        self.metrics = metrics
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1e3
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="ServeBatcher", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    # -- collection ----------------------------------------------------------
    def _collect(self) -> list[Request]:
        """Block for a first request, then coalesce until max_batch or the
        window deadline. Returns [] on an idle timeout (caller re-loops)."""
        first = self.admission.get(timeout=_IDLE_POLL_SECS)
        if first is None:
            return []
        batch = [first]
        window_ends = time.monotonic() + self.max_wait
        while len(batch) < self.max_batch:
            remaining = window_ends - time.monotonic()
            if remaining <= 0:
                break
            req = self.admission.get_nowait()
            if req is None:
                # nothing queued right now — wait out the window's remainder
                # for stragglers (but not past it)
                req = self.admission.get(timeout=remaining)
                if req is None:
                    break
            batch.append(req)
        return batch

    # -- execution -----------------------------------------------------------
    def _run_batch(self, batch: list[Request]) -> None:
        now = time.monotonic()
        live: list[Request] = []
        for req in batch:
            if req.cancelled:
                # hedge loser withdrawn before execution (admission.py's
                # cancel_event contract): never occupies a batch slot
                self.metrics.record_cancelled()
                req.future.set_exception(CancelledError(
                    "request cancelled before execution"))
            elif req.expired(now):
                self.metrics.record_rejected("deadline")
                req.future.set_exception(DeadlineExceededError(
                    f"expired in queue after "
                    f"{(now - req.t_submit) * 1e3:.1f} ms"))
            else:
                live.append(req)
        if not live:
            return
        # variable-length serving: one engine batch per image shape (the
        # engine pads each group to its own (batch, height) grid cell —
        # stacking mixed heights is impossible anyway), preserving
        # submission order within a group. An oversized window — max_batch
        # beyond the engine's bucket ceiling — is split here into
        # max_bucket-sized executions instead of bucket_for raising.
        groups: dict[tuple, list[Request]] = {}
        for req in live:
            groups.setdefault(tuple(req.image.shape), []).append(req)
        for reqs in groups.values():
            for i in range(0, len(reqs), self.engine.max_bucket):
                self._execute(reqs[i:i + self.engine.max_bucket])

    def _execute(self, reqs: list[Request]) -> None:
        """One engine call for same-shaped `reqs` (<= max_bucket of them)."""
        try:
            images = np.stack([r.image for r in reqs])
            logits = self.engine.predict(images)
        except Exception as err:  # fail the batch, keep the server
            log.exception("batch of %d failed", len(reqs))
            self.metrics.record_failed(len(reqs))
            for req in reqs:
                req.future.set_exception(err)
            return
        done = time.monotonic()
        self.metrics.record_batch(
            len(reqs), self.engine.bucket_for(len(reqs)),
            seq_occupancy=self._seq_occupancy(images),
            moe_drop_fraction=getattr(
                self.engine, "last_moe_drop_fraction", None))
        for req, row in zip(reqs, logits):
            latency_ms = (done - req.t_submit) * 1e3
            self.metrics.record_latency(latency_ms)
            req.future.set_result(InferenceResult(
                logits=row, label=int(row.argmax()), latency_ms=latency_ms))

    def _seq_occupancy(self, images) -> float | None:
        """Real tokens / padded tokens for one executed group, None for a
        native-only engine (no sequence padding to attribute)."""
        grid = getattr(self.engine, "seq_grid", None)
        if grid is None:
            return None
        h = images.shape[1]
        bucket_h = self.engine.seq_bucket_for(h)
        return grid.n_tokens(h) / grid.n_tokens(bucket_h)

    def _loop(self) -> None:
        while True:
            batch = self._collect()
            if batch:
                self._run_batch(batch)
            elif self._stop.is_set() and self.admission.depth == 0:
                return

    # -- shutdown ------------------------------------------------------------
    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful stop: finish everything already admitted, then exit the
        loop. The admission queue must be closed FIRST (server.py does) or
        new submits could race the drain forever. Returns False if the
        thread didn't exit within `timeout` (batch wedged in the engine)."""
        self._stop.set()
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()
