"""Deterministic closed-loop load generator.

Closed loop with a fixed concurrency window: at most `concurrency`
requests are in flight; each completion (via Future callback) releases a
slot for the next submit. That makes offered load self-clocking — the
generator pushes exactly as hard as the server can absorb plus a full
window, which is what exercises the batcher's coalescing (many requests
genuinely simultaneous) without the arrival-time nondeterminism of an
open-loop Poisson process. Images are a fixed seeded uint8 pool, so every
run of the same (seed, n_requests) submits byte-identical inputs in the
same order.

Used by three consumers with one definition: `scripts/serve_loadgen.py`
(CLI), `bench.py --serve` (the serve_p99_latency_ms BENCH metric), and
tests/test_serve.py (the acceptance path).
"""

from __future__ import annotations

import threading

import numpy as np

from dist_mnist_tpu.serve.admission import (
    DeadlineExceededError,
    QueueFullError,
    ShuttingDownError,
)

# fixed input pool size: big enough to defeat any value-level caching,
# small enough to keep generation instant
_POOL = 256


def make_images(image_shape: tuple[int, ...], seed: int = 0,
                n: int = _POOL) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, *image_shape), dtype=np.uint8)


def run_loadgen(
    server,
    *,
    n_requests: int,
    concurrency: int,
    image_shape: tuple[int, ...],
    seed: int = 0,
    deadline_ms: float | None = None,
    timeout: float = 120.0,
) -> dict:
    """Drive `server` and return a summary dict (latency percentiles,
    rejection counts, batching stats, cache stats). Deterministic inputs;
    raises on a hung run rather than reporting partial numbers."""
    images = make_images(image_shape, seed=seed)
    window = threading.Semaphore(concurrency)
    futures = []
    rejected_queue_full = 0
    rejected_shutdown = 0

    for i in range(n_requests):
        window.acquire()
        try:
            fut = server.submit(images[i % len(images)],
                                deadline_ms=deadline_ms)
        except QueueFullError:
            rejected_queue_full += 1
            window.release()
            continue
        except ShuttingDownError:
            rejected_shutdown += 1
            window.release()
            continue
        fut.add_done_callback(lambda _f: window.release())
        futures.append(fut)

    ok = 0
    deadline_expired = 0
    errors = 0
    latencies = []
    for fut in futures:
        try:
            res = fut.result(timeout=timeout)
        except DeadlineExceededError:
            deadline_expired += 1
            continue
        except Exception:
            errors += 1
            continue
        ok += 1
        latencies.append(res.latency_ms)

    lat = np.asarray(latencies, dtype=np.float64)
    summary = {
        "n_requests": n_requests,
        "concurrency": concurrency,
        "ok": ok,
        "rejected_queue_full": rejected_queue_full,
        "rejected_shutdown": rejected_shutdown,
        "deadline_expired": deadline_expired,
        "errors": errors,
        "p50_ms": float(np.percentile(lat, 50)) if lat.size else float("nan"),
        "p95_ms": float(np.percentile(lat, 95)) if lat.size else float("nan"),
        "p99_ms": float(np.percentile(lat, 99)) if lat.size else float("nan"),
        "mean_ms": float(lat.mean()) if lat.size else float("nan"),
    }
    stats = server.stats()
    summary["mean_batch_size"] = stats["mean_batch_size"]
    summary["mean_occupancy"] = stats["mean_occupancy"]
    summary["n_batches"] = stats["n_batches"]
    summary["cache"] = stats["cache"]
    return summary
