"""Deterministic closed-loop load generator.

Closed loop with a fixed concurrency window: at most `concurrency`
requests are in flight; each completion (via Future callback) releases a
slot for the next submit. That makes offered load self-clocking — the
generator pushes exactly as hard as the server can absorb plus a full
window, which is what exercises the batcher's coalescing (many requests
genuinely simultaneous) without the arrival-time nondeterminism of an
open-loop Poisson process. Images are a fixed seeded uint8 pool, so every
run of the same (seed, n_requests) submits byte-identical inputs in the
same order.

Used by three consumers with one definition: `scripts/serve_loadgen.py`
(CLI), `bench.py --serve` (the serve_p99_latency_ms BENCH metric), and
tests/test_serve.py (the acceptance path).

`run_fleet_loadgen` is the two-class variant for a `serve/router.py`
Router: a seeded latency_sensitive/best_effort class sequence with
per-class deadlines, and per-class accounting that separates the
outcomes the tier policy is allowed to produce (best_effort shed) from
the ones it must not (latency_sensitive errors or drops).
"""

from __future__ import annotations

import threading
from concurrent.futures import TimeoutError as _FuturesTimeout

import numpy as np

from dist_mnist_tpu.serve.admission import (
    DeadlineExceededError,
    QueueFullError,
    ShuttingDownError,
)

# fixed input pool size: big enough to defeat any value-level caching,
# small enough to keep generation instant
_POOL = 256


def make_images(image_shape: tuple[int, ...], seed: int = 0,
                n: int = _POOL) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, *image_shape), dtype=np.uint8)


def run_loadgen(
    server,
    *,
    n_requests: int,
    concurrency: int,
    image_shape: tuple[int, ...],
    seed: int = 0,
    deadline_ms: float | None = None,
    timeout: float = 120.0,
) -> dict:
    """Drive `server` and return a summary dict (latency percentiles,
    rejection counts, batching stats, cache stats). Deterministic inputs;
    raises on a hung run rather than reporting partial numbers."""
    images = make_images(image_shape, seed=seed)
    window = threading.Semaphore(concurrency)
    futures = []
    rejected_queue_full = 0
    rejected_shutdown = 0

    for i in range(n_requests):
        window.acquire()
        try:
            fut = server.submit(images[i % len(images)],
                                deadline_ms=deadline_ms)
        except QueueFullError:
            rejected_queue_full += 1
            window.release()
            continue
        except ShuttingDownError:
            rejected_shutdown += 1
            window.release()
            continue
        fut.add_done_callback(lambda _f: window.release())
        futures.append(fut)

    ok = 0
    deadline_expired = 0
    errors = 0
    latencies = []
    for fut in futures:
        try:
            res = fut.result(timeout=timeout)
        except DeadlineExceededError:
            deadline_expired += 1
            continue
        except Exception:
            errors += 1
            continue
        ok += 1
        latencies.append(res.latency_ms)

    lat = np.asarray(latencies, dtype=np.float64)
    summary = {
        "n_requests": n_requests,
        "concurrency": concurrency,
        "ok": ok,
        "rejected_queue_full": rejected_queue_full,
        "rejected_shutdown": rejected_shutdown,
        "deadline_expired": deadline_expired,
        "errors": errors,
        "p50_ms": float(np.percentile(lat, 50)) if lat.size else float("nan"),
        "p95_ms": float(np.percentile(lat, 95)) if lat.size else float("nan"),
        "p99_ms": float(np.percentile(lat, 99)) if lat.size else float("nan"),
        "mean_ms": float(lat.mean()) if lat.size else float("nan"),
    }
    stats = server.stats()
    summary["mean_batch_size"] = stats["mean_batch_size"]
    summary["mean_occupancy"] = stats["mean_occupancy"]
    summary["n_batches"] = stats["n_batches"]
    summary["cache"] = stats["cache"]
    return summary


def make_varlen_images(image_shape: tuple[int, ...], patch: int,
                       seed: int = 0, n: int = _POOL) -> list[np.ndarray]:
    """Seeded pool of variable-HEIGHT images for the zoo's long-context
    path: each entry's height is a patch-multiple drawn uniformly from
    [patch, native], width/channels fixed. Patch-multiple heights keep
    every patch token fully real (models/vit.py's VALID patch conv would
    otherwise mix real and pad pixels inside one token)."""
    native_h = image_shape[0]
    rest = tuple(image_shape[1:])
    rng = np.random.default_rng(seed)
    ks = rng.integers(1, native_h // patch + 1, size=n)
    return [rng.integers(0, 256, size=(int(k) * patch, *rest),
                         dtype=np.uint8) for k in ks]


def run_longctx_loadgen(
    server,
    *,
    n_requests: int,
    concurrency: int,
    seed: int = 0,
    deadline_ms: float | None = None,
    timeout: float = 240.0,
) -> dict:
    """`run_loadgen` for a zoo engine's 2-D grid: variable-height seeded
    traffic, plus the per-seq-bucket routing counters and compile-cache
    hit/miss deltas that prove the grid absorbed every shape without a
    hot-path recompile. Requires `server.engine.seq_grid`."""
    grid = getattr(server.engine, "seq_grid", None)
    if grid is None:
        raise ValueError("run_longctx_loadgen needs a seq-grid engine "
                         "(serve/zoo.py build_zoo_engine seq_buckets=...)")
    images = make_varlen_images(
        (grid.native_height, grid.width, grid.channels), grid.patch,
        seed=seed)
    cache0 = server.engine.cache.stats()
    buckets0 = dict(server.engine.seq_bucket_counts)
    window = threading.Semaphore(concurrency)
    futures = []
    rejected_queue_full = 0
    rejected_shutdown = 0

    for i in range(n_requests):
        window.acquire()
        try:
            fut = server.submit(images[i % len(images)],
                                deadline_ms=deadline_ms)
        except QueueFullError:
            rejected_queue_full += 1
            window.release()
            continue
        except ShuttingDownError:
            rejected_shutdown += 1
            window.release()
            continue
        fut.add_done_callback(lambda _f: window.release())
        futures.append(fut)

    ok = 0
    deadline_expired = 0
    errors = 0
    latencies = []
    for fut in futures:
        try:
            res = fut.result(timeout=timeout)
        except DeadlineExceededError:
            deadline_expired += 1
            continue
        except Exception:
            errors += 1
            continue
        ok += 1
        latencies.append(res.latency_ms)

    summary = _pct(np.asarray(latencies, dtype=np.float64))
    summary.update(
        n_requests=n_requests,
        concurrency=concurrency,
        ok=ok,
        rejected_queue_full=rejected_queue_full,
        rejected_shutdown=rejected_shutdown,
        deadline_expired=deadline_expired,
        errors=errors,
    )
    cache1 = server.engine.cache.stats()
    summary["cache"] = cache1
    # compiles that happened DURING the timed traffic — 0 after a full
    # prewarm is the zoo's no-recompile guarantee
    summary["recompiles_during_traffic"] = \
        cache1["misses"] - cache0["misses"]
    counts = server.engine.seq_bucket_counts
    summary["seq_bucket_counts"] = {
        str(h): counts.get(h, 0) - buckets0.get(h, 0)
        for h in grid.heights
        if counts.get(h, 0) - buckets0.get(h, 0)
    }
    stats = server.stats()
    summary["mean_batch_size"] = stats["mean_batch_size"]
    summary["mean_occupancy"] = stats["mean_occupancy"]
    summary["mean_seq_occupancy"] = stats.get("mean_seq_occupancy", 1.0)
    summary["n_batches"] = stats["n_batches"]
    return summary


# -- trace-driven (open-loop) arrival processes -------------------------------
#
# The closed-loop generators above measure what a fleet CAN absorb; the
# autoscaler (serve/autoscale.py) needs the opposite: traffic that arrives
# on ITS schedule whether or not the fleet keeps up, so under-provisioning
# shows up as backlog/shed/latency instead of silently slowing the offered
# load. All three generators share one deterministic clock — a common
# rate-envelope integrator: arrival k lands where the cumulative intensity
# crosses ``k + u_k`` (u_k a seeded uniform jitter). The arrival COUNT in
# any window is therefore a pure function of the envelope (seed moves each
# arrival by less than one intensity unit), which is what lets tests pin
# rate envelopes exactly, and two runs with one seed submit byte-identical
# traffic at identical offsets — the precondition for the static-vs-
# autoscaled economics comparison in `bench.py --serve --autoscale`.

def _arrival_times(rate_fn, duration_s: float, seed: int,
                   dt: float = 0.005) -> np.ndarray:
    """Deterministic inhomogeneous arrival process: integrate the rate
    envelope (requests/sec over trace seconds) on a fixed grid and place
    arrival k at the instant the cumulative intensity crosses k + u_k."""
    grid = np.arange(0.0, duration_s + dt, dt)
    rates = np.maximum(np.asarray(rate_fn(grid), dtype=np.float64), 0.0)
    cum = np.concatenate(
        [[0.0], np.cumsum((rates[1:] + rates[:-1]) * 0.5 * dt)])
    n = int(np.floor(cum[-1]))
    rng = np.random.default_rng(seed)
    targets = np.arange(n) + rng.random(n)
    return np.interp(targets, cum, grid)


def diurnal_trace(*, duration_s: float, base_rps: float, peak_rps: float,
                  period_s: float | None = None, seed: int = 0) -> np.ndarray:
    """Sinusoidal daily wave compressed into ``period_s`` (default: one
    full period over the trace): trough ``base_rps`` at t=0, crest
    ``peak_rps`` mid-period."""
    period = float(period_s) if period_s is not None else float(duration_s)

    def rate(t):
        return base_rps + (peak_rps - base_rps) * 0.5 * (
            1.0 - np.cos(2.0 * np.pi * t / period))

    return _arrival_times(rate, duration_s, seed)


def burst_trace(*, duration_s: float, base_rps: float, burst_rps: float,
                burst_every_s: float, burst_len_s: float,
                seed: int = 0) -> np.ndarray:
    """Square-wave bursts: ``burst_rps`` for the first ``burst_len_s`` of
    every ``burst_every_s`` period, ``base_rps`` between."""

    def rate(t):
        return np.where(np.mod(t, burst_every_s) < burst_len_s,
                        burst_rps, base_rps)

    return _arrival_times(rate, duration_s, seed)


def flash_crowd_trace(*, duration_s: float, base_rps: float,
                      spike_at_s: float, spike_len_s: float,
                      spike_mult: float = 10.0, decay_s: float = 2.0,
                      seed: int = 0) -> np.ndarray:
    """Baseline -> a ``spike_mult``x flash crowd of ``spike_len_s`` ->
    linear decay back to baseline over ``decay_s``."""
    peak = base_rps * spike_mult

    def rate(t):
        t = np.asarray(t, dtype=np.float64)
        frac = np.clip(1.0 - (t - spike_at_s - spike_len_s) / decay_s,
                       0.0, 1.0)
        r = np.full_like(t, base_rps)
        r = np.where(t >= spike_at_s + spike_len_s,
                     base_rps + (peak - base_rps) * frac, r)
        return np.where((t >= spike_at_s) & (t < spike_at_s + spike_len_s),
                        peak, r)

    return _arrival_times(rate, duration_s, seed)


def run_trace_loadgen(
    router,
    *,
    arrivals: np.ndarray,
    image_shape: tuple[int, ...],
    seed: int = 0,
    ls_fraction: float = 0.8,
    ls_deadline_ms: float | None = None,
    be_deadline_ms: float | None = None,
    time_scale: float = 1.0,
    timeout: float = 180.0,
    keep_latencies: bool = False,
) -> dict:
    """Open-loop `run_fleet_loadgen`: submit on the TRACE's schedule.

    ``arrivals`` is a sorted array of trace-time offsets (seconds) from
    one of the generators above; ``time_scale`` maps trace seconds onto
    wall seconds (0.5 replays a trace at double speed). A generator that
    falls behind wall time submits immediately — burst catch-up is the
    point of open loop. Outcome taxonomy and summary shape match
    `run_fleet_loadgen`, plus the trace envelope under ``"trace"``."""
    import time as _t

    from dist_mnist_tpu.serve.errors import AllReplicasDownError, ShedError
    from dist_mnist_tpu.serve.router import (
        BEST_EFFORT,
        LATENCY_SENSITIVE,
        REQUEST_CLASSES,
    )

    arrivals = np.asarray(arrivals, dtype=np.float64)
    n_requests = int(arrivals.size)
    images = make_images(image_shape, seed=seed)
    rng = np.random.default_rng(seed)
    classes = np.where(rng.random(n_requests) < ls_fraction,
                       LATENCY_SENSITIVE, BEST_EFFORT)
    deadline_for = {LATENCY_SENSITIVE: ls_deadline_ms,
                    BEST_EFFORT: be_deadline_ms}
    futures: list = []  # (class, future)
    shed = {c: 0 for c in REQUEST_CLASSES}
    rejected = {c: 0 for c in REQUEST_CLASSES}

    t0 = _t.monotonic()
    for i in range(n_requests):
        wait = t0 + arrivals[i] * time_scale - _t.monotonic()
        if wait > 0:
            _t.sleep(wait)
        cls = str(classes[i])
        try:
            fut = router.submit(images[i % len(images)], request_class=cls,
                                deadline_ms=deadline_for[cls])
        except ShedError:
            shed[cls] += 1
            continue
        except (QueueFullError, ShuttingDownError, AllReplicasDownError):
            rejected[cls] += 1
            continue
        futures.append((cls, fut))
    submit_wall_s = _t.monotonic() - t0

    gather_deadline = _t.monotonic() + timeout
    ok = {c: 0 for c in REQUEST_CLASSES}
    deadline_expired = {c: 0 for c in REQUEST_CLASSES}
    errors = {c: 0 for c in REQUEST_CLASSES}
    dropped = {c: 0 for c in REQUEST_CLASSES}
    latencies = {c: [] for c in REQUEST_CLASSES}
    for cls, fut in futures:
        remaining = gather_deadline - _t.monotonic()
        try:
            res = fut.result(timeout=max(remaining, 0.001))
        except DeadlineExceededError:
            deadline_expired[cls] += 1
            continue
        except (TimeoutError, _FuturesTimeout):
            dropped[cls] += 1
            continue
        except Exception:
            errors[cls] += 1
            continue
        ok[cls] += 1
        latencies[cls].append(res.latency_ms)

    summary: dict = {
        "n_requests": n_requests,
        "ls_fraction": ls_fraction,
        "offered": {c: int((classes == c).sum()) for c in REQUEST_CLASSES},
        "ok": ok,
        "shed": shed,
        "rejected": rejected,
        "deadline_expired": deadline_expired,
        "errors": errors,
        "dropped": dropped,
        "trace": {
            "n_arrivals": n_requests,
            "duration_s": (round(arrivals[-1] * time_scale, 3)
                           if n_requests else 0.0),
            "time_scale": time_scale,
            "submit_wall_s": round(submit_wall_s, 3),
        },
    }
    for cls in REQUEST_CLASSES:
        summary[f"latency_{cls}"] = _pct(
            np.asarray(latencies[cls], dtype=np.float64))
    summary["total_ok"] = sum(ok.values())
    summary["router"] = router.metrics.snapshot()
    if keep_latencies:
        summary["raw_latencies"] = {c: list(latencies[c])
                                    for c in REQUEST_CLASSES}
    return summary


def _pct(lat: np.ndarray) -> dict:
    if not lat.size:
        return {"p50_ms": float("nan"), "p95_ms": float("nan"),
                "p99_ms": float("nan"), "mean_ms": float("nan")}
    return {
        "p50_ms": float(np.percentile(lat, 50)),
        "p95_ms": float(np.percentile(lat, 95)),
        "p99_ms": float(np.percentile(lat, 99)),
        "mean_ms": float(lat.mean()),
    }


def make_prompts(n: int, *, max_seq: int, seed: int = 0,
                 min_prompt: int = 2, max_prompt: int | None = None,
                 min_new: int = 1, max_new: int | None = None,
                 vocab_size: int = 256):
    """Seeded decode traffic: `n` (prompt, max_new_tokens) pairs whose
    prompt lengths, token values, and output lengths are a fixed function
    of the arguments — two runs (or two scheduling modes) see
    byte-identical requests in the same order, the precondition for the
    stream-identity comparison. Lengths always satisfy
    ``prompt + max_new <= max_seq``."""
    if max_prompt is None:
        max_prompt = max(min_prompt, max_seq // 2)
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(min_prompt, max_prompt + 1))
        hi = max_new if max_new is not None else max_seq - plen
        hi = min(hi, max_seq - plen)
        new = int(rng.integers(min_new, max(min_new, hi) + 1))
        prompt = rng.integers(0, vocab_size, size=plen, dtype=np.int32)
        out.append((prompt, new))
    return out


def run_decode_loadgen(
    scheduler,
    *,
    n_requests: int,
    concurrency: int,
    seed: int = 0,
    ls_fraction: float = 0.0,
    min_prompt: int = 2,
    max_prompt: int | None = None,
    max_new: int | None = None,
    timeout: float = 240.0,
    keep_streams: bool = False,
) -> dict:
    """Drive a `serve/decode.DecodeScheduler` with seeded autoregressive
    traffic; closed loop like `run_loadgen` (the semaphore window keeps
    `concurrency` requests in flight, so continuous batching always has a
    queue to admit from). Returns the decode SLO summary: TTFT
    percentiles (submit -> first token), per-request generation
    throughput (tokens / generation wall time), per-request token
    timestamps, and the compile-cache miss delta across the timed
    traffic (`recompiles_during_traffic` — 0 after prewarm is the
    decode grid's no-recompile guarantee). `keep_streams` returns each
    request's full token stream for mode-vs-mode identity checks."""
    from dist_mnist_tpu.serve.router import (
        BEST_EFFORT,
        LATENCY_SENSITIVE,
    )

    reqs = make_prompts(n_requests, max_seq=scheduler.engine.max_seq,
                        seed=seed, min_prompt=min_prompt,
                        max_prompt=max_prompt, max_new=max_new,
                        vocab_size=scheduler.engine.model.vocab_size)
    rng = np.random.default_rng(seed + 1)
    classes = np.where(rng.random(n_requests) < ls_fraction,
                       LATENCY_SENSITIVE, BEST_EFFORT)
    cache0 = scheduler.engine.stats()
    window = threading.Semaphore(concurrency)
    futures = []
    rejected_queue_full = 0
    rejected_shutdown = 0

    for i, (prompt, new) in enumerate(reqs):
        window.acquire()
        try:
            fut = scheduler.submit(prompt, new,
                                   request_class=str(classes[i]))
        except QueueFullError:
            rejected_queue_full += 1
            window.release()
            continue
        except ShuttingDownError:
            rejected_shutdown += 1
            window.release()
            continue
        fut.add_done_callback(lambda _f: window.release())
        futures.append(fut)

    ok = 0
    errors = 0
    ttfts = []
    latencies = []
    tokens_per_s = []
    tokens_out = 0
    streams = []
    token_times = []
    for fut in futures:
        try:
            res = fut.result(timeout=timeout)
        except Exception:
            errors += 1
            continue
        ok += 1
        ttfts.append(res.ttft_ms)
        latencies.append(res.latency_ms)
        tokens_out += len(res.tokens)
        wall_s = res.latency_ms / 1e3
        tokens_per_s.append(len(res.tokens) / max(wall_s, 1e-9))
        token_times.append(list(res.token_times))
        if keep_streams:
            streams.append(list(res.tokens))

    ttft = np.asarray(ttfts, dtype=np.float64)
    tps = np.asarray(tokens_per_s, dtype=np.float64)
    summary = {
        "n_requests": n_requests,
        "concurrency": concurrency,
        "mode": scheduler.mode,
        "ok": ok,
        "errors": errors,
        "rejected_queue_full": rejected_queue_full,
        "rejected_shutdown": rejected_shutdown,
        "tokens_out": tokens_out,
        "ttft_p50_ms": float(np.percentile(ttft, 50)) if ttft.size
        else float("nan"),
        "ttft_p99_ms": float(np.percentile(ttft, 99)) if ttft.size
        else float("nan"),
        "ttft_mean_ms": float(ttft.mean()) if ttft.size else float("nan"),
        "tokens_per_s_p50": float(np.percentile(tps, 50)) if tps.size
        else float("nan"),
        "tokens_per_s_mean": float(tps.mean()) if tps.size
        else float("nan"),
        "token_times": token_times,
    }
    summary.update(_pct(np.asarray(latencies, dtype=np.float64)))
    cache1 = scheduler.engine.stats()
    summary["cache"] = cache1
    summary["recompiles_during_traffic"] = \
        cache1["misses"] - cache0["misses"]
    summary["scheduler"] = scheduler.metrics.snapshot()
    if keep_streams:
        summary["streams"] = streams
    return summary


def run_fleet_loadgen(
    router,
    *,
    n_requests: int,
    concurrency: int,
    image_shape: tuple[int, ...],
    seed: int = 0,
    ls_fraction: float = 0.8,
    ls_deadline_ms: float | None = None,
    be_deadline_ms: float | None = None,
    timeout: float = 180.0,
    keep_latencies: bool = False,
) -> dict:
    """Drive a `Router` with seeded two-class traffic; per-class summary.

    The class sequence is a fixed function of (seed, n_requests,
    ls_fraction), so two runs offer byte-identical traffic in the same
    order — which is what lets a fault-injected run be compared against
    a clean one request-for-request. Outcome taxonomy per class:
    `ok` / `shed` (router tier policy — only legitimate for best_effort) /
    `rejected` (queue-full / shutdown / all-down at submit) /
    `deadline_expired` / `errors` (post-admission failures) / `dropped`
    (future never settled inside `timeout` — always a bug).
    """
    from dist_mnist_tpu.serve.errors import AllReplicasDownError, ShedError
    from dist_mnist_tpu.serve.router import (
        BEST_EFFORT,
        LATENCY_SENSITIVE,
        REQUEST_CLASSES,
    )

    images = make_images(image_shape, seed=seed)
    rng = np.random.default_rng(seed)
    classes = np.where(rng.random(n_requests) < ls_fraction,
                       LATENCY_SENSITIVE, BEST_EFFORT)
    deadline_for = {LATENCY_SENSITIVE: ls_deadline_ms,
                    BEST_EFFORT: be_deadline_ms}
    window = threading.Semaphore(concurrency)
    futures: list = []  # (class, future)
    shed = {c: 0 for c in REQUEST_CLASSES}
    rejected = {c: 0 for c in REQUEST_CLASSES}

    for i in range(n_requests):
        cls = str(classes[i])
        window.acquire()
        try:
            fut = router.submit(images[i % len(images)], request_class=cls,
                                deadline_ms=deadline_for[cls])
        except ShedError:
            shed[cls] += 1
            window.release()
            continue
        except (QueueFullError, ShuttingDownError, AllReplicasDownError):
            rejected[cls] += 1
            window.release()
            continue
        fut.add_done_callback(lambda _f: window.release())
        futures.append((cls, fut))

    import time as _t

    gather_deadline = _t.monotonic() + timeout
    ok = {c: 0 for c in REQUEST_CLASSES}
    deadline_expired = {c: 0 for c in REQUEST_CLASSES}
    errors = {c: 0 for c in REQUEST_CLASSES}
    dropped = {c: 0 for c in REQUEST_CLASSES}
    latencies = {c: [] for c in REQUEST_CLASSES}
    for cls, fut in futures:
        remaining = gather_deadline - _t.monotonic()
        try:
            res = fut.result(timeout=max(remaining, 0.001))
        except DeadlineExceededError:
            deadline_expired[cls] += 1
            continue
        except (TimeoutError, _FuturesTimeout):
            # the future itself never settled — an in-flight request was
            # dropped on the floor somewhere, which the router contract
            # forbids; surfaced separately so tests can pin dropped == 0
            dropped[cls] += 1
            continue
        except Exception:
            errors[cls] += 1
            continue
        ok[cls] += 1
        latencies[cls].append(res.latency_ms)

    summary: dict = {
        "n_requests": n_requests,
        "concurrency": concurrency,
        "ls_fraction": ls_fraction,
        "offered": {c: int((classes == c).sum()) for c in REQUEST_CLASSES},
        "ok": ok,
        "shed": shed,
        "rejected": rejected,
        "deadline_expired": deadline_expired,
        "errors": errors,
        "dropped": dropped,
    }
    for cls in REQUEST_CLASSES:
        summary[f"latency_{cls}"] = _pct(
            np.asarray(latencies[cls], dtype=np.float64))
    summary["total_ok"] = sum(ok.values())
    summary["router"] = router.metrics.snapshot()
    if keep_latencies:
        summary["raw_latencies"] = {c: list(latencies[c])
                                    for c in REQUEST_CLASSES}
    return summary
