"""Router-boundary error taxonomy: every failure a replica attempt can
produce, classified into a retry disposition — by TYPE, never by message.

The train side learned this lesson first (train/loop.py `_is_preemption`):
string-matching exception text turns log-wording changes into behavior
changes and makes adversarial payloads ("user input containing the word
'preempt'") steer control flow. The serving router faces the same choice
on every failed attempt — give up, try again, or declare the replica
dead — so the classification is a single type-first function, unit-pinned
in tests/test_router.py.

Dispositions:

    RETRYABLE      transient: another attempt (same or different replica,
                   after backoff) can succeed. Admission pushback
                   (`QueueFullError`), a draining replica
                   (`ShuttingDownError`), and unrecognized engine errors
                   (the injected `serve_error` model) land here —
                   bounded by the router's attempt budget and the
                   request's deadline.
    TERMINAL       the REQUEST is over: its deadline expired
                   (`DeadlineExceededError`), it was shed, or a hedge
                   loser was cancelled. Retrying spends capacity on an
                   answer nobody is waiting for.
    REPLICA_FATAL  the REPLICA is gone: `ReplicaKilledError` from the
                   fault injector, or any connection-level `OSError`
                   from an HTTP replica. The router marks the replica
                   down and immediately requeues the flight elsewhere —
                   failover, not backoff.

Ordering note: since 3.10 `TimeoutError` IS an `OSError`, so
`DeadlineExceededError` (a `TimeoutError`) must be classified before the
connection-error clause or a dead client request would read as a dead
replica.
"""

from __future__ import annotations

from concurrent.futures import CancelledError

from dist_mnist_tpu.serve.admission import (
    DeadlineExceededError,
    QueueFullError,
    ShuttingDownError,
)

RETRYABLE = "retryable"
TERMINAL = "terminal"
REPLICA_FATAL = "replica_fatal"


class ShedError(RuntimeError):
    """Rejected at the ROUTER boundary: backlog policy shed this request
    (best-effort first) before any replica queue saw it."""


class ReplicaKilledError(RuntimeError):
    """The replica's engine/process is dead — every future call fails.
    Raised by faults.inject.FaultyEngine for a planned
    ``serve_replica_kill`` and by transport shims on connection loss."""


class AllReplicasDownError(RuntimeError):
    """No replica can ever take this request: the whole fleet is down."""


def classify_failure(err: BaseException) -> str:
    """RETRYABLE | TERMINAL | REPLICA_FATAL for one failed attempt."""
    if isinstance(err, DeadlineExceededError):
        return TERMINAL  # before the OSError clause: TimeoutError is OSError
    if isinstance(err, (ShedError, AllReplicasDownError, CancelledError)):
        return TERMINAL
    if isinstance(err, ReplicaKilledError):
        return REPLICA_FATAL
    if isinstance(err, (QueueFullError, ShuttingDownError)):
        return RETRYABLE
    if isinstance(err, (ConnectionError, OSError)):
        return REPLICA_FATAL  # transport-level loss: the replica, not the request
    # unrecognized engine/application error: treat as transient, bounded by
    # the router's attempt budget (the injected serve_error path)
    return RETRYABLE
