"""Checkpoint -> servable model, without ever constructing an optimizer.

Training restore (`CheckpointManager.restore`) targets a full TrainState —
params AND Adam slots AND the loop rng. Serving needs exactly the weights,
so the loader builds *abstract* param/model-state targets with
`jax.eval_shape` over `model.init` (zero throwaway device allocation),
attaches the same `parallel/sharding.py` placement the model trained
under, and calls the manager's weights-only restore
(`restore_weights`): optimizer slots restore into metadata-derived
abstract leaves and are discarded — `optim/` is never imported here.

Falls back to a fresh deterministic init (same split discipline as
`train.state.create_train_state`, so an untrained served model equals an
untrained trained model bit-for-bit) when the directory holds no
checkpoint — the loadgen/bench path needs no training run to exist.
"""

from __future__ import annotations

import dataclasses
import logging
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dist_mnist_tpu.configs import Config, get_config
from dist_mnist_tpu.data.datasets import DATASETS
from dist_mnist_tpu.models.registry import get_model
from dist_mnist_tpu.parallel.sharding import (
    ShardingRules,
    resolve_rules,
    tree_sharding,
)

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ServingBundle:
    model: Any
    params: Any
    model_state: Any
    image_shape: tuple[int, ...]
    num_classes: int
    rules: ShardingRules
    step: int  # train step the weights came from; 0 on fresh init
    restored: bool
    #: weight-only quant mode ("int8") when `params` was converted at load
    #: time; None = full-width float weights (the historical bundle)
    quant: str | None = None
    #: ops/quant.error_report of the conversion (per-leaf max error) —
    #: what ServeMetrics exports as serve/quant_error*
    quant_report: dict | None = None


def quantize_for_serving(params, *, mode: str = "int8"):
    """The load-time param transform: float checkpoint -> (int8 weights,
    f32 scales) pytree + per-leaf error report.

    One leaf-selection rule for every architecture
    (`ops.quant.default_leaf_rule`): matmul/conv kernels (`w`/`w1`/`w2`,
    2-D+, floating) quantize; biases, norms, embeddings, and the MoE
    router gate stay float. Quantizing runs eagerly on the restored leaves,
    so TP/fsdp shard placements survive the conversion."""
    from dist_mnist_tpu.ops.quant import error_report, quantize_tree

    if mode != "int8":
        raise ValueError(f"unsupported quant mode {mode!r} "
                         "(supported: 'int8')")
    qparams = quantize_tree(params)
    return qparams, error_report(params, qparams)


def load_for_serving(
    cfg: Config | str,
    mesh: Mesh,
    *,
    checkpoint_dir: str | Path | None = None,
    step: int | None = None,
    sharding_rules: str | ShardingRules | None = None,
    quant: str | None = None,
) -> ServingBundle:
    """Build everything `InferenceEngine` needs from a config (+ optional
    checkpoint directory). `cfg` may be a config name or a Config.

    `sharding_rules` overrides the config's TRAIN-time strategy for the
    serve placement (cross-strategy restore, e.g. an fsdp-trained
    checkpoint served under tp): the abstract restore targets are built
    with the SERVE rules, so `restore_weights` lands each leaf directly in
    its serve-time shard layout — `parallel/sharding.py` does the
    resharding by construction, no full replica ever materializes."""
    if isinstance(cfg, str):
        cfg = get_config(cfg)
    model = get_model(cfg.model, **cfg.model_kwargs)
    if sharding_rules is None:
        rules = resolve_rules(cfg.sharding_rules)
    elif isinstance(sharding_rules, str):
        rules = resolve_rules(sharding_rules)
    else:
        rules = sharding_rules
    info = DATASETS[cfg.dataset]
    image_shape = tuple(info["image_shape"])
    sample = jnp.zeros((1, *image_shape), jnp.float32)
    # same split as create_train_state: key0 inits, key1 runs the loop
    init_key, _ = jax.random.split(jax.random.PRNGKey(cfg.seed))

    restored = None
    if checkpoint_dir is not None and Path(checkpoint_dir).exists():
        from dist_mnist_tpu.checkpoint.manager import CheckpointManager

        mgr = CheckpointManager(checkpoint_dir, async_save=False)
        try:
            abs_params, abs_ms = jax.eval_shape(model.init, init_key, sample)
            abs_params = _with_shardings(abs_params, mesh, rules)
            abs_ms = _with_shardings(abs_ms, mesh, rules)
            restored = mgr.restore_weights(abs_params, abs_ms, step=step)
        finally:
            mgr.close()

    if restored is not None:
        ckpt_step, params, model_state = restored
        log.info("serving weights from step %d of %s", ckpt_step,
                 checkpoint_dir)
    else:
        if checkpoint_dir is not None:
            log.warning("no checkpoint under %s; serving a FRESH init",
                        checkpoint_dir)
        ckpt_step = 0
        params, model_state = model.init(init_key, sample)
        params = jax.device_put(params, tree_sharding(params, mesh, rules))
        model_state = jax.device_put(
            model_state, tree_sharding(model_state, mesh, rules)
        )
    quant_report = None
    if quant:
        params, quant_report = quantize_for_serving(params, mode=quant)
        log.info(
            "quantized %d leaves to %s for serving (max rel err %.2e)",
            quant_report["n_quantized"], quant,
            quant_report["max_rel_err"])
    return ServingBundle(
        model=model,
        params=params,
        model_state=model_state,
        image_shape=image_shape,
        num_classes=int(info["num_classes"]),
        rules=rules,
        step=ckpt_step,
        restored=restored is not None,
        quant=quant or None,
        quant_report=quant_report,
    )


def _with_shardings(abstract_tree, mesh, rules):
    shd = tree_sharding(abstract_tree, mesh, rules)
    return jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract_tree, shd,
    )


def init_lm_for_serving(model_name: str, *, seed: int = 0,
                        **model_overrides):
    """(model, params) for a registry causal LM (serve/decode.py).

    Decode serving's loader seam: today the synthetic-token decode
    workload always fresh-initializes from `seed` (mirroring
    `load_for_serving`'s no-checkpoint fallback — deterministic, so two
    replicas built with the same seed serve identical weights); a future
    checkpoint-restored LM replaces only this function's body. Params
    stay host-side — the decode engine owns placement the way
    `InferenceEngine` does for bundles."""
    model = get_model(model_name, **model_overrides)
    if not hasattr(model, "decode_step"):
        raise ValueError(
            f"model {model_name!r} has no decode surface (decode_step/"
            "prefill/init_cache) — decode serving needs a causal LM")
    params, _state = model.init(jax.random.PRNGKey(seed))
    return model, params
