"""Autoregressive decode serving: prefill/decode split, sharded KV cache,
continuous batching.

The classifier serve stack (engine/batcher) answers one request with one
forward; decode serving answers one request with a prefill plus N
single-token steps whose state — the KV cache — lives on device between
steps. That forces a different execution shape, built here in two
layers (docs/SERVING.md "Autoregressive decode"):

`DecodeEngine` — owns the device state and the compiled programs:

- The KV cache is **engine-owned sharded device state**: per-model-layer
  ``[slot, max_seq, heads, head_dim]`` buffers (models/causal_lm.py
  `init_cache`), device_put with the heads axis sharded over the mesh's
  `model` axis (the parallel/flash.py TP placement) and updated IN PLACE
  by `lax.dynamic_update_slice` inside the jitted step — the cache
  argument is donated, so steps never copy it.
- **Prefill and decode are separate executables** on the
  `serve/zoo.DecodeGrid`: prefill cells bucket (admit batch, prompt
  length) exactly like the classifier's (batch, seq) grid; decode is one
  program at full slot capacity. `prewarm()` compiles every cell through
  the shared `CompiledModelCache`, so mixed traffic never recompiles —
  `cache.stats()["misses"]` deltas are the proof the bench asserts on.
- A request's prompt bucket depends on ITS OWN length only, never on
  the admission batch — the property that keeps token streams
  bit-identical between scheduling modes.
- **Paged KV cache** (``cache_layout="paged"`` models): the cache is a
  device page POOL ``[depth, num_pages, page_tokens, heads, head_dim]``
  and the engine owns a host-side page table ``[rows, pages_per_slot]``
  int32 plus a free list. Pages are pinned at admission
  (`try_reserve`) and reclaimed at eviction (`release_slot`) —
  `kv_page_alloc`/`kv_page_reclaim` journal events — and the
  memory-budget accounting (`CompiledModelCache.set_base_bytes`)
  charges params + scratch + PINNED pages instead of the dense worst
  case, so `--serve_memory_budget_mb` eviction decisions see real
  residency (a 40-token slot pins pages for 40 tokens, not a max_seq
  stripe). Unallocated table entries alias the reserved scratch pages
  (written only by rows whose output is discarded, never read by live
  rows). Each decode step picks the smallest ``("decode", p)``
  page-bucket cell covering the live prefix and passes the truncated
  table as a REPLICATED jit argument — the table is data, not donated
  device state, so host-side alloc/free never races the step.

`DecodeScheduler` — **continuous batching** over the engine's slots (one
daemon thread, name prefix ``DecodeScheduler`` in the conftest leak
registry): between any two decode steps it admits queued requests into
free slots (prefill), evicts finished sequences, and NEVER drains the
in-flight batch to make room — a fresh request rides along with
sequences mid-generation. Router SLO classes map onto decode SLOs
(serve/router.DECODE_SLO_TARGETS): `latency_sensitive` requests jump the
admission queue (time-to-first-token), `best_effort` fills remaining
slots (per-token throughput). ``mode="static"`` is the measured
baseline: admit a batch, decode until EVERY member finishes, only then
admit again — same executables, same per-request streams, strictly worse
tail TTFT (bench.py --serve --decode shows the gap).

``runahead=1`` (the default, mirroring ``TrainLoop(runahead=k)``)
overlaps host scheduling with the device step in continuous mode: the
loop dispatches the step without syncing (`DecodeEngine.decode_async`),
runs admission bookkeeping + page allocation while the device computes,
then harvests the token ids (`decode_harvest`) and prefills the admitted
batch. Per-slot streams are independent of batch composition, so overlap
moves WHEN a request is admitted (by at most one step), never the tokens
it produces. ``runahead=0`` restores the serial admit-then-step loop. No
extra threads are created — the conftest leak registry still watches the
single ``DecodeScheduler`` prefix.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from collections import deque
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import MODEL_AXIS, activate
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.serve.admission import QueueFullError, ShuttingDownError
from dist_mnist_tpu.serve.engine import CompiledModelCache
from dist_mnist_tpu.serve.metrics import DecodeMetrics
from dist_mnist_tpu.serve.router import (
    BEST_EFFORT,
    DECODE_SLO_TARGETS,
    LATENCY_SENSITIVE,
    REQUEST_CLASSES,
)

log = logging.getLogger(__name__)

#: scheduler idle poll (waiting for the first/next request), mirroring
#: serve/batcher.py
_IDLE_POLL_SECS = 0.05

_SCHED_IDS = itertools.count()


class DecodeEngine:
    """Compiled prefill/decode programs + the sharded KV cache they share.

    Single-owner by design: the KV cache and the per-call donation of it
    make concurrent callers nonsensical — the scheduler thread is the one
    driver. Engines on the same mesh CAN share a `CompiledModelCache`
    (executables close over no weights), which is how the bench runs
    continuous and static modes on one compiled set.
    """

    def __init__(self, model, params, mesh: Mesh, *,
                 model_name: str = "causal_lm", grid=None,
                 max_slots: int = 8, store=None,
                 cache: CompiledModelCache | None = None,
                 num_pages: int | None = None):
        from dist_mnist_tpu.serve.zoo import default_decode_grid

        self.model = model
        self.mesh = mesh
        self.model_name = model_name
        self.grid = grid if grid is not None else default_decode_grid(
            model, max_slots=max_slots)
        self.max_slots = self.grid.max_slots
        self.max_seq = int(model.max_seq)
        if self.grid.max_seq != self.max_seq:
            raise ValueError(
                f"grid max_seq {self.grid.max_seq} != model max_seq "
                f"{self.max_seq}")
        self.cache = cache if cache is not None else CompiledModelCache(
            store=store)
        self._rep = NamedSharding(mesh, P())
        # the TP placement: heads axis of [layer, slot, seq, head, dim]
        # rides the model axis (parallel/flash.py's spec, one rank up for
        # the layer stack). Indivisible head counts fail HERE, not deep
        # inside XLA partitioning (models/causal_lm._heads_spec raises at
        # trace time with the same contract).
        m = dict(mesh.shape).get(MODEL_AXIS, 1)
        heads = int(model.heads)
        if m > 1 and heads % m:
            raise ValueError(
                f"heads={heads} not divisible by model axis {m}; "
                "the TP-sharded KV cache needs heads % model == 0")
        self._kv_shd = (NamedSharding(
            mesh, P(None, None, None, MODEL_AXIS, None))
            if m > 1 else self._rep)
        self.params = jax.device_put(params, self._rep)
        self.layout = getattr(model, "cache_layout", "dense")
        self.kv_quant = getattr(model, "kv_quant", "none")
        self.page_tokens = (int(model.kv_page_tokens)
                            if self.layout == "paged" else 0)
        if self.layout == "paged":
            if not self.grid.decode_page_buckets:
                raise ValueError(
                    "paged model needs a grid with decode_page_buckets "
                    "(serve/zoo.default_decode_grid derives them)")
            if self.grid.decode_page_buckets[-1] != model.pages_per_slot:
                raise ValueError(
                    f"widest decode page bucket "
                    f"{self.grid.decode_page_buckets[-1]} != "
                    f"pages_per_slot {model.pages_per_slot}")
            kv_host = model.init_cache(self.grid.rows, num_pages=num_pages)
        else:
            if self.grid.decode_page_buckets:
                raise ValueError("dense model with paged decode buckets")
            kv_host = model.init_cache(self.grid.rows)
        #: the live cache state: slots + 1 rows (scratch row absorbs
        #: prefill-padding writes), donated to and rebound from every step
        self.kv = jax.device_put(kv_host, self._kv_shd)
        self._params_bytes = sum(
            int(np.prod(a.shape)) * a.dtype.itemsize
            for a in jax.tree.leaves(self.params))
        self._kv_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                             for a in jax.tree.leaves(self.kv))
        if self.layout == "paged":
            pps = int(model.pages_per_slot)
            self.num_pages = int(jax.tree.leaves(self.kv)[0].shape[1])
            if self.num_pages < 2 * pps:
                raise ValueError(
                    f"num_pages {self.num_pages} < {2 * pps}: the pool "
                    "needs the scratch stripe plus at least one full slot")
            self._page_bytes = self._kv_bytes // self.num_pages
            # the LAST pages_per_slot page ids are the permanent scratch
            # stripe: the scratch row's table points at them forever, and
            # every unallocated table entry aliases them
            self._scratch_pages = np.arange(self.num_pages - pps,
                                            self.num_pages, dtype=np.int32)
            self._free_pages = list(range(self.num_pages - pps))
            self._slot_pages: dict = {}
            self._page_table = np.tile(self._scratch_pages,
                                       (self.grid.rows, 1))
            # committed device copies of the (truncated) table, keyed by
            # width and dirtied on every alloc/free: the table only
            # changes at admission/finish boundaries, so steady-state
            # decode steps re-use one device buffer instead of paying a
            # host->device table transfer per step
            self._table_device: dict = {}
            self._peak_pinned = 0
            self._update_base_bytes()
        else:
            self.cache.set_base_bytes(
                (self._params_bytes + self._kv_bytes) // max(1, mesh.size))

    # -- paged-cache page management (host-owned; no-ops for dense) ---------

    def _update_base_bytes(self) -> None:
        """Re-derive the memory-budget floor from pages actually pinned:
        params + the scratch stripe + every allocated page. The byte-
        accounting fix over the dense engine, which charged the full
        worst-case KV allocation up front."""
        pinned = sum(len(p) for p in self._slot_pages.values())
        self._peak_pinned = max(self._peak_pinned, pinned)
        resident = (self._params_bytes + self._page_bytes
                    * (len(self._scratch_pages) + pinned))
        self.cache.set_base_bytes(resident // max(1, self.mesh.size))

    def _device_table(self, width: int):
        """The page table's first `width` columns as a committed device
        array, cached until an alloc/free dirties it. Committing also
        freezes the in-flight step's view: host-side bookkeeping after
        dispatch mutates the numpy table, never this buffer."""
        tab = self._table_device.get(width)
        if tab is None:
            tab = jax.device_put(
                np.ascontiguousarray(self._page_table[:, :width]),
                self._rep)
            self._table_device[width] = tab
        return tab

    def try_reserve(self, slot: int, total_len: int) -> bool:
        """Pin the pages `slot` needs for a prompt + full generation of
        `total_len` tokens; False when the free pool can't cover it (the
        scheduler defers the admission). Dense layout: always True."""
        if self.layout != "paged":
            return True
        n = -(-int(total_len) // self.page_tokens)
        if n > self._page_table.shape[1]:
            raise ValueError(
                f"{total_len} tokens need {n} pages > pages_per_slot "
                f"{self._page_table.shape[1]}")
        if len(self._free_pages) < n:
            return False
        pages = [self._free_pages.pop(0) for _ in range(n)]
        self._page_table[slot, :n] = pages
        self._slot_pages[slot] = pages
        self._table_device.clear()
        self._update_base_bytes()
        events.emit("kv_page_alloc", slot=int(slot), pages=n,
                    free=len(self._free_pages))
        return True

    def release_slot(self, slot: int) -> None:
        """Reclaim a finished slot's pages and re-alias its table row to
        the scratch stripe. Idempotent; no-op for dense."""
        if self.layout != "paged":
            return
        pages = self._slot_pages.pop(slot, None)
        if not pages:
            return
        self._free_pages.extend(pages)
        self._page_table[slot] = self._scratch_pages
        self._table_device.clear()
        self._update_base_bytes()
        events.emit("kv_page_reclaim", slot=int(slot), pages=len(pages),
                    free=len(self._free_pages))

    def reset_pages(self) -> None:
        """Reclaim EVERY slot's pages — the scheduler's crash-recovery
        hook, paired with its slot-table reset."""
        if self.layout != "paged":
            return
        for slot in list(self._slot_pages):
            self.release_slot(slot)

    def kv_stats(self) -> dict:
        """Residency counters for metrics/bench: pages + bytes pinned vs
        the pool. Dense reports its whole allocation as pinned — that IS
        its residency, which is the point of the comparison."""
        if self.layout != "paged":
            return {"layout": "dense", "kv_quant": self.kv_quant,
                    "page_tokens": 0, "kv_pages_total": 0,
                    "kv_pages_pinned": 0,
                    "kv_bytes_pinned": self._kv_bytes,
                    "kv_bytes_peak": self._kv_bytes,
                    "kv_bytes_pool": self._kv_bytes}
        pinned = sum(len(p) for p in self._slot_pages.values())
        scratch = len(self._scratch_pages)
        return {"layout": "paged", "kv_quant": self.kv_quant,
                "page_tokens": self.page_tokens,
                "kv_pages_total": self.num_pages,
                "kv_pages_pinned": pinned,
                "kv_bytes_pinned": self._page_bytes * pinned,
                # high-water residency incl. the scratch stripe: what the
                # bench's <=0.35x-dense contract is asserted against
                "kv_bytes_peak": self._page_bytes
                * (scratch + self._peak_pinned),
                "kv_bytes_pool": self._page_bytes * self.num_pages}

    # -- compilation --------------------------------------------------------

    def _mesh_key(self):
        return tuple(sorted(dict(self.mesh.shape).items()))

    def _layout_key(self) -> tuple:
        """Everything about the KV layout that changes the compiled
        program: the layout itself, page size, quantization, and (for
        int8, where it selects the attention implementation at trace
        time) the paged-kernel dispatch. Tuned knobs (`kv_page_tokens`,
        `decode_admit_buckets` — the latter via the grid cell) fold into
        the executable key HERE, the contract the graftlint cache-key
        rule cross-checks."""
        from dist_mnist_tpu.ops.pallas.paged_attention import \
            use_paged_kernel

        kernel = use_paged_kernel() if self.kv_quant == "int8" else False
        return (self.layout, self.page_tokens, self.kv_quant, kernel,
                getattr(self.model, "attention_impl", "xla"))

    def _key(self, cell: tuple):
        dt = str(jnp.dtype(self.model.compute_dtype))
        return (self.model_name, "decode_grid", cell, self.grid.rows,
                self.max_seq, self._mesh_key(), dt, self._layout_key())

    def _store_key(self, cell: tuple) -> str | None:
        if self.cache._store is None:
            return None
        from dist_mnist_tpu.compilecache import cache_key

        return cache_key({
            "kind": "serve_decode",
            "model": self.model_name,
            "cell": cell,
            "rows": self.grid.rows,
            "max_seq": self.max_seq,
            "mesh": self._mesh_key(),
            "dtype": str(jnp.dtype(self.model.compute_dtype)),
            "layout": list(self._layout_key()),
        })

    def _abstract_kv(self):
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=self._kv_shd), self.kv)

    def _compile_decode(self, cell: tuple):
        rows = self.grid.rows
        ivec = jax.ShapeDtypeStruct((rows,), jnp.int32, sharding=self._rep)
        if self.layout == "paged":
            def step(params, kv, tokens, positions, page_table):
                logits, kv = self.model.decode_step(
                    params, kv, tokens, positions, page_table=page_table)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv

            jitted = jax.jit(
                step,
                in_shardings=(self._rep, self._kv_shd, self._rep,
                              self._rep, self._rep),
                out_shardings=(self._rep, self._kv_shd),
                donate_argnums=(1,))
            pt = jax.ShapeDtypeStruct((rows, cell[1]), jnp.int32,
                                      sharding=self._rep)
            with activate(self.mesh):
                return jitted.lower(self.params, self._abstract_kv(),
                                    ivec, ivec, pt).compile()

        def step(params, kv, tokens, positions):
            logits, kv = self.model.decode_step(params, kv, tokens,
                                                positions)
            # greedy argmax in-graph: the host reads token ids, never the
            # [rows, vocab] logits
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv

        jitted = jax.jit(
            step,
            in_shardings=(self._rep, self._kv_shd, self._rep, self._rep),
            out_shardings=(self._rep, self._kv_shd),
            donate_argnums=(1,))
        with activate(self.mesh):
            return jitted.lower(self.params, self._abstract_kv(),
                                ivec, ivec).compile()

    def _compile_prefill(self, n_bucket: int, s_bucket: int):
        toks = jax.ShapeDtypeStruct((n_bucket, s_bucket), jnp.int32,
                                    sharding=self._rep)
        ivec = jax.ShapeDtypeStruct((n_bucket,), jnp.int32,
                                    sharding=self._rep)
        if self.layout == "paged":
            def fwd(params, kv, tokens, slot_ids, lengths, page_table):
                logits, kv = self.model.prefill(
                    params, kv, tokens, slot_ids, lengths,
                    page_table=page_table)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv

            jitted = jax.jit(
                fwd,
                in_shardings=(self._rep, self._kv_shd, self._rep,
                              self._rep, self._rep, self._rep),
                out_shardings=(self._rep, self._kv_shd),
                donate_argnums=(1,))
            # prefill always sees the FULL-width table: chunk writes are
            # table lookups, not attention, so there's nothing to truncate
            pt = jax.ShapeDtypeStruct(
                (self.grid.rows, self.model.pages_per_slot), jnp.int32,
                sharding=self._rep)
            with activate(self.mesh):
                return jitted.lower(self.params, self._abstract_kv(),
                                    toks, ivec, ivec, pt).compile()

        def fwd(params, kv, tokens, slot_ids, lengths):
            logits, kv = self.model.prefill(params, kv, tokens, slot_ids,
                                            lengths)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv

        jitted = jax.jit(
            fwd,
            in_shardings=(self._rep, self._kv_shd, self._rep, self._rep,
                          self._rep),
            out_shardings=(self._rep, self._kv_shd),
            donate_argnums=(1,))
        with activate(self.mesh):
            return jitted.lower(self.params, self._abstract_kv(),
                                toks, ivec, ivec).compile()

    def compiled_for(self, cell: tuple):
        """The executable for a grid cell: ``("decode",)`` /
        ``("decode", p)`` or ``("prefill", n_bucket, s_bucket)``."""
        if cell[0] == "decode":
            build = lambda: self._compile_decode(cell)  # noqa: E731
        else:
            _, n_b, s_b = cell
            build = lambda: self._compile_prefill(n_b, s_b)  # noqa: E731
        return self.cache.get(self._key(cell), build,
                              store_key=self._store_key(cell))

    def prewarm(self) -> int:
        """Compile the whole grid up front; returns programs compiled.
        After this, live traffic hits the memory tier only — the
        zero-recompile contract tests and the bench assert via
        `cache.stats()["misses"]` deltas."""
        n0 = self.cache.misses
        for cell in self.grid.cells():
            self.compiled_for(cell)
        compiled = self.cache.misses - n0
        events.emit("decode_prewarm", programs=len(self.grid.cells()),
                    compiled=compiled)
        return compiled

    # -- execution ----------------------------------------------------------

    def prefill(self, prompts: list, slot_ids: list) -> np.ndarray:
        """Land `prompts[i]` (1-D int32 arrays) in cache slot
        `slot_ids[i]` and return each prompt's FIRST generated token,
        ``[len(prompts)]`` int32.

        Grouping discipline: requests are grouped by their own prompt
        bucket (stream determinism — see class docstring), each group
        chunked to the admit-bucket grid; padding rows prefill a length-1
        dummy into the scratch row."""
        out = np.zeros(len(prompts), np.int32)
        groups: dict = {}
        for i, p in enumerate(prompts):
            groups.setdefault(self.grid.prompt_bucket_for(len(p)),
                              []).append(i)
        max_admit = self.grid.admit_buckets[-1]
        scratch = self.max_slots
        for s_b, idxs in sorted(groups.items()):
            for at in range(0, len(idxs), max_admit):
                chunk = idxs[at:at + max_admit]
                n_b = self.grid.admit_bucket_for(len(chunk))
                tokens = np.zeros((n_b, s_b), np.int32)
                slots = np.full((n_b,), scratch, np.int32)
                lengths = np.ones((n_b,), np.int32)
                for row, i in enumerate(chunk):
                    tokens[row, :len(prompts[i])] = prompts[i]
                    slots[row] = slot_ids[i]
                    lengths[row] = len(prompts[i])
                exe = self.compiled_for(("prefill", n_b, s_b))
                if self.layout == "paged":
                    first, self.kv = exe(
                        self.params, self.kv, tokens, slots, lengths,
                        self._device_table(self._page_table.shape[1]))
                else:
                    first, self.kv = exe(self.params, self.kv, tokens,
                                         slots, lengths)
                # one intentional sync per admission: the scheduler needs
                # the first token on host to stream it / update slot state
                first = np.asarray(jax.device_get(first))  # lint: ok[host-sync] scheduler consumes token ids on host
                for row, i in enumerate(chunk):
                    out[i] = first[row]
        return out

    def decode_async(self, tokens: np.ndarray, positions: np.ndarray):
        """Dispatch one decode step WITHOUT syncing: returns the
        on-device next-token vector. Pair with `decode_harvest` — the
        seam the scheduler's runahead overlap is built on: host
        admission/page bookkeeping runs between dispatch and harvest.

        Paged engines pick the smallest page-bucket cell covering the
        live prefix here (host arithmetic over the positions the caller
        already holds — no device readback) and pass a truncated COPY of
        the page table, so later host-side alloc/free can't touch the
        in-flight step's view."""
        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int32)
        if self.layout == "paged":
            needed = -(-(int(positions.max()) + 1) // self.page_tokens)
            p = self.grid.decode_page_bucket_for(needed)
            exe = self.compiled_for(("decode", p))
            nxt, self.kv = exe(self.params, self.kv, tokens, positions,
                               self._device_table(p))
        else:
            exe = self.compiled_for(("decode",))
            nxt, self.kv = exe(self.params, self.kv, tokens, positions)
        return nxt

    def decode_harvest(self, nxt) -> np.ndarray:
        """Block on a `decode_async` result and return host token ids."""
        # the one per-step sync decode serving cannot avoid: token ids
        # drive host-side stop/admit decisions
        return np.asarray(jax.device_get(nxt))  # lint: ok[host-sync] scheduler consumes token ids on host

    def decode(self, tokens: np.ndarray, positions: np.ndarray) -> np.ndarray:
        """One step for every slot row: feed each slot's latest token at
        its position, get back next-token ids ``[rows]`` int32. Inactive
        rows compute garbage that their next prefill overwrites — the
        batch shape never changes, which is why admission/eviction can
        happen between any two steps without recompiling."""
        return self.decode_harvest(self.decode_async(tokens, positions))

    def stats(self) -> dict:
        return self.cache.stats()


@dataclasses.dataclass
class DecodeResult:
    """One finished request: the greedy token stream plus its timeline.
    `token_times` are monotonic stamps, one per token — `token_times[0] -
    t_submit` is the TTFT the metrics aggregate."""

    tokens: list
    ttft_ms: float
    latency_ms: float
    token_times: list
    request_class: str
    prompt_len: int


class _Request:
    __slots__ = ("prompt", "max_new_tokens", "request_class", "future",
                 "t_submit", "tokens", "token_times", "slot")

    def __init__(self, prompt, max_new_tokens, request_class):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.request_class = request_class
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.tokens: list = []
        self.token_times: list = []
        self.slot: int | None = None


class DecodeScheduler:
    """Slot-allocating batcher over a `DecodeEngine` (one daemon thread).

    ``mode="continuous"``: between steps, free slots are refilled from
    the queue (latency_sensitive first) and finished sequences evicted —
    the in-flight batch never drains. ``mode="static"``: admission only
    when NO sequence is in flight (the whole batch finishes together),
    the baseline continuous batching is measured against. Both modes run
    the same executables in the same per-request order, so streams are
    bit-identical — scheduling changes WHEN a request runs, never WHAT
    it computes.
    """

    def __init__(self, engine: DecodeEngine, *, mode: str = "continuous",
                 max_queue: int = 256, metrics: DecodeMetrics | None = None,
                 writer=None, runahead: int = 1):
        if mode not in ("continuous", "static"):
            raise ValueError(f"unknown mode {mode!r}; "
                             "use 'continuous' | 'static'")
        if runahead not in (0, 1):
            raise ValueError("runahead must be 0 (serial) or 1 (overlap "
                             "host scheduling with the device step)")
        self.engine = engine
        self.mode = mode
        self.runahead = runahead
        self.max_queue = max_queue
        self.metrics = metrics if metrics is not None else DecodeMetrics()
        self.writer = writer
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._closed = False
        self._pending = {c: deque() for c in REQUEST_CLASSES}
        self._free = list(range(engine.max_slots))
        self._active: dict = {}
        rows = engine.grid.rows
        self._tokens = np.zeros(rows, np.int32)
        self._positions = np.zeros(rows, np.int32)
        #: admission order as (submit_seq, request_class) — the SLO
        #: priority test hook
        self.admit_log: list = []
        self._seq = itertools.count()
        self._emit_step = itertools.count()
        events.emit("decode_start", mode=mode, max_slots=engine.max_slots,
                    max_seq=engine.max_seq)
        self._thread = threading.Thread(
            target=self._loop,
            name=f"DecodeScheduler-{next(_SCHED_IDS)}", daemon=True)
        self._thread.start()

    # -- client surface -----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               request_class: str = BEST_EFFORT) -> Future:
        """Enqueue one request; the Future resolves to a `DecodeResult`.
        `request_class` is a serve/router class: latency_sensitive jumps
        the queue (TTFT), best_effort rides for throughput
        (DECODE_SLO_TARGETS)."""
        if request_class not in REQUEST_CLASSES:
            raise ValueError(f"unknown request class {request_class!r}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.engine.max_seq:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"> max_seq {self.engine.max_seq}")
        req = _Request(prompt, int(max_new_tokens), request_class)
        with self._lock:
            if self._closed:
                self.metrics.record_rejected("shutdown")
                raise ShuttingDownError("decode scheduler is shutting down")
            depth = sum(len(q) for q in self._pending.values())
            if depth >= self.max_queue:
                self.metrics.record_rejected("queue_full")
                raise QueueFullError(
                    f"decode queue full ({self.max_queue})")
            self._pending[request_class].append((next(self._seq), req))
            self.metrics.record_submitted(request_class)
        self._work.set()
        return req.future

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._pending.values())

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    @property
    def free_slots(self) -> int:
        with self._lock:
            return len(self._free)

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting new work, let everything queued/in-flight
        finish, then shut the thread down. False on timeout (close is
        still performed)."""
        with self._lock:
            self._closed = True
        deadline = time.monotonic() + timeout
        ok = True
        while time.monotonic() < deadline:
            with self._lock:
                empty = (not self._active
                         and not any(self._pending.values()))
            if empty:
                break
            time.sleep(0.005)
        else:
            ok = False
        self.close()
        return ok

    def close(self) -> None:
        """Reject new submissions, stop the loop, join the thread, fail
        every unfinished future with ShuttingDownError. Idempotent."""
        with self._lock:
            self._closed = True
        self._stop.set()
        self._work.set()
        if self._thread.is_alive():
            self._thread.join(timeout=30.0)
        orphans = []
        with self._lock:
            for q in self._pending.values():
                orphans.extend(req for _, req in q)
                q.clear()
            orphans.extend(self._active.values())
            self._active.clear()
            self.engine.reset_pages()
        for req in orphans:
            if not req.future.done():
                req.future.set_exception(
                    ShuttingDownError("decode scheduler closed"))
                self.metrics.record_failed()
        events.emit("decode_stop", completed=self.metrics.completed,
                    failed=self.metrics.failed)
        if self.writer is not None:
            self.metrics.emit(self.writer, next(self._emit_step),
                              queue_depth=0, cache=self.engine.stats(),
                              kv=self.engine.kv_stats())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- scheduler loop -----------------------------------------------------

    def _take_admissions(self) -> list:
        """Pop (request, slot) assignments under the lock: LS queue fully
        before BE (the TTFT priority), one free slot each. Paged engines
        additionally pin the slot's KV pages here; a request whose pages
        don't fit stays at the HEAD of its queue (admission order is
        preserved) until evictions reclaim enough pool."""
        out = []
        with self._lock:
            while self._free:
                for cls in (LATENCY_SENSITIVE, BEST_EFFORT):
                    if self._pending[cls]:
                        seq, req = self._pending[cls][0]
                        total = int(req.prompt.size) + req.max_new_tokens
                        if not self.engine.try_reserve(self._free[0],
                                                       total):
                            return out
                        self._pending[cls].popleft()
                        req.slot = self._free.pop(0)
                        self.admit_log.append((seq, cls))
                        out.append(req)
                        break
                else:
                    break
        return out

    def _admit(self, reqs: list) -> None:
        first = self.engine.prefill([r.prompt for r in reqs],
                                    [r.slot for r in reqs])
        now = time.monotonic()
        finished = []
        with self._lock:
            for r, tok in zip(reqs, first):
                r.tokens.append(int(tok))
                r.token_times.append(now)
                ttft_ms = (now - r.t_submit) * 1e3
                self.metrics.record_admitted(ttft_ms, r.request_class)
                events.emit("decode_admit", slot=r.slot,
                            request_class=r.request_class,
                            slo_target=DECODE_SLO_TARGETS[r.request_class],
                            prompt_len=int(r.prompt.size))
                self._active[r.slot] = r
                self._tokens[r.slot] = int(tok)
                self._positions[r.slot] = r.prompt.size
                if len(r.tokens) >= r.max_new_tokens:
                    finished.append(r)
            for r in finished:
                self._finish_locked(r, now)

    def _finish_locked(self, r, now: float) -> None:
        slot = r.slot
        self._active.pop(slot, None)
        self.engine.release_slot(slot)
        self._free.append(slot)
        self._tokens[slot] = 0
        self._positions[slot] = 0
        latency_ms = (now - r.t_submit) * 1e3
        wall = max(now - r.t_submit, 1e-9)
        self.metrics.record_completed(latency_ms, len(r.tokens),
                                      len(r.tokens) / wall)
        events.emit("decode_evict", slot=slot, tokens=len(r.tokens),
                    request_class=r.request_class)
        r.future.set_result(DecodeResult(
            tokens=list(r.tokens),
            ttft_ms=(r.token_times[0] - r.t_submit) * 1e3,
            latency_ms=latency_ms,
            token_times=list(r.token_times),
            request_class=r.request_class,
            prompt_len=int(r.prompt.size)))

    def _step(self) -> None:
        self._harvest(self.engine.decode_async(self._tokens,
                                               self._positions))

    def _harvest(self, nxt_dev) -> None:
        nxt = self.engine.decode_harvest(nxt_dev)
        now = time.monotonic()
        with self._lock:
            self.metrics.record_step(len(self._active))
            finished = []
            for slot in sorted(self._active):
                r = self._active[slot]
                tok = int(nxt[slot])
                r.tokens.append(tok)
                r.token_times.append(now)
                self._positions[slot] += 1
                self._tokens[slot] = tok
                if len(r.tokens) >= r.max_new_tokens:
                    finished.append(r)
            for r in finished:
                self._finish_locked(r, now)

    def _loop(self) -> None:
        overlap = self.runahead > 0
        while not self._stop.is_set():
            try:
                if not self._active or (self.mode == "continuous"
                                        and not overlap):
                    reqs = self._take_admissions()
                    if reqs:
                        self._admit(reqs)
                if self._active:
                    if overlap and self.mode == "continuous":
                        # host/device overlap: admission bookkeeping +
                        # page allocation run while the dispatched step
                        # computes; the admitted batch prefills after
                        # harvest (bounded runahead=1)
                        nxt_dev = self.engine.decode_async(
                            self._tokens, self._positions)
                        reqs = self._take_admissions()
                        self._harvest(nxt_dev)
                        if reqs:
                            self._admit(reqs)
                    else:
                        self._step()
                    continue
            except Exception:  # pragma: no cover - defensive
                log.exception("decode scheduler step failed")
                with self._lock:
                    broken = list(self._active.values())
                    self._active.clear()
                    self.engine.reset_pages()
                    self._free = list(range(self.engine.max_slots))
                for r in broken:
                    if not r.future.done():
                        r.future.set_exception(
                            RuntimeError("decode step failed"))
                        self.metrics.record_failed()
                continue
            with self._lock:
                idle = not any(self._pending.values())
            if idle:
                self._work.wait(_IDLE_POLL_SECS)
                self._work.clear()
