"""The inference engine: power-of-two bucketing + an AOT compiled-model
cache over the data-axis mesh.

Why buckets: a continuous batcher produces a *different* batch size every
tick; jitting on the raw size would recompile on nearly every request
pattern. Rounding up to a power of two caps the number of distinct
executables at log2(max_batch) while wasting at most 2x compute on padding
— and padding rows are pure throughput cost, never a correctness one
(logits for pad rows are sliced off before completion).

Why AOT (`jit(...).lower(...).compile()`): the cache makes compilation an
*explicit, observable* event — hit/miss counters and compile-time
attribution (utils/timing.stopclock) instead of jit's invisible internal
cache, and `prewarm()` can move every expected compile to startup where it
cannot poke a p99 latency hole in live traffic.

The batch rides the `data` axis exactly as in training (`P(DATA_AXIS)`,
the same spec data/pipeline.py uses), so a bucket of B runs B/data rows
per device; params/model_state are placed once at engine construction by
the same `parallel/sharding.py` rules the model trained under.
"""

from __future__ import annotations

import logging
import threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import DATA_AXIS
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.ops.quant import is_quantized, quantize_tree
from dist_mnist_tpu.parallel.sharding import DP_RULES, ShardingRules, tree_sharding
from dist_mnist_tpu.utils.timing import stopclock

log = logging.getLogger(__name__)


class ServeMemoryBudgetError(RuntimeError):
    """The serve-side memory budget cannot hold the requested working set:
    either the weights alone exceed it, or the prewarm grid's executables
    would evict each other (thrash) instead of all staying resident."""


def _exe_nbytes(exe) -> int:
    """Best-effort per-device byte attribution for an AOT executable:
    XLA's own memory analysis (generated code + temp allocations — the
    bytes the program itself pins beyond its arguments), 0 when the
    backend doesn't expose one (budget accounting then covers weights +
    counted-as-zero executables, still monotonic in grid size)."""
    try:
        m = exe.memory_analysis()
        return int(
            getattr(m, "generated_code_size_in_bytes", 0)
            + getattr(m, "temp_size_in_bytes", 0)
        )
    except Exception:  # noqa: BLE001 — backend-optional API
        return 0


class CompiledModelCache:
    """key -> AOT-compiled executable, with hit/miss counters, per-key
    compile/load attribution, an optional DISK tier, and an optional
    MEMORY BUDGET. Keys are `(model_name, input_shape, mesh_key, dtype,
    variant)` — everything that changes the compiled program (the variant
    distinguishes the masked sub-native-sequence programs from the
    maskless native one).

    With `store` (a compilecache.ExecutableStore), a memory miss consults
    the store before compiling and saves after: a restarted server's
    `prewarm()` deserializes last generation's executables in milliseconds
    instead of recompiling every bucket. Hits are tiered — `hits_memory`
    vs `hits_disk` — and `per_key` records, for each key, which tier
    satisfied it first and the compile-or-load wall ms it cost.

    With a budget (`set_budget`), every insert that pushes
    `base_bytes` (served weights) + Σ executable bytes past the cap
    evicts the COLDEST other entries (LRU by last touch) until it fits —
    the hot path keeps serving while the least-loved bucket pays — and
    raises `ServeMemoryBudgetError` when even an empty cache could not
    hold the new entry. Evicted entries recompile (or disk-load) on next
    use; `evictions` counts them."""

    def __init__(self, store=None):
        self._lock = threading.Lock()
        self._cache: dict = {}
        self._store = store
        self.hits = 0
        self.misses = 0
        self.hits_memory = 0
        self.hits_disk = 0
        self.evictions = 0
        self.budget_bytes: int | None = None
        self.base_bytes = 0  # served weights, counted against the budget
        self._tick = 0  # LRU clock: bumped on every touch
        #: key -> {"tier": memory|disk|fresh, "compile_ms", "load_ms",
        #:         "hits", "nbytes", "last_used"}
        self.per_key: dict = {}
        self.times: dict = {}  # stopclock accumulator: compile/execute secs

    def set_budget(self, budget_bytes: int | None, *,
                   base_bytes: int = 0) -> None:
        """Arm (or disarm, None) the memory budget. `base_bytes` is the
        non-evictable floor — the served weights' per-device bytes."""
        with self._lock:
            if budget_bytes is not None and base_bytes > budget_bytes:
                raise ServeMemoryBudgetError(
                    f"served weights alone ({base_bytes} B/device) exceed "
                    f"the serve memory budget ({budget_bytes} B)")
            self.budget_bytes = budget_bytes
            self.base_bytes = base_bytes

    def set_base_bytes(self, base_bytes: int) -> None:
        """Update the weights floor WITHOUT touching the budget arming —
        budgetless engines still report the weights-vs-executables split
        (stats/metrics), and a quantized engine's floor is what lets a
        budget that refused the bf16 grid admit the int8 one."""
        with self._lock:
            if (self.budget_bytes is not None
                    and base_bytes > self.budget_bytes):
                raise ServeMemoryBudgetError(
                    f"served weights alone ({base_bytes} B/device) exceed "
                    f"the serve memory budget ({self.budget_bytes} B)")
            self.base_bytes = base_bytes

    def resident_bytes(self) -> int:
        """base (weights) + every resident executable, per device."""
        with self._lock:
            return self.base_bytes + sum(
                v.get("nbytes", 0) for k, v in self.per_key.items()
                if k in self._cache)

    def _admit_locked(self, key, nbytes: int) -> None:
        """Evict coldest entries (never `key`) until the budget holds."""
        if self.budget_bytes is None:
            return
        if self.base_bytes + nbytes > self.budget_bytes:
            self._cache.pop(key, None)
            raise ServeMemoryBudgetError(
                f"executable for {key} ({nbytes} B) cannot fit the serve "
                f"memory budget ({self.budget_bytes} B) even alone next to "
                f"the weights ({self.base_bytes} B)")

        def resident():
            return self.base_bytes + sum(
                v.get("nbytes", 0) for k, v in self.per_key.items()
                if k in self._cache)

        while resident() > self.budget_bytes:
            victims = [k for k in self._cache if k != key]
            victim = min(
                victims, key=lambda k: self.per_key[k].get("last_used", 0))
            del self._cache[victim]
            self.evictions += 1
            log.info("evicted %s (LRU) to hold the serve memory budget",
                     victim)
            events.emit("compile_cache", outcome="evict", key=str(victim))

    def get(self, key, build, *, store_key: str | None = None):
        """The executable for `key`: memory tier, then the disk store
        (when wired and `store_key` given), then `build()`. Compilation
        runs under the lock: concurrent misses for the same bucket must
        not compile twice."""
        with self._lock:
            self._tick += 1
            if key in self._cache:
                self.hits += 1
                self.hits_memory += 1
                self.per_key[key]["hits"] += 1
                self.per_key[key]["last_used"] = self._tick
                return self._cache[key]
            if self._store is not None and store_key is not None:
                t0 = _time.perf_counter()
                exe = self._store.load(store_key)
                if exe is not None:
                    load_ms = (_time.perf_counter() - t0) * 1e3
                    self.hits += 1
                    self.hits_disk += 1
                    self.per_key[key] = {"tier": "disk", "compile_ms": 0.0,
                                         "load_ms": load_ms, "hits": 1,
                                         "nbytes": _exe_nbytes(exe),
                                         "last_used": self._tick}
                    self._cache[key] = exe
                    self._admit_locked(key, self.per_key[key]["nbytes"])
                    log.info("loaded %s from compile cache (%.0f ms)",
                             key, load_ms)
                    return exe
            self.misses += 1
            with stopclock(self.times, "compile"):
                t0 = _time.perf_counter()
                exe = build()
                compile_ms = (_time.perf_counter() - t0) * 1e3
            self.per_key[key] = {"tier": "fresh", "compile_ms": compile_ms,
                                 "load_ms": 0.0, "hits": 0,
                                 "nbytes": _exe_nbytes(exe),
                                 "last_used": self._tick}
            self._cache[key] = exe
            self._admit_locked(key, self.per_key[key]["nbytes"])
            if self._store is not None and store_key is not None:
                self._store.save(store_key, exe,
                                 meta={"compile_ms": compile_ms})
            log.info("compiled %s (miss #%d, %.0f ms)", key, self.misses,
                     compile_ms)
            # the disk tier journals its own hits/misses (compilecache/
            # store.py); a fresh compile is the remaining interesting case
            events.emit("compile_cache", outcome="compile", key=str(key),
                        compile_ms=round(compile_ms, 3))
            return exe

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hits_memory": self.hits_memory,
                "hits_disk": self.hits_disk,
                "evictions": self.evictions,
                "entries": len(self._cache),
                "resident_bytes": self.base_bytes + sum(
                    v.get("nbytes", 0) for k, v in self.per_key.items()
                    if k in self._cache),
                # the split the budget is actually spending on: weights
                # floor (non-evictable) vs executables (the LRU tier)
                "resident_bytes_weights": self.base_bytes,
                "resident_bytes_executables": sum(
                    v.get("nbytes", 0) for k, v in self.per_key.items()
                    if k in self._cache),
                "budget_bytes": self.budget_bytes,
                "compile_secs": self.times.get("compile", 0.0),
                "execute_secs": self.times.get("execute", 0.0),
                "execute_count": self.times.get("execute_count", 0),
                "per_key": {str(k): dict(v) for k, v in self.per_key.items()},
            }


def _pow2_at_least(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


class InferenceEngine:
    """Stateless-forward inference over a fixed (model, weights, mesh).

    `predict(images)` takes a host batch of raw uint8 images `[n, H, W, C]`
    and returns logits `[n, classes]` — padding, placement, compilation
    caching and unpadding are internal. Normalization matches
    train/step.py's eval step exactly (`x/255`), so serving a checkpoint
    reproduces its eval accuracy bit-for-bit per row.
    """

    def __init__(
        self,
        model,
        params,
        model_state,
        mesh: Mesh,
        *,
        model_name: str = "model",
        image_shape: tuple[int, ...],
        rules: ShardingRules = DP_RULES,
        max_bucket: int = 256,
        store=None,
        cache: CompiledModelCache | None = None,
        seq_grid=None,
        memory_budget_bytes: int | None = None,
        quant: str | None = None,
        quant_report: dict | None = None,
    ):
        self.model = model
        self.mesh = mesh
        self.model_name = model_name
        self.image_shape = tuple(image_shape)
        # weight-only quantized serving (ops/quant.py): `quant="int8"`
        # converts float kernels to (int8, f32 scale) pytree nodes HERE
        # (idempotent — a loader-quantized tree passes through), and an
        # already-quantized tree auto-tags the engine so cache keys and
        # byte accounting can never disagree with the weights actually
        # served. Eager quantization of restored sharded leaves preserves
        # their NamedShardings, so a TP/fsdp restore serves quantized
        # under the same placements.
        if quant is None and is_quantized(params):
            quant = "int8"
        if quant is not None and quant != "int8":
            raise ValueError(f"unsupported quant mode {quant!r} "
                             "(supported: 'int8')")
        if quant and not is_quantized(params):
            params = quantize_tree(params)
        self.quant = quant
        #: per-leaf quantization-error report (ops/quant.error_report) when
        #: the loader produced one; surfaced on /metrics by the server
        self.quant_report = quant_report
        # `cache` lets N same-model replicas share one CompiledModelCache:
        # executables take (params, model_state, x) as runtime arguments, so
        # a program compiled by replica 0 serves replica 1's weights too —
        # the fleet pays log2(max_batch) compiles once, not per replica.
        # A provided cache keeps ITS store; `store` only seeds a fresh one.
        self.cache = cache if cache is not None else CompiledModelCache(store=store)
        self._rules = rules
        #: serve/zoo.SeqGrid (or None): the sequence-bucket axis of the
        #: 2-D (batch, height) grid. None = the classic 1-D batch grid
        #: pinned to the native image shape.
        self.seq_grid = seq_grid
        if seq_grid is not None and (
                seq_grid.native_height != self.image_shape[0]
                or (seq_grid.width, seq_grid.channels)
                != tuple(self.image_shape[1:])):
            raise ValueError(
                f"seq_grid native shape ({seq_grid.native_height}, "
                f"{seq_grid.width}, {seq_grid.channels}) != engine image "
                f"shape {self.image_shape}")
        # buckets must divide over the data axis; the smallest power of two
        # >= the axis size always does (the axis size is itself a device
        # count, i.e. a power of two on every supported topology)
        self._data = mesh.shape[DATA_AXIS]
        self.min_bucket = _pow2_at_least(self._data)
        # a ceiling below the data-axis floor would leave NO legal bucket
        self.max_bucket = max(max_bucket, self.min_bucket)
        self._batch_shd = NamedSharding(mesh, P(DATA_AXIS))
        # pin in_shardings off the LIVE weights when they already sit on
        # THIS mesh (the make_eval_step idiom): a TP/fsdp restore placed by
        # the loader serves resident-sharded; rule-derived placement is the
        # fallback for host arrays / single-device trees, and `device_put`
        # onto an array's own sharding is a no-op (no copy, no re-layout)
        self._param_shd = self._live_or_rule_sharding(params, mesh, rules)
        self._ms_shd = self._live_or_rule_sharding(model_state, mesh, rules)
        self.params = jax.device_put(params, self._param_shd)
        self.model_state = jax.device_put(model_state, self._ms_shd)
        #: version tag of the weights currently served (a train step after a
        #: hot swap; 0 for the construction-time weights)
        self.weights_version = 0
        # MoE checkpoints surface routed-overflow drops as a serve metric:
        # the compiled fwd returns `moe_drop_fraction_metric` beside the
        # logits (never silent truncation); predict() stores the last
        # batch's value here for the batcher to record.
        self._moe = (isinstance(model_state, dict)
                     and "moe_drop_fraction_metric" in model_state)
        self.last_moe_drop_fraction: float | None = None
        #: executed-batch count per height bucket (bench's seq-bucket
        #: traffic attribution; cache.per_key has the compile hit/miss side)
        self.seq_bucket_counts: dict = {}
        if memory_budget_bytes is not None:
            self.cache.set_budget(
                memory_budget_bytes,
                base_bytes=self.state_bytes_per_device()["total_bytes"])
        else:
            # budgetless engines still record the weights floor so the
            # stats/metrics weights-vs-executables split is live
            self.cache.set_base_bytes(
                self.state_bytes_per_device()["total_bytes"])

    @staticmethod
    def _live_or_rule_sharding(tree, mesh, rules):
        """Per-leaf: the leaf's own NamedSharding when it is already placed
        on `mesh`, else the rule-derived spec."""
        ruled = tree_sharding(tree, mesh, rules)

        def pick(leaf, rule_shd):
            shd = getattr(leaf, "sharding", None)
            if isinstance(shd, NamedSharding) and shd.mesh == mesh:
                return shd
            return rule_shd

        return jax.tree.map(pick, tree, ruled)

    def state_bytes_per_device(self) -> dict:
        """Per-device resident bytes of the SERVED weights under their
        actual placements (shard-shape metadata — no transfer): the serve
        analogue of `train.state.state_memory_bytes`, and the number an
        fsdp-sharded restore divides by the data axis."""
        from dist_mnist_tpu.train.state import _per_device_nbytes

        out = {
            "param_bytes": sum(_per_device_nbytes(x)
                               for x in jax.tree.leaves(self.params)),
            "model_state_bytes": sum(
                _per_device_nbytes(x)
                for x in jax.tree.leaves(self.model_state)),
        }
        out["total_bytes"] = out["param_bytes"] + out["model_state_bytes"]
        return out

    # -- hot swap ------------------------------------------------------------
    def swap_weights(self, params, model_state, *, version: int | None = None,
                     ) -> None:
        """Replace the served weights IN PLACE, without recompilation.

        The compiled executables take ``(params, model_state, x)`` as
        runtime arguments (see `_compile`), so new same-shaped weights run
        under the exact programs already cached — a weight rollout costs a
        device_put, never an XLA compile. Placement reuses the
        construction-time shardings, and the swap is all-or-nothing: both
        trees are validated (structure + per-leaf shape) and fully
        transferred BEFORE the engine pointers move, so any failure leaves
        the old weights serving untouched — which is what makes a kill
        mid-swap recoverable (docs/SERVING.md "Fleet router").

        A batch already executing keeps its references to the old arrays
        (the arguments were captured at call time); the swap is only
        *observable* from the next `predict`.

        A quantized engine RE-QUANTIZES an incoming float tree on the fly
        (the rollout path hands us full-width checkpoints): the cached
        int8 programs take (int8, scale) arguments, so quantizing before
        the shape checks is what keeps hot-swap compile-free.
        """
        if self.quant and not is_quantized(params):
            params = quantize_tree(params)

        def _check(old, new):
            if tuple(old.shape) != tuple(jnp.shape(new)):
                raise ValueError(
                    f"swap shape mismatch: {tuple(old.shape)} vs "
                    f"{tuple(jnp.shape(new))}"
                )
            return None

        jax.tree.map(_check, self.params, params)  # raises on tree mismatch
        jax.tree.map(_check, self.model_state, model_state)
        new_p = jax.device_put(params, self._param_shd)
        new_ms = jax.device_put(model_state, self._ms_shd)
        jax.block_until_ready((new_p, new_ms))  # fail HERE, not mid-predict
        self.params = new_p
        self.model_state = new_ms
        if version is not None:
            self.weights_version = int(version)
        log.info("swapped weights (version=%s)", self.weights_version)

    # -- bucketing -----------------------------------------------------------
    def bucket_for(self, n: int, height: int | None = None):
        """Batch bucket for `n` requests — and, with `height`, the 2-D
        (batch-bucket, height-bucket) grid cell a variable-length batch
        executes in. `height=None` keeps the classic int return."""
        if n < 1:
            raise ValueError("empty batch")
        b = max(_pow2_at_least(n), self.min_bucket)
        if b > self.max_bucket:
            raise ValueError(
                f"batch {n} needs bucket {b} > max_bucket {self.max_bucket}; "
                "raise max_bucket or split the batch upstream"
            )
        if height is None:
            return b
        return b, self.seq_bucket_for(height)

    def seq_bucket_for(self, height: int) -> int:
        """Height bucket for one request height; without a seq grid only
        the native height is servable."""
        if self.seq_grid is None:
            if height != self.image_shape[0]:
                raise ValueError(
                    f"height {height} != native {self.image_shape[0]} and "
                    "this engine has no seq grid (serve/zoo.py)")
            return height
        return self.seq_grid.bucket_for(height)

    def buckets(self) -> list[int]:
        """Every batch bucket this engine can execute, smallest first."""
        out, b = [], self.min_bucket
        while b <= self.max_bucket:
            out.append(b)
            b *= 2
        return out

    def grid(self) -> list[tuple[int, int]]:
        """Every (batch-bucket, height-bucket) cell, the prewarm/rewarm
        surface. Without a seq grid: one native-height column."""
        heights = (list(self.seq_grid.heights) if self.seq_grid is not None
                   else [self.image_shape[0]])
        return [(b, h) for b in self.buckets() for h in heights]

    # -- compilation ---------------------------------------------------------
    # Variant contract: `height=None` is the maskless NATIVE program
    # (bit-identical to eval); any explicit height — including the native
    # one — is the masked variable-length variant at that height bucket.
    # A masked native-shaped cell is reachable: a real height between the
    # largest sub-native bucket and native rounds UP into the native
    # bucket but still needs its padding masked.

    def _native(self, height: int) -> bool:
        return height == self.image_shape[0]

    def _key(self, bucket: int, height: int | None = None):
        h = self.image_shape[0] if height is None else height
        mesh_key = tuple(sorted(self.mesh.shape.items()))
        # quant mode rides the dtype component: an int8 engine's programs
        # take (int8, scale) weight arguments, so they can NEVER be keyed
        # identically to a float engine's (shared fleet caches included);
        # the float tag is byte-identical to the historical one
        dtype_key = ("uint8->float32" if not self.quant
                     else f"uint8->float32/w{self.quant}")
        key = (self.model_name, (bucket, h, *self.image_shape[1:]),
               mesh_key, dtype_key,
               "dense" if height is None else "masked")
        # the capacity factor is baked into an MoE program's expert-buffer
        # shapes, so two factors can never share an executable; folded in
        # only for MoE models so dense keys stay byte-identical
        cap = getattr(self.model, "moe_capacity_factor", None)
        if self._moe and cap is not None:
            key = (*key, ("moe_capacity_factor", cap))
        return key

    def _compile(self, bucket: int, height: int | None = None):
        h = self.image_shape[0] if height is None else height
        if height is None:
            # the maskless native program — bit-identical to
            # train/step.py's eval forward on the same checkpoint
            def fwd(params, model_state, x):
                x = x.astype(jnp.float32) / 255.0
                logits, out_state = self.model.apply(
                    params, model_state, x, train=False)
                if self._moe:
                    return logits, out_state["moe_drop_fraction_metric"]
                return logits

            in_shd = (self._param_shd, self._ms_shd, self._batch_shd)
            abstract = (jax.ShapeDtypeStruct(
                (bucket, *self.image_shape), jnp.uint8,
                sharding=self._batch_shd),)
        else:
            # masked sub-native program: right-padded rows + a token mask
            # (models' apply(mask=...); serve/zoo.SeqGrid semantics)
            def fwd(params, model_state, x, mask):
                x = x.astype(jnp.float32) / 255.0
                logits, out_state = self.model.apply(
                    params, model_state, x, train=False, mask=mask)
                if self._moe:
                    return logits, out_state["moe_drop_fraction_metric"]
                return logits

            n_tok = self.seq_grid.n_tokens(h)
            in_shd = (self._param_shd, self._ms_shd, self._batch_shd,
                      self._batch_shd)
            abstract = (
                jax.ShapeDtypeStruct((bucket, h, *self.image_shape[1:]),
                                     jnp.uint8, sharding=self._batch_shd),
                jax.ShapeDtypeStruct((bucket, n_tok), jnp.bool_,
                                     sharding=self._batch_shd),
            )
        out_shd = ((self._batch_shd, NamedSharding(self.mesh, P()))
                   if self._moe else self._batch_shd)
        jitted = jax.jit(fwd, in_shardings=in_shd, out_shardings=out_shd)
        return jitted.lower(self.params, self.model_state,
                            *abstract).compile()

    def _store_key(self, bucket: int, height: int | None = None) -> str:
        """Durable-store key for a grid cell's program — same contract as
        the train side (compilecache.cache_key folds jax/backend versions
        in)."""
        from dist_mnist_tpu.compilecache import cache_key

        h = self.image_shape[0] if height is None else height
        payload = {
            "kind": "serve",
            "model": self.model_name,
            "input_shape": (bucket, h, *self.image_shape[1:]),
            "mesh": tuple(sorted(self.mesh.shape.items())),
            "dtype": "uint8->float32",
            "rules": self._rules,
        }
        # native cells keep the exact historical payload so a pre-zoo disk
        # store stays warm across the upgrade; masked cells are new programs
        if height is not None:
            payload["variant"] = "masked"
        if self._moe:
            payload["moe_outputs"] = "drop_fraction"
            cap = getattr(self.model, "moe_capacity_factor", None)
            if cap is not None:
                # shapes change with the factor — see _key
                payload["moe_capacity_factor"] = cap
        # conditional for the same reason: float payloads stay byte-for-
        # byte what they were, while an int8 engine's store keys diverge —
        # a warm-start store can never hand an int8 program to a float
        # engine (or vice versa)
        if self.quant:
            payload["quant"] = self.quant
        return cache_key(payload)

    def compiled_for(self, bucket: int, height: int | None = None):
        # key the disk tier only when one is wired — predict() lands here
        # per request and the hash need not be paid on the memory fast path
        sk = (self._store_key(bucket, height)
              if self.cache._store is not None else None)
        return self.cache.get(
            self._key(bucket, height),
            lambda: self._compile(bucket, height), store_key=sk)

    def prewarm(self, buckets: list[int] | None = None,
                heights: list[int] | None = None) -> int:
        """Compile the expected (batch, height) grid up front (all of it by
        default) so live traffic never waits on XLA. Returns the number
        compiled. Under a memory budget this REFUSES (raises
        `ServeMemoryBudgetError`) a grid whose executables evicted each
        other while warming: a grid that cannot fit resident would turn
        every live request into a recompile, which is exactly the p99 hole
        prewarm exists to prevent — shrink the grid (fewer batch buckets /
        coarser heights) or raise the budget."""
        n0 = self.cache.misses
        ev0 = self.cache.evictions
        variable = self.seq_grid is not None and not self.seq_grid.native_only
        if heights is None:
            heights = (list(self.seq_grid.heights) if variable else [])
        for b in buckets if buckets is not None else self.buckets():
            bb = self.bucket_for(b)
            # dense native cell first (the bit-parity program every
            # full-length request runs), then — variable-length engines —
            # the masked cell per height, INCLUDING the masked native-
            # shaped one (real heights rounding up into the native bucket
            # land there; skipping it would be a hot-path recompile)
            self.compiled_for(bb)
            for h in heights:
                self.compiled_for(bb, h)
        if self.cache.evictions > ev0:
            raise ServeMemoryBudgetError(
                f"prewarm grid does not fit the serve memory budget "
                f"({self.cache.budget_bytes} B): "
                f"{self.cache.evictions - ev0} eviction(s) during warmup "
                "— the grid would thrash under live traffic; shrink it or "
                "raise --serve_memory_budget_mb")
        return self.cache.misses - n0

    # -- execution -----------------------------------------------------------
    def predict(self, images: np.ndarray,
                heights: np.ndarray | None = None) -> np.ndarray:
        """Logits for `images` [n, h, W, C]; pads to the (batch, height)
        grid cell, runs the cached executable, unpads. `h` may be any
        servable height when the engine has a seq grid (the batcher groups
        requests by height first); `heights` optionally carries each row's
        REAL height when rows were already padded to a common `h`. The
        executed-batch clock stops on the device_get of the logits
        (utils/timing.py discipline)."""
        images = np.asarray(images)
        if images.shape[2:] != self.image_shape[1:] or images.ndim != 4:
            raise ValueError(
                f"image shape {images.shape[1:]} != engine's {self.image_shape}"
            )
        n, h = images.shape[0], images.shape[1]
        bucket = self.bucket_for(n)
        h_bucket = self.seq_bucket_for(h)
        real_h = (np.full((n,), h) if heights is None
                  else np.asarray(heights))
        # the native cell runs the maskless bit-parity program only when no
        # row is actually short; short rows rounded into the native bucket
        # use the masked native-shaped variant
        masked = (not self._native(h_bucket)) or bool(
            np.any(real_h < self.image_shape[0]))
        if masked and self.seq_grid is None:
            raise ValueError(
                "variable-length rows need a seq grid (serve/zoo.py)")
        exe = self.compiled_for(bucket,
                                h_bucket if masked else None)
        if h < h_bucket:
            pad = np.zeros((n, h_bucket - h, *self.image_shape[1:]),
                           dtype=np.uint8)
            images = np.concatenate([images.astype(np.uint8), pad], axis=1)
        if n < bucket:
            pad = np.zeros((bucket - n, h_bucket, *self.image_shape[1:]),
                           dtype=np.uint8)
            images = np.concatenate([images.astype(np.uint8), pad])
        args = [jax.device_put(images.astype(np.uint8), self._batch_shd)]
        if masked:
            mask = np.zeros((bucket, self.seq_grid.n_tokens(h_bucket)),
                            dtype=bool)
            mask[:n] = self.seq_grid.mask(real_h, h_bucket)
            args.append(jax.device_put(mask, self._batch_shd))
        self.seq_bucket_counts[h_bucket] = \
            self.seq_bucket_counts.get(h_bucket, 0) + 1
        with stopclock(self.cache.times, "execute"):
            # THE batched logits pull — the one intentional
            # lint: ok[host-sync] sync per executed batch (stop-clock discipline)
            out = jax.device_get(exe(self.params, self.model_state, *args))
        if self._moe:
            logits, drop = out
            # lint: ok[host-sync] `drop` arrived in the device_get above
            self.last_moe_drop_fraction = float(drop)
        else:
            logits = out
            self.last_moe_drop_fraction = None
        return np.asarray(logits)[:n]
