"""The inference engine: power-of-two bucketing + an AOT compiled-model
cache over the data-axis mesh.

Why buckets: a continuous batcher produces a *different* batch size every
tick; jitting on the raw size would recompile on nearly every request
pattern. Rounding up to a power of two caps the number of distinct
executables at log2(max_batch) while wasting at most 2x compute on padding
— and padding rows are pure throughput cost, never a correctness one
(logits for pad rows are sliced off before completion).

Why AOT (`jit(...).lower(...).compile()`): the cache makes compilation an
*explicit, observable* event — hit/miss counters and compile-time
attribution (utils/timing.stopclock) instead of jit's invisible internal
cache, and `prewarm()` can move every expected compile to startup where it
cannot poke a p99 latency hole in live traffic.

The batch rides the `data` axis exactly as in training (`P(DATA_AXIS)`,
the same spec data/pipeline.py uses), so a bucket of B runs B/data rows
per device; params/model_state are placed once at engine construction by
the same `parallel/sharding.py` rules the model trained under.
"""

from __future__ import annotations

import logging
import threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import DATA_AXIS
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.parallel.sharding import DP_RULES, ShardingRules, tree_sharding
from dist_mnist_tpu.utils.timing import stopclock

log = logging.getLogger(__name__)


class CompiledModelCache:
    """key -> AOT-compiled executable, with hit/miss counters, per-key
    compile/load attribution, and an optional DISK tier. Keys are
    `(model_name, input_shape, mesh_key, dtype)` — everything that changes
    the compiled program.

    With `store` (a compilecache.ExecutableStore), a memory miss consults
    the store before compiling and saves after: a restarted server's
    `prewarm()` deserializes last generation's executables in milliseconds
    instead of recompiling every bucket. Hits are tiered — `hits_memory`
    vs `hits_disk` — and `per_key` records, for each key, which tier
    satisfied it first and the compile-or-load wall ms it cost."""

    def __init__(self, store=None):
        self._lock = threading.Lock()
        self._cache: dict = {}
        self._store = store
        self.hits = 0
        self.misses = 0
        self.hits_memory = 0
        self.hits_disk = 0
        #: key -> {"tier": memory|disk|fresh, "compile_ms", "load_ms", "hits"}
        self.per_key: dict = {}
        self.times: dict = {}  # stopclock accumulator: compile/execute secs

    def get(self, key, build, *, store_key: str | None = None):
        """The executable for `key`: memory tier, then the disk store
        (when wired and `store_key` given), then `build()`. Compilation
        runs under the lock: concurrent misses for the same bucket must
        not compile twice."""
        with self._lock:
            if key in self._cache:
                self.hits += 1
                self.hits_memory += 1
                self.per_key[key]["hits"] += 1
                return self._cache[key]
            if self._store is not None and store_key is not None:
                t0 = _time.perf_counter()
                exe = self._store.load(store_key)
                if exe is not None:
                    load_ms = (_time.perf_counter() - t0) * 1e3
                    self.hits += 1
                    self.hits_disk += 1
                    self.per_key[key] = {"tier": "disk", "compile_ms": 0.0,
                                         "load_ms": load_ms, "hits": 1}
                    self._cache[key] = exe
                    log.info("loaded %s from compile cache (%.0f ms)",
                             key, load_ms)
                    return exe
            self.misses += 1
            with stopclock(self.times, "compile"):
                t0 = _time.perf_counter()
                exe = build()
                compile_ms = (_time.perf_counter() - t0) * 1e3
            self.per_key[key] = {"tier": "fresh", "compile_ms": compile_ms,
                                 "load_ms": 0.0, "hits": 0}
            self._cache[key] = exe
            if self._store is not None and store_key is not None:
                self._store.save(store_key, exe,
                                 meta={"compile_ms": compile_ms})
            log.info("compiled %s (miss #%d, %.0f ms)", key, self.misses,
                     compile_ms)
            # the disk tier journals its own hits/misses (compilecache/
            # store.py); a fresh compile is the remaining interesting case
            events.emit("compile_cache", outcome="compile", key=str(key),
                        compile_ms=round(compile_ms, 3))
            return exe

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hits_memory": self.hits_memory,
                "hits_disk": self.hits_disk,
                "entries": len(self._cache),
                "compile_secs": self.times.get("compile", 0.0),
                "execute_secs": self.times.get("execute", 0.0),
                "execute_count": self.times.get("execute_count", 0),
                "per_key": {str(k): dict(v) for k, v in self.per_key.items()},
            }


def _pow2_at_least(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


class InferenceEngine:
    """Stateless-forward inference over a fixed (model, weights, mesh).

    `predict(images)` takes a host batch of raw uint8 images `[n, H, W, C]`
    and returns logits `[n, classes]` — padding, placement, compilation
    caching and unpadding are internal. Normalization matches
    train/step.py's eval step exactly (`x/255`), so serving a checkpoint
    reproduces its eval accuracy bit-for-bit per row.
    """

    def __init__(
        self,
        model,
        params,
        model_state,
        mesh: Mesh,
        *,
        model_name: str = "model",
        image_shape: tuple[int, ...],
        rules: ShardingRules = DP_RULES,
        max_bucket: int = 256,
        store=None,
        cache: CompiledModelCache | None = None,
    ):
        self.model = model
        self.mesh = mesh
        self.model_name = model_name
        self.image_shape = tuple(image_shape)
        # `cache` lets N same-model replicas share one CompiledModelCache:
        # executables take (params, model_state, x) as runtime arguments, so
        # a program compiled by replica 0 serves replica 1's weights too —
        # the fleet pays log2(max_batch) compiles once, not per replica.
        # A provided cache keeps ITS store; `store` only seeds a fresh one.
        self.cache = cache if cache is not None else CompiledModelCache(store=store)
        self._rules = rules
        # buckets must divide over the data axis; the smallest power of two
        # >= the axis size always does (the axis size is itself a device
        # count, i.e. a power of two on every supported topology)
        self._data = mesh.shape[DATA_AXIS]
        self.min_bucket = _pow2_at_least(self._data)
        # a ceiling below the data-axis floor would leave NO legal bucket
        self.max_bucket = max(max_bucket, self.min_bucket)
        self._batch_shd = NamedSharding(mesh, P(DATA_AXIS))
        self._param_shd = tree_sharding(params, mesh, rules)
        self._ms_shd = tree_sharding(model_state, mesh, rules)
        self.params = jax.device_put(params, self._param_shd)
        self.model_state = jax.device_put(model_state, self._ms_shd)
        #: version tag of the weights currently served (a train step after a
        #: hot swap; 0 for the construction-time weights)
        self.weights_version = 0

    # -- hot swap ------------------------------------------------------------
    def swap_weights(self, params, model_state, *, version: int | None = None,
                     ) -> None:
        """Replace the served weights IN PLACE, without recompilation.

        The compiled executables take ``(params, model_state, x)`` as
        runtime arguments (see `_compile`), so new same-shaped weights run
        under the exact programs already cached — a weight rollout costs a
        device_put, never an XLA compile. Placement reuses the
        construction-time shardings, and the swap is all-or-nothing: both
        trees are validated (structure + per-leaf shape) and fully
        transferred BEFORE the engine pointers move, so any failure leaves
        the old weights serving untouched — which is what makes a kill
        mid-swap recoverable (docs/SERVING.md "Fleet router").

        A batch already executing keeps its references to the old arrays
        (the arguments were captured at call time); the swap is only
        *observable* from the next `predict`.
        """

        def _check(old, new):
            if tuple(old.shape) != tuple(jnp.shape(new)):
                raise ValueError(
                    f"swap shape mismatch: {tuple(old.shape)} vs "
                    f"{tuple(jnp.shape(new))}"
                )
            return None

        jax.tree.map(_check, self.params, params)  # raises on tree mismatch
        jax.tree.map(_check, self.model_state, model_state)
        new_p = jax.device_put(params, self._param_shd)
        new_ms = jax.device_put(model_state, self._ms_shd)
        jax.block_until_ready((new_p, new_ms))  # fail HERE, not mid-predict
        self.params = new_p
        self.model_state = new_ms
        if version is not None:
            self.weights_version = int(version)
        log.info("swapped weights (version=%s)", self.weights_version)

    # -- bucketing -----------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        if n < 1:
            raise ValueError("empty batch")
        b = max(_pow2_at_least(n), self.min_bucket)
        if b > self.max_bucket:
            raise ValueError(
                f"batch {n} needs bucket {b} > max_bucket {self.max_bucket}; "
                "raise max_bucket or split the batch upstream"
            )
        return b

    def buckets(self) -> list[int]:
        """Every bucket size this engine can execute, smallest first."""
        out, b = [], self.min_bucket
        while b <= self.max_bucket:
            out.append(b)
            b *= 2
        return out

    # -- compilation ---------------------------------------------------------
    def _key(self, bucket: int):
        mesh_key = tuple(sorted(self.mesh.shape.items()))
        return (self.model_name, (bucket, *self.image_shape), mesh_key,
                "uint8->float32")

    def _compile(self, bucket: int):
        def fwd(params, model_state, x):
            x = x.astype(jnp.float32) / 255.0
            logits, _ = self.model.apply(params, model_state, x, train=False)
            return logits

        jitted = jax.jit(
            fwd,
            in_shardings=(self._param_shd, self._ms_shd, self._batch_shd),
            out_shardings=self._batch_shd,
        )
        abstract_x = jax.ShapeDtypeStruct(
            (bucket, *self.image_shape), jnp.uint8, sharding=self._batch_shd
        )
        return jitted.lower(self.params, self.model_state, abstract_x).compile()

    def _store_key(self, bucket: int) -> str:
        """Durable-store key for a bucket's program — same contract as the
        train side (compilecache.cache_key folds jax/backend versions in)."""
        from dist_mnist_tpu.compilecache import cache_key

        return cache_key({
            "kind": "serve",
            "model": self.model_name,
            "input_shape": (bucket, *self.image_shape),
            "mesh": tuple(sorted(self.mesh.shape.items())),
            "dtype": "uint8->float32",
            "rules": self._rules,
        })

    def compiled_for(self, bucket: int):
        # key the disk tier only when one is wired — predict() lands here
        # per request and the hash need not be paid on the memory fast path
        sk = (self._store_key(bucket)
              if self.cache._store is not None else None)
        return self.cache.get(self._key(bucket), lambda: self._compile(bucket),
                              store_key=sk)

    def prewarm(self, buckets: list[int] | None = None) -> int:
        """Compile the expected buckets up front (all of them by default) so
        live traffic never waits on XLA. Returns the number compiled."""
        n0 = self.cache.misses
        for b in buckets if buckets is not None else self.buckets():
            self.compiled_for(self.bucket_for(b))
        return self.cache.misses - n0

    # -- execution -----------------------------------------------------------
    def predict(self, images: np.ndarray) -> np.ndarray:
        """Logits for `images` [n, *image_shape]; pads to the bucket, runs
        the cached executable, unpads. The executed-batch clock stops on the
        device_get of the logits (utils/timing.py discipline)."""
        images = np.asarray(images)
        if images.shape[1:] != self.image_shape:
            raise ValueError(
                f"image shape {images.shape[1:]} != engine's {self.image_shape}"
            )
        n = images.shape[0]
        bucket = self.bucket_for(n)
        exe = self.compiled_for(bucket)
        if n < bucket:
            pad = np.zeros((bucket - n, *self.image_shape), dtype=np.uint8)
            images = np.concatenate([images.astype(np.uint8), pad])
        x = jax.device_put(images.astype(np.uint8), self._batch_shd)
        with stopclock(self.cache.times, "execute"):
            logits = np.asarray(
                jax.device_get(exe(self.params, self.model_state, x))
            )
        return logits[:n]
