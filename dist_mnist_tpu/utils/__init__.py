"""Small shared utilities."""

from __future__ import annotations

import jax
import numpy as np


def param_count(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def tree_bytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def format_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024:
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} TiB"
