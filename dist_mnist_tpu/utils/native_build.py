"""Shared build-and-load machinery for the C++ components.

One place for the g++ invocation, mtime-based rebuild cache, and lazy CDLL
loading used by parallel/ps_demo and data/native.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
import threading
from pathlib import Path

log = logging.getLogger(__name__)

_lock = threading.Lock()
_loaded: dict[Path, ctypes.CDLL] = {}


def build_shared_lib(src: Path, out: Path, *, force: bool = False) -> Path:
    """Compile src -> out with g++ (skipped when out is newer than src)."""
    with _lock:
        if not force and out.exists() and out.stat().st_mtime >= src.stat().st_mtime:
            return out
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               str(src), "-o", str(out)]
        log.info("building native library: %s", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except FileNotFoundError as e:
            raise RuntimeError("g++ not available for native components") from e
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed:\n{e.stderr}"
            ) from e
        return out


def load_lib(src: Path, out: Path, signatures: dict) -> ctypes.CDLL:
    """Build (if needed) + load + apply ctypes signatures; cached per path.

    `signatures`: name -> (argtypes, restype).
    """
    with _lock:
        if out in _loaded:
            return _loaded[out]
    build_shared_lib(src, out)
    lib = ctypes.CDLL(str(out))
    for name, (argtypes, restype) in signatures.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    with _lock:
        _loaded[out] = lib
    return lib
