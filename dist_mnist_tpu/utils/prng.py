"""Scoped PRNG-implementation selection.

`rbg` exists because threefry2x32's bit-mixing is a measurable TPU cost
for per-layer dropout masks (configs.py `prng_impl`); the impl must be the
process default BEFORE any key is made so init, dropout, and in-program
sampling derive from one impl, and must be restored afterwards so
co-resident runs (tests, sweeps, probe variants) keep theirs. One
definition — cli/train.run_config and scripts/vit_probe both scope
through here. A checkpoint written under one impl must be resumed under
the same impl (key shapes differ across impls, so a mismatch fails loudly
at restore rather than silently).
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def prng_impl_scope(impl: str):
    """Make `impl` the process-default PRNG inside the scope.

    Compares against the CURRENT default (not the library default), so an
    explicit threefry config is enforced even when the ambient default was
    changed by env or a prior caller; restores the previous default on
    every exit path."""
    import jax

    prev = jax.config.jax_default_prng_impl
    if impl != prev:
        jax.config.update("jax_default_prng_impl", impl)
    try:
        yield
    finally:
        if impl != prev:
            jax.config.update("jax_default_prng_impl", prev)
