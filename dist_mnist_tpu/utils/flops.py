"""FLOPs accounting: XLA-counted step FLOPs ÷ time ÷ chip peak = MFU.

The reference stack had no FLOPs accounting at all (its per-step cost was
dominated by the gRPC weight pull/grad push, SURVEY.md §3.3); on TPU the
honest cross-dataset performance metric is model-FLOPs utilization — what
fraction of the MXU's peak the training step sustains. The numerator comes
from XLA's own cost model on the compiled program
(`train/step.py` `wrapper.cost_analysis`), so it is the true compiled-op
count, not a hand-derived estimate.
"""

from __future__ import annotations

import jax

# Peak dense bf16 matmul throughput per chip (FLOP/s), keyed by
# `jax.Device.device_kind`. Public figures from the TPU system docs.
PEAK_BF16_FLOPS: dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}


def device_peak_flops(device: jax.Device | None = None) -> float | None:
    """bf16 peak for `device` (default: first visible device); None when the
    chip isn't in the table (CPU/GPU/unknown kind) — MFU is then unknowable
    and must be reported as null, not guessed."""
    device = device or jax.devices()[0]
    return PEAK_BF16_FLOPS.get(device.device_kind)


# Peak HBM bandwidth per chip (bytes/s), same public TPU system docs and
# same device_kind keys — the denominator for the memory-bound side of the
# roofline (`bench.py --kernels` achieved-vs-peak attribution).
PEAK_HBM_BYTES: dict[str, float] = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5": 2765e9,        # v5p
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,   # v6e / Trillium
    "TPU v6e": 1640e9,
}


def device_peak_hbm_bytes(device: jax.Device | None = None) -> float | None:
    """HBM bandwidth peak for `device`; None off-table (CPU/GPU/unknown) —
    achieved-vs-peak fractions are then reported as null, never guessed."""
    device = device or jax.devices()[0]
    return PEAK_HBM_BYTES.get(device.device_kind)


def analytic_step_flops(model, sample_shape, batch: int,
                        bwd_multiplier: float = 2.0) -> float | None:
    """Analytic training-step FLOPs: batch x (1 + bwd_multiplier) x the
    model's published forward count (`flops_per_example`), the standard
    "model FLOPs" convention (backward ~= 2x forward for matmul-dominated
    nets). This is the MFU numerator of record: XLA's cost analysis counts
    a `lax.scan` body ONCE, so any model that scans over layers
    (ViT `scan_blocks`) has its compiled-program count understated by
    ~depth x — discovered when the ViT ladder point reported 0.5% MFU
    from a 13.8G XLA count vs ~46G actual forward FLOPs. None when the
    model doesn't publish a count."""
    fwd = getattr(model, "flops_per_example", None)
    if fwd is None:
        return None
    return batch * (1.0 + bwd_multiplier) * fwd(sample_shape)


def step_flops(step_fn, *args) -> float | None:
    """FLOPs XLA counts for one invocation of a `_lazy_jit` step wrapper
    (or any object exposing `.cost_analysis(*args)` / a jitted fn).

    NOTE (verified on this backend): XLA's HLO cost analysis counts a
    `while`-loop body ONCE, regardless of trip count — so for a
    `make_scanned_train_fn` chunk the returned number already IS the
    per-STEP figure (one scan-body execution + the negligible epilogue),
    not the per-chunk total. Do not divide by the chunk length.
    COROLLARY: the same once-per-body rule UNDERSTATES any model whose
    layer stack itself runs under a scan (ViT scan_blocks) — use
    `analytic_step_flops` as the MFU numerator and keep this as the
    no-nested-scan cross-check."""
    try:
        cost = getattr(step_fn, "cost_analysis", None)
        if cost is not None:
            ca = cost(*args)
        else:  # a plain jax.jit-ed function
            ca = step_fn.lower(*args).compile().cost_analysis()
    except Exception:  # noqa: BLE001 — metrics aid, never fail a run
        return None
    if isinstance(ca, (list, tuple)):  # older jax: one dict per device
        ca = ca[0] if ca else None
    if ca is None:
        return None
    flops = ca.get("flops") if hasattr(ca, "get") else None
    return float(flops) if flops else None


def mfu(flops_per_step: float | None, step_secs: float,
        device: jax.Device | None = None) -> float | None:
    """Model-FLOPs utilization in [0, 1]; None when either side is unknown."""
    peak = device_peak_flops(device)
    if not flops_per_step or not peak or step_secs <= 0:
        return None
    return flops_per_step / step_secs / peak
