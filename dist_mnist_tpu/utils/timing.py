"""The hardened throughput stop-clock (single definition, used by bench.py,
scripts/perf_sweep.py and scripts/step_ablation.py).

On this image's experimental axon TPU relay, `jax.block_until_ready` can
return EARLY once several compiled programs have executed in one process —
measured symptom: benchmark rates above the chip's physical peak (up to
3.5M steps/sec ≈ 44 PFLOP/s on a 197 TFLOP/s v5e), unstable run-to-run.
A `jax.device_get` of the final output is immune: actual bytes cannot be
handed back before the dependency chain has executed. docs/PERF.md
("Timing methodology") records the evidence; keep every timed loop on this
helper so a future clock fix lands in one place.
"""

from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def stopclock(acc: dict, key: str):
    """Accumulate the block's wall time (seconds) into ``acc[key]`` and
    bump ``acc[key + "_count"]`` — the serve-side compile/execute
    attribution primitive (serve/engine.py). Callers timing device work
    must keep the device_get-inside-the-block discipline this module's
    docstring mandates: the clock can only stop on bytes actually handed
    back to the host."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        dt = time.monotonic() - t0
        acc[key] = acc.get(key, 0.0) + dt
        acc[key + "_count"] = acc.get(key + "_count", 0) + 1


def timed_chunks(run_fn, state, n_chunks: int):
    """Warm up once, then time `n_chunks` chained `state -> (state, out)`
    calls; the clock stops on a device_get of the final `out["loss"]`.

    Returns `(seconds, final_state, final_loss)` — callers should surface
    the loss as an executed-for-real sanity check (it decreases under
    training; a chain that never ran would not)."""
    state, out = run_fn(state)  # compile + warmup, outside the clock
    float(jax.device_get(out["loss"]))
    t0 = time.monotonic()
    for _ in range(n_chunks):
        state, out = run_fn(state)
    loss = float(jax.device_get(out["loss"]))
    return time.monotonic() - t0, state, loss
