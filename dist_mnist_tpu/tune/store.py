"""TunedConfigStore: persisted knob winners, keyed like the executable cache.

An entry is one JSON file per geometry key holding the winning knob
values AND the measurement evidence that justified them (metric,
winner/baseline scores, bench stage, search shape, timestamp). The key
is `compilecache/store.cache_key` over `compilecache/key_fields.py
compile_cache_key_fields` — model config, mesh shape, sharding, dtype,
backend, jax/jaxlib versions — so a tuned value can never be silently
applied to a geometry it wasn't measured on: change the mesh, the
backend or the jax version and the lookup misses.

Two deliberate deviations from the raw compile key:

- the tuned knobs THEMSELVES (`overlap`/`overlap_bucket_mb`/
  `overlap_chunk`) are dropped from the key fields. The lookup happens
  with the launch-time config, before the winner is applied; if the
  knob's own value were keyed, a stored winner could only ever match a
  run already launched with it.
- a `kind: "tuned"` field separates this namespace from the executable
  store's step keys.

Failure semantics mirror `compilecache.ExecutableStore`: atomic
tmp+rename writes, a corrupt or truncated entry is quarantined
(unlinked, counted) and reads as a miss — never a crash — and a failed
save degrades to a warning.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from pathlib import Path

from dist_mnist_tpu.obs import events
from dist_mnist_tpu.tune.spec import KNOBS

log = logging.getLogger("dist_mnist_tpu.tune")

ENTRY_SUFFIX = ".tuned.json"
TMP_PREFIX = ".tmp-"

#: supervisors inject a shared store dir across restarts, like the journal
ENV_TUNED_DIR = "DIST_MNIST_TPU_TUNED_DIR"

#: in-flight tmp files (leak-checked by tests/conftest.py, same contract
#: as compilecache.store._PENDING_TMP)
_PENDING_TMP: set = set()

#: key fields that ARE tuned knobs (or their master switch) — excluded
#: from the tuning key so a winner can match the run it should improve
TUNED_KEY_EXCLUDES = ("overlap", "overlap_bucket_mb", "overlap_chunk")


class TunedConfigMissError(RuntimeError):
    """--tuned=require and the store has no entry for this geometry."""


def tuned_key_fields(cfg, mesh) -> dict:
    """The geometry fields the tuning key hashes (see module docstring
    for why the tuned knobs themselves are excluded)."""
    # key_fields, not cli.train: importing the train CLI from a serve or
    # tune process would re-run its flags.DEFINE_* block (DuplicateFlagError
    # under `python -m`, --config collision from cli/serve.py)
    from dist_mnist_tpu.compilecache.key_fields import compile_cache_key_fields

    fields = compile_cache_key_fields(cfg, mesh)
    for name in TUNED_KEY_EXCLUDES:
        fields.pop(name, None)
    fields["kind"] = "tuned"
    return fields


def tuning_key(cfg, mesh, **overrides) -> str:
    """Store key for (cfg, mesh) on the current backend/jax version.
    `overrides` lets tests pin a foreign backend/jax_version without
    monkeypatching jax (cache_key folds explicit fields over its
    auto-merged ones)."""
    from dist_mnist_tpu.compilecache.store import cache_key

    return cache_key({**tuned_key_fields(cfg, mesh), **overrides})


class TunedConfigStore:
    """Directory of `<key>.tuned.json` winner entries."""

    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._corrupt = 0
        self._saves = 0
        self._save_errors = 0

    def _path(self, key: str) -> Path:
        return self.root / f"{key}{ENTRY_SUFFIX}"

    def load(self, key: str) -> dict | None:
        """The entry dict, or None on miss. A corrupt/truncated entry is
        quarantined (unlinked + counted) and reported as a miss."""
        path = self._path(key)
        if not path.exists():
            with self._lock:
                self._misses += 1
            return None
        try:
            entry = json.loads(path.read_text())
            if not isinstance(entry, dict) or not isinstance(
                    entry.get("knobs"), dict):
                raise ValueError("entry is not a {knobs: {...}} object")
        except (ValueError, OSError) as e:
            log.warning("tuned store: quarantining corrupt entry %s (%s)",
                        path.name, e)
            try:
                path.unlink()
            except OSError:
                pass
            with self._lock:
                self._corrupt += 1
                self._misses += 1
            return None
        with self._lock:
            self._hits += 1
        return entry

    def save(self, key: str, entry: dict) -> int:
        """Atomically persist `entry`; returns bytes written (0 on a
        failed save — tuning evidence is an aid, never a crash)."""
        path = self._path(key)
        tmp = self.root / f"{TMP_PREFIX}{key}-{os.getpid()}"
        blob = json.dumps({"key": key, **entry}, indent=1, sort_keys=True)
        _PENDING_TMP.add(tmp)
        try:
            tmp.write_text(blob)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("tuned store: could not save %s (%s)", path.name, e)
            try:
                tmp.unlink()
            except OSError:
                pass
            with self._lock:
                self._save_errors += 1
            return 0
        finally:
            _PENDING_TMP.discard(tmp)
        with self._lock:
            self._saves += 1
        return len(blob)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "hits": self._hits,
                "misses": self._misses,
                "corrupt": self._corrupt,
                "saves": self._saves,
                "save_errors": self._save_errors,
            }
        out["entries"] = len(list(self.root.glob(f"*{ENTRY_SUFFIX}")))
        return out


def make_entry(cfg, mesh, results) -> dict:
    """Store entry from per-spec `SearchResult`s (tune/search.py): the
    flattened winning knob values plus per-knob embedded evidence."""
    knobs: dict = {}
    evidence: dict = {}
    for res in results:
        knobs.update(res.spec.knob_values(res.winner))
        evidence[res.spec.name] = res.evidence()
    import jax
    import jaxlib

    return {
        "knobs": knobs,
        "evidence": evidence,
        "fields": {k: repr(v) for k, v in
                   sorted(tuned_key_fields(cfg, mesh).items())},
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "jaxlib_version": getattr(jaxlib.version, "__version__", "unknown"),
        "created_at": time.time(),
    }


def _resolve_store_dir(store_dir) -> str | None:
    return store_dir or os.environ.get(ENV_TUNED_DIR)


def apply_tuned(cfg, mesh, *, mode: str = "auto", store_dir=None,
                protect=(), subsystem: str = "train"):
    """`--tuned` lookup+apply: returns `(cfg, runtime_knobs)`.

    On a key hit, every auto-apply knob whose spec targets `subsystem`
    ("train" -> config + train_runtime knobs, "serve" -> serve knobs)
    and is not in `protect` (names the operator pinned with an explicit
    flag) is applied — config knobs via dataclasses.replace, the rest
    returned in `runtime_knobs` for the caller to thread through. Each
    application emits a `tuning/applied` journal event carrying the
    stored evidence; a miss emits `tuning/stale_key` and falls back to
    defaults (`mode="auto"`) or raises (`mode="require"`).
    `mode="off"` is handled by the CALLER never invoking this — the off
    path stays bit-identical to pre-tuner behavior by not importing it.
    """
    if mode not in ("auto", "require"):
        raise ValueError(f"tuned mode must be auto|require, got {mode!r}")
    root = _resolve_store_dir(store_dir)
    targets = (("config", "train_runtime") if subsystem == "train"
               else ("serve",))
    if root is None:
        if mode == "require":
            raise TunedConfigMissError(
                "--tuned=require but no tuned-config store is configured "
                f"(--tuned_dir / ${ENV_TUNED_DIR})")
        return cfg, {}
    key = tuning_key(cfg, mesh)
    entry = TunedConfigStore(root).load(key)
    if entry is None:
        events.emit("tuning/stale_key", key=key, store=str(root),
                    mode=mode, subsystem=subsystem)
        if mode == "require":
            raise TunedConfigMissError(
                f"--tuned=require but the store at {root} has no entry "
                f"for key {key} (this model/mesh/backend/jax-version "
                "geometry was never tuned — run cli/tune.py on it, or "
                "drop to --tuned=auto)")
        return cfg, {}
    stored = entry["knobs"]
    evidence = entry.get("evidence", {})
    config_updates: dict = {}
    runtime_knobs: dict = {}
    for spec in KNOBS.values():
        if not spec.auto_apply or spec.target not in targets:
            continue
        names = spec.fields if spec.fields else (spec.name,)
        applied = {n: stored[n] for n in names
                   if n in stored and n not in protect}
        if not applied:
            continue
        if spec.target == "config":
            config_updates.update(applied)
        else:
            runtime_knobs.update(applied)
        ev = evidence.get(spec.name, {})
        events.emit(
            "tuning/applied", key=key, knob=spec.name,
            value=applied if spec.fields else next(iter(applied.values())),
            metric=ev.get("metric", spec.metric),
            measured=ev.get("value"), baseline=ev.get("baseline"),
            bench_stage=ev.get("bench_stage", spec.bench_stage),
            measured_at=ev.get("measured_at", entry.get("created_at")),
        )
    if config_updates:
        cfg = dataclasses.replace(cfg, **config_updates)
    return cfg, runtime_knobs
