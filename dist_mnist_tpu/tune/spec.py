"""The declared knob space: every tunable registers a `TunableSpec`.

A spec is the contract between three consumers:

- the search engine (tune/search.py) reads `candidates`, `metric`,
  `direction` and runs successive halving over short seeded bench legs;
- the apply path (tune/store.py apply_tuned) reads `target`,
  `auto_apply` and `knob_fields()` to decide where a stored winner lands
  (a Config field, a train runtime parameter, or a serve flag);
- the graftlint cache-key rule reads `compile_relevant` and cross-checks
  it against `compilecache/key_fields.py compile_cache_key_fields`: a knob declared
  compile-relevant must fold into the executable-store key (so a
  tuner-applied change forces a compile-cache miss), and a runtime-only
  knob must carry its reason in the rule's TUNER_RUNTIME_ONLY allowlist.

Objectives live in tune/objectives.py, keyed by `name` — the spec is
pure metadata so the catalog imports without jax.
"""

from __future__ import annotations

import dataclasses

#: candidate ladders are tuples; a serve-grid candidate is itself a tuple
#: zipped against `fields` (see TunableSpec.knob_values)
Candidate = object


@dataclasses.dataclass(frozen=True)
class TunableSpec:
    """One knob's declared search space and application contract."""

    name: str                  # catalog key; also the stored knob name
    subsystem: str             # overlap | input | serve | checkpoint | headline
    candidates: tuple          # the ladder successive halving prunes
    default: Candidate         # the stock default the winner must beat
    metric: str                # objective name recorded in the evidence
    bench_stage: str           # which bench leg family measures it
    target: str                # config | train_runtime | serve
    direction: str = "lower_is_better"
    #: True: the applied value changes the traced program, so it MUST be
    #: part of compile_cache_key_fields (lint-enforced); False: runtime
    #: only, allowlisted with a reason in analysis/rules/cache_key.py
    compile_relevant: bool = False
    #: True: the objective is a deterministic function of (candidate,
    #: budget, seed) on any backend — safe for CI and `bench.py --tune`;
    #: False: wall-clock timed, offline `cli/tune.py` only
    deterministic: bool = True
    #: False: searchable offline but never applied by `--tuned=auto`
    #: (the doc says why); True: a store hit applies it
    auto_apply: bool = True
    #: multi-valued knobs: candidate tuples zip against these stored
    #: knob names (e.g. serve_grid -> serve_max_batch, serve_seq_buckets)
    fields: tuple = ()
    doc: str = ""

    def knob_values(self, candidate) -> dict:
        """Map a candidate to the {stored_knob_name: value} dict the
        store persists and apply_tuned reads."""
        if self.fields:
            return dict(zip(self.fields, candidate))
        return {self.name: candidate}

    def better(self, a: float, b: float) -> bool:
        """True when score `a` beats score `b` under `direction`."""
        return a < b if self.direction == "lower_is_better" else a > b


#: every registered knob. Ladders are deliberately short: successive
#: halving keeps total trial count ~2x the ladder length.
KNOBS: dict[str, TunableSpec] = {
    "overlap_bucket_mb": TunableSpec(
        name="overlap_bucket_mb",
        subsystem="overlap",
        candidates=(0.5, 1.0, 2.0, 4.0, 8.0),
        default=4.0,  # configs.Config.overlap_bucket_mb
        metric="exposed_gather_cost_mb",
        bench_stage="overlap",
        target="config",
        compile_relevant=True,
        doc=(
            "fsdp gather-bucket granularity (parallel/overlap.py). The "
            "objective is a byte-denominated schedule cost over the REAL "
            "gather plan (plan_stats on the live mesh): mean bucket size "
            "(the head-of-line gather nothing can hide behind) plus a "
            "fixed per-launch toll per bucket. Byte-denominated because "
            "it is the stand-in for comm_exposed_ms_per_step that stays "
            "deterministic on the CPU lane, where XLA runs collectives "
            "inline and wall-clock cannot resolve the schedule (the "
            "bench --overlap timing_resolves_overlap caveat)."),
    ),
    "serve_grid": TunableSpec(
        name="serve_grid",
        subsystem="serve",
        fields=("serve_max_batch", "serve_seq_buckets"),
        candidates=(
            (64, ""),                       # stock: native-only, pre-zoo
            (64, "auto"),                   # power-of-two height ladder
            (64, "4,8,12,16,20,24,28"),     # every patch multiple
            (32, "auto"),
            (128, "auto"),
            (32, "4,8,12,16,20,24,28"),
        ),
        default=(64, ""),  # cli/serve.py --max_batch/--seq_buckets defaults
        metric="serve_padded_slot_ratio",
        bench_stage="serve",
        target="serve",
        compile_relevant=False,  # flows through the zoo's per-bucket keys
        doc=(
            "the serve zoo's (batch, seq) bucket grid (serve/zoo.py). The "
            "objective replays a seeded variable-height request stream "
            "(the same height distribution as loadgen.make_varlen_images) "
            "through the real SeqGrid bucketing arithmetic: padded slots "
            "over real slots across both grid dimensions, plus a small "
            "per-cell toll for the prewarm/residency cost of every extra "
            "compiled program (the ServeMemoryBudget pressure). Each grid "
            "cell compiles under its own zoo executable key, so this knob "
            "never touches the train-step cache key."),
    ),
    "prefetch_depth": TunableSpec(
        name="prefetch_depth",
        subsystem="input",
        candidates=(1, 2, 4, 8),
        default=2,  # cli/train.py --prefetch_depth default
        metric="input_ms_per_step",
        bench_stage="input",
        target="train_runtime",
        compile_relevant=False,
        deterministic=False,  # wall-clock feed timing; offline only
        doc=(
            "device-prefetch ring depth for the host input paths "
            "(data/prefetch.py). Runtime-only: the ring lives on the "
            "host side of the feed, the traced program is identical at "
            "every depth, so it is allowlisted out of the compile key "
            "(analysis/rules/cache_key.py TUNER_RUNTIME_ONLY)."),
    ),
    "snapshot_window": TunableSpec(
        name="snapshot_window",
        subsystem="checkpoint",
        candidates=(1, 2, 4, 8),
        default=1,  # cli/train.py --snapshot_window default
        metric="save_call_ms",
        bench_stage="ckpt",
        target="train_runtime",
        compile_relevant=False,
        deterministic=False,  # wall-clock save stalls; offline only
        doc=(
            "AsyncSnapshotter write-behind ring depth "
            "(checkpoint/snapshot.py). The objective times what the TRAIN "
            "LOOP sees — the caller-visible save() wall per call (fork + "
            "admission stall) over a burst of back-to-back snapshots "
            "against a real CheckpointManager: window 1 serializes on "
            "every in-flight save, deeper windows absorb bursts until "
            "disk bandwidth is the wall. Runtime-only: the ring is host-"
            "side write-behind plumbing, the traced step never sees it "
            "(analysis/rules/cache_key.py TUNER_RUNTIME_ONLY)."),
    ),
    "moe_capacity_factor": TunableSpec(
        name="moe_capacity_factor",
        subsystem="serve",
        candidates=(1.0, 1.25, 1.5, 2.0),
        default=1.25,  # models/vit.py MoE default; cli --moe_capacity_factor
        metric="moe_drop_cost",
        bench_stage="serve",
        target="serve",
        compile_relevant=False,  # serve-only: folded into the zoo engine's
        #                          per-cell executable keys, never the
        #                          train-step key
        doc=(
            "inference-time MoE expert capacity factor (serve/zoo.py "
            "capacity override; models/moe.py buffer sizing). The "
            "objective is a deterministic drop-fraction cost: seeded "
            "Dirichlet routing distributions -> multinomial expert "
            "loads, tokens over each expert's ceil(factor * tokens / "
            "experts) buffer are dropped, plus a compute toll "
            "proportional to (factor - 1) for the padded expert math a "
            "bigger buffer executes. Larger factors buy fewer drops "
            "with strictly more FLOPs — the knob picks the knee. The "
            "serve engine folds the live factor into every per-cell "
            "executable key (serve/engine.py _key/_store_key), so an "
            "applied winner can never collide with a stale executable."),
    ),
    "kv_page_tokens": TunableSpec(
        name="kv_page_tokens",
        subsystem="serve",
        candidates=(8, 16, 32, 64),
        default=16,  # models/causal_lm.py CausalLMTiny.kv_page_tokens
        metric="kv_page_cost",
        bench_stage="decode",
        target="serve",
        compile_relevant=False,  # decode-serving only: folded into every
        #                          per-cell decode executable key
        #                          (serve/decode.py _layout_key), never
        #                          the train-step key
        doc=(
            "paged-KV page size in tokens (models/causal_lm.py "
            "cache_layout='paged'; serve/decode.py page table). The "
            "objective is a deterministic page-economics cost over the "
            "seeded decode traffic distribution (serve/loadgen.py "
            "make_prompts lengths): mean fraction of pinned page tokens "
            "a request never fills (tail-page waste — small pages win) "
            "plus a per-table-entry toll for page-table width and the "
            "extra decode grid cells small pages compile (large pages "
            "win); the knee is the winner. Page size changes the traced "
            "decode program, and the live value is part of "
            "serve/decode.py's per-cell executable key — a tuner-applied "
            "change forces a fresh compile there, never in train."),
    ),
    "decode_admit_buckets": TunableSpec(
        name="decode_admit_buckets",
        subsystem="serve",
        candidates=("auto", "1,2,4,8", "1,4,8", "2,8", "8"),
        default="auto",  # serve/zoo.default_decode_grid pow2 ladder
        metric="decode_admit_cost",
        bench_stage="decode",
        target="serve",
        compile_relevant=False,  # each admit bucket is its own prefill
        #                          cell in the decode grid's executable
        #                          keys (serve/decode.py _key)
        doc=(
            "the decode grid's admit (prefill batch) buckets "
            "(serve/zoo.py DecodeGrid.admit_buckets), as a comma ladder "
            "or 'auto' (power-of-two up to max_slots). The objective "
            "replays a seeded admission-size stream (arrivals drawn "
            "against the make_prompts traffic shape) through the real "
            "DecodeGrid bucketing arithmetic and charges every padded "
            "prefill row, plus CELL_TOLL per extra (admit x prompt) "
            "grid cell for prewarm/residency. Admit buckets select "
            "WHICH prefill executable runs — each bucket compiles under "
            "its own cell key, so the train-step cache key is never "
            "involved."),
    ),
    "scan_chunk": TunableSpec(
        name="scan_chunk",
        subsystem="headline",
        candidates=(10, 100, 500),
        default=0,  # one program per step
        metric="steps_per_sec_per_chip",
        direction="higher_is_better",
        bench_stage="headline",
        target="train_runtime",
        compile_relevant=True,  # keyed via compile_cache_key_fields
        deterministic=False,
        auto_apply=False,
        doc=(
            "multi-step lax.scan chunking (the perf_sweep.py sweep, now "
            "a tune objective). Not auto-applied: a nonzero chunk "
            "requires --input_pipeline=device|device_sharded — flipping "
            "the input contract is an operator decision, not a store "
            "hit; the offline search reports the winner and the flag "
            "applies it."),
    ),
}


def knob_names() -> tuple:
    """All stored knob names across the catalog (flattened fields)."""
    out = []
    for spec in KNOBS.values():
        out.extend(spec.fields if spec.fields else (spec.name,))
    return tuple(out)
