"""Offline knob search: `python -m dist_mnist_tpu.tune`.

Runs successive halving over registered knobs and commits the winners
(with embedded evidence) to a TunedConfigStore, keyed to THIS process's
geometry — the config you pass, the mesh it builds, the backend and jax
version it runs under. Train/serve runs on the same geometry then pick
the winners up via `--tuned=auto`.

One JSON line per trial plus a final summary line, the
scripts/perf_sweep.py output discipline (that script is now a shim over
this module). Deterministic knobs run anywhere; timed knobs
(`prefetch_depth`, `scan_chunk`) meter wall-clock and belong on the
real chip.
"""

from __future__ import annotations

import argparse
import json
import sys

from dist_mnist_tpu.tune.spec import KNOBS


def _selected(spec_arg: str):
    if spec_arg == "all":
        return list(KNOBS)
    if spec_arg == "deterministic":
        return [n for n, s in KNOBS.items() if s.deterministic]
    names = [n.strip() for n in spec_arg.split(",") if n.strip()]
    unknown = [n for n in names if n not in KNOBS]
    if unknown:
        raise SystemExit(
            f"unknown knob(s) {unknown}; registered: {sorted(KNOBS)}")
    return names


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="successive-halving search over registered tunables")
    ap.add_argument("--knobs", default="deterministic",
                    help="comma list of knob names, or 'deterministic' "
                         "(default: the CI-safe subset) or 'all'")
    ap.add_argument("--store", default=None,
                    help="TunedConfigStore directory (default: "
                         "$DIST_MNIST_TPU_TUNED_DIR; omit both to search "
                         "without persisting)")
    ap.add_argument("--config", default="mlp_mnist",
                    help="config whose geometry keys the store entry")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--budget", type=int, default=32,
                    help="round-0 objective budget (stream length); "
                         "doubles every halving round")
    # perf_sweep.py compatibility surface (the timed scan/input legs)
    ap.add_argument("--steps", type=int, default=2000,
                    help="timed-knob step budget (scan_chunk / "
                         "prefetch_depth legs)")
    ap.add_argument("--batch", type=int, default=200,
                    help="global batch for the timed train legs")
    ap.add_argument("--model", default="lenet5",
                    help="model for the timed scan_chunk leg")
    ap.add_argument("--data-dir", default="/tmp/mnist-data")
    args = ap.parse_args(argv)

    from dist_mnist_tpu.cluster.mesh import make_mesh
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.tune.objectives import (
        TuneObjectiveUnavailable,
        build_objective,
    )
    from dist_mnist_tpu.tune.search import successive_halving
    from dist_mnist_tpu.tune.store import (
        TunedConfigStore,
        make_entry,
        tuning_key,
        _resolve_store_dir,
    )

    cfg = get_config(args.config)
    mesh = make_mesh(cfg.mesh)
    results = []
    for name in _selected(args.knobs):
        spec = KNOBS[name]
        base = (args.budget if spec.deterministic
                else max(10, args.steps // 4))
        try:
            objective = build_objective(
                name, mesh=mesh, model=args.model, batch=args.batch,
                data_dir=args.data_dir)
        except TuneObjectiveUnavailable as e:
            print(json.dumps({"knob": name, "skipped": str(e)}),
                  flush=True)
            continue
        res = successive_halving(spec, objective, seed=args.seed,
                                 base_budget=base)
        for t in res.trials:
            print(json.dumps({
                "knob": name, "candidate": t.candidate, "round": t.round,
                "budget": t.budget, spec.metric: round(t.score, 6),
                **t.extra}), flush=True)
        results.append(res)
        print(json.dumps({
            "knob": name, "winner": res.winner,
            spec.metric: round(res.winner_score, 6),
            "baseline": round(res.default_score, 6),
            "vs_default_ratio": round(res.vs_default_ratio, 6),
            "strictly_beats_default": res.strictly_beats_default,
        }), flush=True)

    summary = {"knobs_searched": [r.spec.name for r in results]}
    root = _resolve_store_dir(args.store)
    if root and results:
        store = TunedConfigStore(root)
        key = tuning_key(cfg, mesh)
        store.save(key, make_entry(cfg, mesh, results))
        summary.update(store=str(root), key=key,
                       store_stats=store.stats())
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
