"""Persistent autotuner: the bench loop closed into a knob-search engine.

- tune/spec.py — `TunableSpec` + the registered knob catalog (KNOBS)
- tune/search.py — seeded successive halving over a spec's ladder
- tune/objectives.py — bench-leg-backed objective functions
- tune/store.py — `TunedConfigStore`: winners + embedded evidence,
  keyed over the executable-cache geometry fields; `apply_tuned` is the
  `--tuned=auto|require` path in cli/train.py and cli/serve.py
- tune/cli.py — the offline search (`python -m dist_mnist_tpu.tune`,
  wrapped by cli/tune.py and scripts/perf_sweep.py)

See docs/TUNING.md for the knob catalog, store layout and key
semantics.
"""

from dist_mnist_tpu.tune.search import SearchResult, Trial, successive_halving
from dist_mnist_tpu.tune.spec import KNOBS, TunableSpec, knob_names
from dist_mnist_tpu.tune.store import (
    ENV_TUNED_DIR,
    TunedConfigMissError,
    TunedConfigStore,
    apply_tuned,
    make_entry,
    tuning_key,
)

__all__ = [
    "ENV_TUNED_DIR",
    "KNOBS",
    "SearchResult",
    "Trial",
    "TunableSpec",
    "TunedConfigMissError",
    "TunedConfigStore",
    "apply_tuned",
    "knob_names",
    "make_entry",
    "successive_halving",
    "tuning_key",
]
