"""Seeded successive halving over a TunableSpec's candidate ladder.

Round r scores every surviving candidate with an objective budget of
``base_budget * 2**r`` (stream length / timed steps — whatever the
objective meters), keeps the better half, and repeats until one
survivor remains. The trial runner is deliberately SYNCHRONOUS: trials
share the process's devices, so parallel trials would contend for them
and perturb each other's measurements — and a single-threaded engine is
trivially deterministic across invocations, which the store's evidence
claims depend on (tests pin a two-invocation replay).

Every round re-derives its stream seed as ``seed + round``, so the
final round's survivors — and the stock default, which is ALWAYS
re-scored at the final round's (budget, seed) even if halving
eliminated it earlier — are compared on the same stream. That final
same-stream pair is the "winner strictly beats default" evidence
`bench.py --tune` asserts and the store embeds.

Objectives are callables ``objective(candidate, *, budget, seed) ->
(score, extra_dict)`` — see tune/objectives.py for the bench-leg-backed
ones.

Journal events (all no-ops without an installed journal):
``tuning/search_start``, one ``tuning/trial`` per scored candidate,
``tuning/winner`` at the end.
"""

from __future__ import annotations

import dataclasses
import time

from dist_mnist_tpu.obs import events
from dist_mnist_tpu.tune.spec import TunableSpec

__all__ = ["Trial", "SearchResult", "successive_halving"]


@dataclasses.dataclass(frozen=True)
class Trial:
    """One scored (candidate, budget) leg."""

    candidate: object
    round: int
    budget: int
    score: float
    extra: dict


@dataclasses.dataclass(frozen=True)
class SearchResult:
    spec: TunableSpec
    winner: object
    winner_score: float
    default_score: float
    final_budget: int
    final_seed: int
    rounds: int
    seed: int
    trials: tuple

    @property
    def strictly_beats_default(self) -> bool:
        return self.spec.better(self.winner_score, self.default_score)

    @property
    def vs_default_ratio(self) -> float:
        """winner/default for lower_is_better metrics (inverted
        otherwise): < 1.0 always means the tuned value wins."""
        if self.default_score == 0:
            return 1.0
        r = self.winner_score / self.default_score
        return r if self.spec.direction == "lower_is_better" else 1.0 / r

    def evidence(self) -> dict:
        """The embedded-evidence dict the TunedConfigStore persists and
        `tuning/applied` replays (metric, value, baseline, bench stage,
        timestamp — the acceptance-criteria fields)."""
        return {
            "metric": self.spec.metric,
            "direction": self.spec.direction,
            "value": self.winner_score,
            "baseline": self.default_score,
            "default": self.spec.default,
            "bench_stage": self.spec.bench_stage,
            "budget": self.final_budget,
            "stream_seed": self.final_seed,
            "rounds": self.rounds,
            "trials": len(self.trials),
            "seed": self.seed,
            "measured_at": time.time(),
        }


def successive_halving(spec: TunableSpec, objective, *, seed: int = 0,
                       base_budget: int = 32) -> SearchResult:
    """Run the search; see the module docstring for the protocol."""
    survivors = list(spec.candidates)
    if not survivors:
        raise ValueError(f"{spec.name}: empty candidate ladder")
    events.emit("tuning/search_start", knob=spec.name,
                candidates=len(survivors), metric=spec.metric,
                direction=spec.direction, seed=seed,
                base_budget=base_budget)
    trials: list[Trial] = []
    rnd, budget, round_seed = 0, base_budget, seed
    last_scores: dict = {}
    while True:
        budget = base_budget * (2 ** rnd)
        round_seed = seed + rnd
        last_scores = {}
        for cand in survivors:
            score, extra = objective(cand, budget=budget, seed=round_seed)
            # lint: ok[host-sync] objective already stop-clocked/fetched; this is host-side score normalization
            score = float(score)
            last_scores[cand] = score
            trials.append(Trial(cand, rnd, budget, score, extra))
            events.emit("tuning/trial", knob=spec.name, candidate=cand,
                        round=rnd, budget=budget, metric=spec.metric,
                        score=round(score, 6))
        if len(survivors) == 1:
            break
        # stable sort: ties resolve by ladder order, deterministically
        survivors.sort(
            key=lambda c: (last_scores[c]
                           if spec.direction == "lower_is_better"
                           else -last_scores[c]))
        survivors = survivors[:-(-len(survivors) // 2) or 1]
        rnd += 1
    winner = survivors[0]
    winner_score = last_scores[winner]
    # baseline leg: the stock default at the final (budget, seed) — the
    # same stream the winner's final score came from
    if winner == spec.default:
        default_score = winner_score
    else:
        default_score, _ = objective(spec.default, budget=budget,
                                     seed=round_seed)
        # lint: ok[host-sync] same: host-side normalization of an already-fetched score
        default_score = float(default_score)
        trials.append(Trial(spec.default, rnd, budget, default_score,
                            {"baseline_leg": True}))
    res = SearchResult(
        spec=spec, winner=winner, winner_score=winner_score,
        default_score=default_score, final_budget=budget,
        final_seed=round_seed, rounds=rnd + 1, seed=seed,
        trials=tuple(trials))
    events.emit("tuning/winner", knob=spec.name, winner=winner,
                metric=spec.metric, score=round(winner_score, 6),
                baseline=round(default_score, 6),
                vs_default_ratio=round(res.vs_default_ratio, 6),
                strictly_beats_default=res.strictly_beats_default)
    return res
