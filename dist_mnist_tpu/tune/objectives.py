"""Objectives: the bench legs, importable, one per registered knob.

Each builder does the expensive one-time construction (mesh, model,
sharded state) and returns a closure ``objective(candidate, *, budget,
seed) -> (score, extra)`` for tune/search.py. They reuse the same
machinery as the corresponding bench.py stage — `plan_stats` and the
fsdp mlp leg from `--overlap`, the SeqGrid bucketing arithmetic and the
`make_varlen_images` height distribution from `--serve`/`--longctx`,
the `timed_chunks` stop-clock and scan legs from `--input` and
scripts/perf_sweep.py — as in-process functions, not subprocesses.

Two classes of objective, flagged on the spec:

- deterministic (`overlap_bucket_mb`, `serve_grid`): pure functions of
  (candidate, budget, seed) — structural plan metadata and seeded
  bucketing arithmetic. These run in CI, in `bench.py --tune`, and on
  the CPU mesh, where wall-clock cannot resolve schedule differences
  (XLA-CPU runs collectives inline) but the structure it would produce
  is exactly measurable.
- timed (`prefetch_depth`, `scan_chunk`): device_get stop-clock legs
  for the offline `cli/tune.py` run on real hardware.
"""

from __future__ import annotations

import numpy as np

#: byte-equivalent toll per gather launch: one more bucket costs the
#: schedule roughly this much head/tail latency (the classic gradient-
#: bucketing trade; docs/TUNING.md "Cost models")
LAUNCH_TOLL_MB = 0.25

#: per grid-cell toll for the serve objective: every (batch, seq) cell
#: is one more compiled program to prewarm and keep resident against
#: the serve memory budget (serve/engine.py prewarm / ServeMemoryBudget)
CELL_TOLL = 0.02

#: per page-table-entry toll for the kv_page_tokens objective: every
#: extra page per slot is one more int32 of table the decode step
#: indirects through and (int8) one more decode grid cell to prewarm —
#: the pressure that stops the search from always picking tiny pages
PAGE_TOLL = 0.01


class TuneObjectiveUnavailable(RuntimeError):
    """This geometry cannot measure the knob (e.g. 1 chip: no fsdp
    communication exists, there is nothing to bucket)."""


# -------------------------------------------------------- overlap_bucket_mb

def overlap_cost_objective(mesh=None, *, data_dir: str = "/tmp/mnist-data"):
    """Objective for `overlap_bucket_mb`: the byte-denominated schedule
    cost of the REAL gather plan (parallel/overlap.plan_stats) for the
    same fsdp mlp leg `bench.py --overlap` times — mean bucket size (the
    head-of-line gather that cannot hide behind compute) plus a fixed
    per-launch toll per bucket. Deterministic: plan metadata, no clock.
    `budget`/`seed` are accepted for protocol parity and recorded."""
    import jax

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import load_dataset
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.overlap import OverlapConfig, plan_stats
    from dist_mnist_tpu.parallel.sharding import (
        FSDP_RULES,
        shard_train_state,
    )
    from dist_mnist_tpu.train import create_train_state

    mesh = mesh if mesh is not None else make_mesh(MeshSpec(data=-1))
    n_chips = int(mesh.devices.size)
    if n_chips < 2:
        raise TuneObjectiveUnavailable(
            "overlap_bucket_mb needs >= 2 chips: a 1-chip mesh has no "
            "fsdp communication to bucket (same caveat as bench "
            "--overlap's single_chip report)")
    dataset = load_dataset("mnist", data_dir, seed=0)
    hidden = max(64, 64 * n_chips)  # the bench --overlap leg's sizing
    with activate(mesh):
        model = get_model("mlp", hidden_units=hidden)
        state = create_train_state(model, optim.adam(1e-3),
                                   jax.random.PRNGKey(0),
                                   dataset.train_images[:1])
        state = shard_train_state(state, mesh, FSDP_RULES)
    params = state.params

    def objective(candidate, *, budget: int, seed: int):
        bucket_mb = float(candidate)  # lint: ok[host-sync] host-side candidate arithmetic, no device value involved
        stats = plan_stats(params, mesh, FSDP_RULES,
                           OverlapConfig(bucket_mb=bucket_mb))
        n_buckets = int(stats["buckets"])
        gathered_mb = stats["gathered_bytes"] / 2**20
        head_mb = gathered_mb / max(1, n_buckets)
        score = head_mb + LAUNCH_TOLL_MB * n_buckets
        return score, {
            "n_buckets": n_buckets,
            "gathered_mbytes": round(gathered_mb, 3),
            "head_mbytes": round(head_mb, 3),
            "launch_toll_mb": LAUNCH_TOLL_MB,
            "chips": n_chips,
            "hidden_units": hidden,
            "budget": budget,
            "seed": seed,
        }

    return objective


# --------------------------------------------------------------- serve_grid

def serve_grid_objective(image_shape=(28, 28, 1), patch: int = 4):
    """Objective for `serve_grid` (max_batch, seq_buckets spec): replay
    a seeded variable-height request stream through the real SeqGrid
    bucketing arithmetic (serve/zoo.py) and charge every padded token
    slot. The stream uses the SAME height distribution as the longctx
    loadgen (`make_varlen_images`: patch-multiple heights uniform in
    [patch, native]) and a seeded dispatch-size stream for the batch
    dimension. Score = token-pad ratio x batch-slot-pad ratio + a
    per-grid-cell toll (prewarm/residency). Pure arithmetic on the
    seeded stream: deterministic on every backend."""
    from dist_mnist_tpu.serve.zoo import parse_seq_buckets

    native_h = int(image_shape[0])

    def objective(candidate, *, budget: int, seed: int):
        max_batch, spec = int(candidate[0]), str(candidate[1])
        grid = parse_seq_buckets(spec, image_shape, patch)
        rng = np.random.default_rng(seed)
        # heights: make_varlen_images' distribution, arrival sizes: up
        # to 1.5x the stock window so every max_batch has to split some
        ks = rng.integers(1, native_h // patch + 1, size=budget)
        arrivals = rng.integers(1, 97, size=budget)
        if grid is not None:
            real_tok = sum(grid.n_tokens(int(k) * patch) for k in ks)
            pad_tok = sum(grid.n_tokens(grid.bucket_for(int(k) * patch))
                          for k in ks)
            n_heights = len(grid.heights)
        else:  # native-only: every request pays the full image
            per = (native_h // patch) * (image_shape[1] // patch)
            real_tok = sum(int(k) * (image_shape[1] // patch) for k in ks)
            pad_tok = per * len(ks)
            n_heights = 1
        real_slots, pad_slots = 0, 0
        for g in arrivals:
            g = int(g)
            real_slots += g
            full, rem = divmod(g, max_batch)
            pad_slots += full * max_batch
            if rem:
                pad_slots += 1 << (rem - 1).bit_length()
        n_batch_buckets = max_batch.bit_length()  # 1,2,4,...,max_batch
        n_cells = n_batch_buckets * n_heights
        tok_ratio = pad_tok / real_tok
        slot_ratio = pad_slots / real_slots
        score = tok_ratio * slot_ratio + CELL_TOLL * n_cells
        return score, {
            "token_pad_ratio": round(tok_ratio, 4),
            "batch_slot_pad_ratio": round(slot_ratio, 4),
            "grid_cells": n_cells,
            "cell_toll": CELL_TOLL,
            "requests": budget,
            "seed": seed,
        }

    return objective


# ------------------------------------------------------ moe_capacity_factor

#: FLOP toll per unit of extra capacity factor: a bigger expert buffer
#: executes proportionally more padded expert math whether or not the
#: slots are filled (models/moe.py fixed-shape dispatch)
CAPACITY_TOLL = 0.05


def moe_capacity_objective(*, n_experts: int = 8, tokens: int = 256,
                           alpha: float = 0.3):
    """Objective for `moe_capacity_factor`: the deterministic
    drop-fraction cost of a capacity factor under skewed routing. Each
    trial draws seeded Dirichlet(alpha) routing distributions (alpha < 1:
    the hot-expert skew that makes capacity a real trade), multinomial
    token loads per expert, and drops every token over the
    ceil(factor * tokens / n_experts) buffer — exactly the fixed-shape
    dispatch models/moe.py executes. Score = mean drop fraction +
    CAPACITY_TOLL * (factor - 1): more capacity buys fewer drops with
    strictly more padded expert FLOPs, and the knee is the winner. Pure
    seeded arithmetic: deterministic on every backend."""
    import math

    def objective(candidate, *, budget: int, seed: int):
        factor = float(candidate)  # lint: ok[host-sync] host-side candidate arithmetic, no device value involved
        rng = np.random.default_rng(seed)
        capacity = math.ceil(factor * tokens / n_experts)
        dropped = 0
        for _ in range(budget):
            probs = rng.dirichlet(np.full(n_experts, alpha))
            loads = rng.multinomial(tokens, probs)
            dropped += int(np.maximum(loads - capacity, 0).sum())
        drop_fraction = dropped / (budget * tokens)
        score = drop_fraction + CAPACITY_TOLL * (factor - 1.0)
        return score, {
            "drop_fraction": round(drop_fraction, 4),
            "capacity_per_expert": capacity,
            "capacity_toll": CAPACITY_TOLL,
            "n_experts": n_experts,
            "tokens": tokens,
            "routing_alpha": alpha,
            "batches": budget,
            "seed": seed,
        }

    return objective


# ------------------------------------------------------------ kv_page_tokens

def kv_page_objective(*, max_seq: int = 64):
    """Objective for `kv_page_tokens`: deterministic page economics over
    the seeded decode traffic shape. Each trial draws request lengths
    with `serve/loadgen.make_prompts` (the SAME distribution the decode
    bench replays), pins ``ceil((prompt + max_new) / T)`` pages per
    request — exactly what `serve/decode.DecodeEngine.try_reserve` does —
    and charges (a) the fraction of pinned page tokens the request never
    fills (tail-page waste) and (b) PAGE_TOLL per page of table width
    (`pages_per_slot`), the indirection + extra-grid-cell pressure.
    Small pages waste nothing but widen every table; big pages pin
    near-dense stripes. Pure seeded arithmetic on every backend."""
    from dist_mnist_tpu.serve.loadgen import make_prompts

    def objective(candidate, *, budget: int, seed: int):
        t = int(candidate)  # lint: ok[host-sync] host-side candidate arithmetic, no device value involved
        if t < 1 or max_seq % t:
            raise TuneObjectiveUnavailable(
                f"kv_page_tokens={t} must divide max_seq={max_seq} "
                "(models/causal_lm.py paged-layout contract)")
        reqs = make_prompts(max(1, budget) * 32, max_seq=max_seq,
                            seed=seed)
        totals = np.array([p.size + n for p, n in reqs], dtype=np.int64)
        pages = -(-totals // t)
        waste = (pages * t - totals) / (pages * t)
        pages_per_slot = max_seq // t
        score = float(waste.mean()) + PAGE_TOLL * pages_per_slot  # lint: ok[host-sync] seeded numpy cost model, no device values
        return score, {
            "page_tokens": t,
            "pages_per_slot": pages_per_slot,
            "mean_tail_waste": round(float(waste.mean()), 4),  # lint: ok[host-sync] seeded numpy cost model, no device values
            "mean_pages_pinned": round(float(pages.mean()), 3),  # lint: ok[host-sync] seeded numpy cost model, no device values
            "page_toll": PAGE_TOLL,
            "max_seq": max_seq,
            "requests": len(reqs),
            "budget": budget,
            "seed": seed,
        }

    return objective


# ------------------------------------------------------ decode_admit_buckets

def decode_admit_objective(*, max_slots: int = 8, max_seq: int = 64):
    """Objective for `decode_admit_buckets`: replay a seeded admission-
    size stream through the real `serve/zoo.DecodeGrid` bucketing
    arithmetic and charge every padded prefill row (a padded row runs
    the full prompt-bucket forward into the scratch slot for nothing),
    plus CELL_TOLL per (admit x prompt) grid cell — every admit bucket
    multiplies the prefill programs to prewarm and keep resident. The
    admission sizes mirror what continuous batching hands `prefill`:
    bursts capped by free slots, drawn seeded per trial."""
    from dist_mnist_tpu.serve.zoo import DecodeGrid

    def parse(spec_str: str) -> tuple:
        if spec_str == "auto":
            out, a = [], 1
            while a < max_slots:
                out.append(a)
                a *= 2
            out.append(max_slots)
            return tuple(out)
        return tuple(int(b) for b in spec_str.split(","))

    def objective(candidate, *, budget: int, seed: int):
        buckets = parse(str(candidate))  # lint: ok[host-sync] host-side candidate arithmetic, no device value involved
        if not buckets or buckets[-1] != max_slots:
            raise TuneObjectiveUnavailable(
                f"admit buckets {buckets} must end at max_slots="
                f"{max_slots} or full admissions cannot land")
        grid = DecodeGrid(max_slots=max_slots, max_seq=max_seq,
                          prompt_buckets=(max_seq,),
                          admit_buckets=buckets)
        rng = np.random.default_rng(seed)
        sizes = rng.integers(1, max_slots + 1,
                             size=max(1, budget) * 64)
        padded = sum(grid.admit_bucket_for(int(m)) - int(m)
                     for m in sizes)
        pad_ratio = padded / (padded + int(sizes.sum()))
        n_cells = len(buckets) * len(grid.prompt_buckets)
        score = pad_ratio + CELL_TOLL * n_cells
        return score, {
            "admit_buckets": list(buckets),
            "padded_rows": int(padded),
            "real_rows": int(sizes.sum()),
            "pad_ratio": round(float(pad_ratio), 4),  # lint: ok[host-sync] seeded numpy cost model, no device values
            "cell_toll": CELL_TOLL,
            "prefill_cells": n_cells,
            "admissions": int(sizes.size),
            "budget": budget,
            "seed": seed,
        }

    return objective


# ----------------------------------------------------- timed, offline-only

def input_feed_objective(mesh=None, *, batch: int = 512,
                         data_dir: str = "/tmp/mnist-data"):
    """Objective for `prefetch_depth` (timed; offline): ms/step of the
    real train step fed through a DevicePrefetcher ring at the candidate
    depth — the `bench.py --input` question, asked per depth."""
    import jax

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import ShardedBatcher, load_dataset
    from dist_mnist_tpu.data.prefetch import DevicePrefetcher
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, make_train_step
    from dist_mnist_tpu.utils.timing import timed_chunks

    mesh = mesh if mesh is not None else make_mesh(MeshSpec(data=-1))
    n_chips = int(mesh.devices.size)
    dataset = load_dataset("mnist", data_dir, seed=0)
    optimizer = optim.adam(1e-3)
    with activate(mesh):
        model = get_model("mlp")
        step = make_train_step(model, optimizer, mesh)

    def fresh_state():
        # the jitted step donates its state argument, so every trial must
        # start from freshly materialized buffers, never a shared state0
        state = create_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   dataset.train_images[:1])
        return shard_train_state(state, mesh)

    def objective(candidate, *, budget: int, seed: int):
        depth = int(candidate)
        with activate(mesh):
            batcher = ShardedBatcher(dataset, batch, mesh, seed=seed)
            feed = DevicePrefetcher(batcher, depth=depth) if depth \
                else batcher
            it = iter(feed)
            try:
                dt, _, loss = timed_chunks(
                    lambda s: step(s, next(it)), fresh_state(), budget)
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()  # drain + join the prefetch worker
        ms = dt / budget * 1e3
        return ms, {"final_loss": round(loss, 4), "depth": depth,
                    "timed_steps": budget, "chips": n_chips}

    return objective


def scan_chunk_objective(mesh=None, *, model_name: str = "lenet5",
                         batch: int = 200,
                         data_dir: str = "/tmp/mnist-data"):
    """Objective for `scan_chunk` (timed; offline): steps/sec/chip of
    the compiled multi-step scan at the candidate chunk size, candidate
    0 = the per-step host-feed path — the scripts/perf_sweep.py sweep
    body, lifted here so the script could become a shim."""
    import jax

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.cluster.mesh import MeshSpec, activate, make_mesh
    from dist_mnist_tpu.data import (
        DeviceDataset,
        ShardedBatcher,
        load_dataset,
    )
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state, make_train_step
    from dist_mnist_tpu.train.step import make_scanned_train_fn
    from dist_mnist_tpu.utils.timing import timed_chunks

    mesh = mesh if mesh is not None else make_mesh(MeshSpec(data=-1))
    n_chips = int(mesh.devices.size)
    dataset = load_dataset("mnist", data_dir, seed=0)
    optimizer = optim.adam(1e-3)
    with activate(mesh):
        model = get_model(model_name)
        dd = DeviceDataset(dataset, mesh)

    def fresh_state():
        state = create_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   dataset.train_images[:1])
        return shard_train_state(state, mesh)

    def objective(candidate, *, budget: int, seed: int):
        chunk = int(candidate)
        with activate(mesh):
            if chunk:
                run = make_scanned_train_fn(model, optimizer, mesh, dd,
                                            batch, chunk)
                n_chunks = max(1, budget // chunk)
                dt, _, loss = timed_chunks(run, fresh_state(), n_chunks)
                steps = n_chunks * chunk
            else:
                step = make_train_step(model, optimizer, mesh)
                it = iter(ShardedBatcher(dataset, batch, mesh, seed=seed))
                dt, _, loss = timed_chunks(
                    lambda s: step(s, next(it)), fresh_state(), budget)
                steps = budget
        return steps / dt / n_chips, {
            "final_loss": round(loss, 4), "scan_chunk": chunk,
            "timed_steps": steps, "chips": n_chips}

    return objective


def snapshot_window_objective(*, ckpt_dir: str | None = None):
    """Objective for `snapshot_window` (timed; offline): the mean
    caller-visible `save()` wall (ms) of a burst of back-to-back
    snapshots through an AsyncSnapshotter at the candidate window depth,
    against a real CheckpointManager — exactly the fork + admission
    stall the train loop pays (checkpoint/snapshot.py save_stall_s
    attribution, asked per window)."""
    import dataclasses
    import shutil
    import tempfile
    import time

    import jax
    import jax.numpy as jnp

    from dist_mnist_tpu import optim
    from dist_mnist_tpu.checkpoint.manager import CheckpointManager
    from dist_mnist_tpu.checkpoint.snapshot import AsyncSnapshotter
    from dist_mnist_tpu.models import get_model
    from dist_mnist_tpu.train import create_train_state

    model = get_model("mlp")
    state0 = create_train_state(model, optim.adam(1e-3),
                                jax.random.PRNGKey(0),
                                jnp.zeros((1, 28, 28, 1), jnp.float32))

    def objective(candidate, *, budget: int, seed: int):
        window = int(candidate)
        tmp = ckpt_dir or tempfile.mkdtemp(prefix="tune_snapwin_")
        mgr = CheckpointManager(tmp, async_save=False, max_to_keep=2)
        snap = AsyncSnapshotter(mgr, window=window)
        try:
            walls = []
            for i in range(budget):
                state = dataclasses.replace(
                    state0, step=jnp.asarray(seed * 10_000 + i, jnp.int32))
                t0 = time.perf_counter()
                snap.save(state)
                walls.append((time.perf_counter() - t0) * 1e3)
            snap.wait()
        finally:
            snap.close()
            mgr.close()
            if ckpt_dir is None:
                shutil.rmtree(tmp, ignore_errors=True)
        ms = sum(walls) / max(len(walls), 1)
        return ms, {
            "window": window,
            "saves": budget,
            "save_stall_s": round(snap.save_stall_s, 4),
            "dropped": snap.dropped,
            "max_save_call_ms": round(max(walls, default=0.0), 3),
            "seed": seed,
        }

    return objective


def build_objective(name: str, *, mesh=None, model: str = "lenet5",
                    batch: int = 200, data_dir: str = "/tmp/mnist-data"):
    """Objective factory by knob name (the cli/tune.py dispatch)."""
    if name == "overlap_bucket_mb":
        return overlap_cost_objective(mesh, data_dir=data_dir)
    if name == "serve_grid":
        return serve_grid_objective()
    if name == "moe_capacity_factor":
        return moe_capacity_objective()
    if name == "kv_page_tokens":
        return kv_page_objective()
    if name == "decode_admit_buckets":
        return decode_admit_objective()
    if name == "snapshot_window":
        return snapshot_window_objective()
    if name == "prefetch_depth":
        return input_feed_objective(mesh, data_dir=data_dir)
    if name == "scan_chunk":
        return scan_chunk_objective(mesh, model_name=model, batch=batch,
                                    data_dir=data_dir)
    raise KeyError(f"no objective registered for knob {name!r}")
