"""`python -m dist_mnist_tpu.tune` — see tune/cli.py."""

import sys

from dist_mnist_tpu.tune.cli import main

sys.exit(main())
