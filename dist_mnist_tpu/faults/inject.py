"""Injector shims: where a `FaultPlan`'s faults actually land.

Each shim wraps one seam of the real system and injects ITS fault kinds,
delegating everything else untouched — the wrapped object's contract
(at_step/close on iterators, save/restore on the checkpoint manager,
predict on the serve engine) is preserved so the shims compose with the
production wiring (prefetcher above or below the stall shim, CheckpointHook
holding the wrapped manager, DynamicBatcher holding the wrapped engine).

Injection points, chosen so each fault exercises the REAL recovery path:

- preempt: raised from `FaultInjectionHook.before_step`, which the loop
  calls inside its recovery try-block with the loop's own host step —
  the one clock that stays correct across restores (a wrapped step_fn's
  call counter runs ahead of the global step during replay; see
  `FaultyStepFn`'s caveat).
- corrupt_checkpoint: applied to the on-disk step directory at RESTORE
  time, after `wait()` — deterministic under async save, and it hits the
  exact read path `CheckpointManager`'s fallback ladder defends.
- stall_input: a sleep in the batch feed, visible to the loop as feed
  wait (goodput stall bucket) like any real input outage.
- serve_error: raised from `predict()` under the DynamicBatcher, which
  must fail ONLY that batch's futures and keep serving.
"""

from __future__ import annotations

import logging
import time
from pathlib import Path

from dist_mnist_tpu.faults.plan import FaultPlan
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.train.loop import PreemptionError

log = logging.getLogger(__name__)


class FaultInjectionHook:
    """Raises planned preemptions at the loop's step clock.

    `before_step(step)` runs inside TrainLoop's try-block, so the raise
    takes the production recovery path: classify via `_is_preemption`,
    restore the latest checkpoint, re-seek the input stream, replay."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def begin(self, loop) -> None:
        pass

    def before_step(self, step: int) -> None:
        # >= not ==: a chunked loop (steps_per_call > 1) can cross the
        # trigger without landing on it; `fired` keeps it at-most-once,
        # so replayed steps below the trigger never re-raise
        for f in self.plan.pending("preempt"):
            if f.step is not None and step >= f.step:
                f.fired = True
                log.warning("fault injected: preemption at step %d", step)
                events.emit("fault_injected", kind="preempt", step=step)
                raise PreemptionError(f"injected preemption at step {step}")
        self._maybe_kill_host(step)

    def _maybe_kill_host(self, step: int) -> None:
        # kill_host: the VICTIM SIGKILLs itself at an exact step —
        # deterministic against import/compile wall-time variance, unlike
        # the launcher's after_s kill timer. Fires in generation 0 only:
        # restart/resized generations re-parse the plan JSON with fresh
        # `fired` latches, and a restored worker replaying past the
        # trigger step must not die again (the loss already happened; the
        # elastic supervisor tracks it via membership, not re-injection).
        for f in self.plan.pending("kill_host"):
            if f.step is None or step < f.step:
                continue
            import os

            if int(os.environ.get(events.ENV_GENERATION, "0") or 0) != 0:
                f.fired = True
                continue
            import jax

            if jax.process_index() != (f.process or 0):
                continue
            f.fired = True
            log.warning(
                "fault injected: kill_host p%d (SIGKILL self) at step %d",
                f.process or 0, step,
            )
            events.emit("fault_injected", kind="kill_host", step=step,
                        process=f.process or 0)
            import signal

            os.kill(os.getpid(), signal.SIGKILL)

    def after_step(self, step: int, state, outputs) -> None:
        pass

    def end(self, state) -> None:
        pass


class FaultyBatches:
    """Batch-stream wrapper injecting input stalls.

    Mirrors the stream contract the loop relies on — `at_step` re-seek
    (preserving this wrapper and its plan across recoveries) and
    generator `close()` propagation — so it can sit above ShardedBatcher,
    NativeBatcher, or DevicePrefetcher."""

    def __init__(self, inner, plan: FaultPlan, *, start_step: int = 0):
        self._inner = inner
        self._plan = plan
        self._start = start_step

    def at_step(self, step: int) -> "FaultyBatches":
        inner = (self._inner.at_step(step)
                 if hasattr(self._inner, "at_step") else self._inner)
        return FaultyBatches(inner, self._plan, start_step=step)

    def __iter__(self):
        it = iter(self._inner)
        step = self._start
        try:
            while True:
                for f in self._plan.pending("stall_input"):
                    if f.step is not None and step >= f.step:
                        f.fired = True
                        log.warning(
                            "fault injected: input stall %.2fs at step %d",
                            f.seconds or 0.0, step,
                        )
                        events.emit("fault_injected", kind="stall_input",
                                    step=step, seconds=f.seconds or 0.0)
                        time.sleep(f.seconds or 0.0)
                try:
                    batch = next(it)
                except StopIteration:
                    return
                yield batch
                step += 1
        finally:
            if hasattr(it, "close"):
                it.close()  # drain a prefetch worker promptly

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _corrupt_step_dir(step_dir: Path, mode: str = "truncate") -> Path | None:
    """Damage the step's LARGEST file (the array payload, not metadata) —
    the realistic partial-write/short-read failure a preempted writer or
    a bad disk produces. Returns the damaged path (None if nothing to
    damage)."""
    files = sorted(
        (p for p in step_dir.rglob("*") if p.is_file()),
        key=lambda p: (p.stat().st_size, str(p)),
        reverse=True,
    )
    if not files:
        return None
    target = files[0]
    if mode == "delete":
        target.unlink()
    else:
        with open(target, "r+b") as fh:
            fh.truncate(max(1, target.stat().st_size // 2))
    return target


class FaultyCheckpointManager:
    """Checkpoint-manager wrapper corrupting planned steps on disk.

    Corruption happens at RESTORE time (after `wait()`, so async writes
    have landed) rather than at save time — deterministic regardless of
    save timing, and it exercises exactly the unreadable-latest path that
    `CheckpointManager.restore`'s fallback ladder defends."""

    def __init__(self, inner, plan: FaultPlan):
        self._inner = inner
        self._plan = plan

    def restore(self, target_state):
        for f in self._plan.pending("corrupt_checkpoint"):
            if f.step is None:
                continue
            step_dir = Path(self._inner.directory) / str(f.step)
            if not step_dir.exists():
                continue  # not on disk yet; stays pending for a later restore
            self._inner.wait()
            damaged = _corrupt_step_dir(step_dir, mode=f.mode)
            f.fired = True
            log.warning(
                "fault injected: %s checkpoint step %d (%s)",
                f.mode, f.step, damaged,
            )
            events.emit("fault_injected", kind="corrupt_checkpoint",
                        step=f.step, mode=f.mode)
        return self._inner.restore(target_state)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyEngine:
    """Serve-engine wrapper raising on a planned predict-call ordinal.

    Three kinds, three failure shapes the layers above must absorb:

    - ``serve_error``: one transient raise — the DynamicBatcher must fail
      only that batch's futures and keep serving (serve/batcher.py), and
      a router classifies it RETRYABLE (serve/errors.py).
    - ``serve_replica_kill`` (scoped by ``replica_id``): the engine goes
      PERMANENTLY dead — the fired call and every call after it raise
      ReplicaKilledError, like a device loss under a live server. The
      batcher keeps failing batches; only a router failing over (and a
      `restart()` building a FRESH engine) recovers.
    - ``serve_replica_stall`` (scoped): one sleep inside predict — a
      straggler that stretches a whole batch's latency, which is what a
      router's hedged requests exist to cut off.

    Ordinals count THIS engine's predict calls (each replica has its own
    clock), so one shared plan targets replicas independently.
    """

    def __init__(self, inner, plan: FaultPlan, *, replica_id: int | None = None):
        self._inner = inner
        self._plan = plan
        self._replica_id = replica_id
        self._calls = 0
        self._dead = False

    def _mine(self, kind: str):
        return [f for f in self._plan.pending(kind)
                if f.replica is None or f.replica == self._replica_id]

    def predict(self, *args, **kwargs):
        from dist_mnist_tpu.serve.errors import ReplicaKilledError

        if self._dead:
            raise ReplicaKilledError(
                f"replica {self._replica_id}: engine is dead (injected kill)"
            )
        call = self._calls
        self._calls += 1
        for f in self._mine("serve_replica_kill"):
            if f.request is not None and call >= f.request:
                f.fired = True
                self._dead = True
                log.warning(
                    "fault injected: replica %s killed on predict call %d",
                    self._replica_id, call,
                )
                events.emit("fault_injected", kind="serve_replica_kill",
                            replica=self._replica_id, call=call)
                raise ReplicaKilledError(
                    f"replica {self._replica_id}: injected kill on predict "
                    f"call {call}"
                )
        for f in self._mine("serve_replica_stall"):
            if f.request is not None and call >= f.request:
                f.fired = True
                log.warning(
                    "fault injected: replica %s stalls %.2fs on predict "
                    "call %d", self._replica_id, f.seconds or 0.0, call,
                )
                events.emit("fault_injected", kind="serve_replica_stall",
                            replica=self._replica_id, call=call,
                            seconds=f.seconds or 0.0)
                time.sleep(f.seconds or 0.0)
        for f in self._plan.pending("serve_error"):
            if f.request is not None and call >= f.request:
                f.fired = True
                log.warning(
                    "fault injected: serve engine error on predict call %d",
                    call,
                )
                events.emit("fault_injected", kind="serve_error", call=call)
                raise RuntimeError(
                    f"injected serve engine error on predict call {call}"
                )
        return self._inner.predict(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyStepFn:
    """Standalone step_fn wrapper raising planned preemptions by CALL count.

    Caveat, and why the loop path uses `FaultInjectionHook` instead: this
    clock counts calls from `initial_step`, so after an in-loop restore the
    replayed steps advance it PAST the global step — fine for driving a
    bare step_fn (unit tests, harnesses without hooks), wrong as the
    trigger clock inside a recovering TrainLoop."""

    def __init__(self, step_fn, plan: FaultPlan, *, initial_step: int = 0):
        self._fn = step_fn
        self._plan = plan
        self._step = initial_step

    def __call__(self, state, batch):
        step = self._step
        for f in self._plan.pending("preempt"):
            if f.step is not None and step >= f.step:
                f.fired = True
                raise PreemptionError(
                    f"injected preemption at step call {step}"
                )
        out = self._fn(state, batch)
        self._step += 1
        return out
