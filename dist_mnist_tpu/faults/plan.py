"""Fault plans: a deterministic, serializable schedule of injected faults.

The reference validated its preemption ring by hand-injecting
AbortedError into `_RecoverableSession` in unit tests (SURVEY.md §4);
nothing exercised the launch or checkpoint layers. A `FaultPlan` makes
every recovery path in this repo reachable on purpose, from a test, a
bench run, or the CLI (``--fault_plan``), with no real hardware fault:

=================== ========================== ==========================
kind                trigger                    consumed by
=================== ========================== ==========================
preempt             loop step >= ``step``      FaultInjectionHook (raises
                                               PreemptionError inside the
                                               loop's recovery try)
corrupt_checkpoint  restore while step ``step``FaultyCheckpointManager
                    is on disk                 (truncates/deletes payload)
stall_input         loop step >= ``step``      FaultyBatches (sleeps
                                               ``seconds`` in the feed)
kill_process        ``after_s`` after spawn    cli/launch.py supervisor
                                               (SIGKILLs child ``process``)
kill_host           loop step >= ``step``,     FaultInjectionHook on the
                    generation 0 only          victim (SIGKILLs ITSELF) +
                                               cli/launch.py --elastic
                                               (excludes the host from
                                               later generations until
                                               ``recover_after_s`` elapses)
serve_error         predict call >= ``request``FaultyEngine (raises into
                                               the DynamicBatcher)
serve_replica_kill  predict call >= ``request``FaultyEngine on replica
                    on replica ``replica``     ``replica`` (engine goes
                                               PERMANENTLY dead: every
                                               later predict raises
                                               ReplicaKilledError — the
                                               router must fail over)
serve_replica_stall predict call >= ``request``FaultyEngine on replica
                    on replica ``replica``     ``replica`` (sleeps
                                               ``seconds`` once — the
                                               router's hedge trigger)
=================== ========================== ==========================

``kill_host`` vs ``kill_process``: a kill_process is a transient crash —
the same process index comes back in the next (full-size) generation. A
kill_host models permanent host loss: the victim dies at an exact step
(deterministic against import/compile time variance, and only in
generation 0 so restore+replay never re-fires it), and the elastic
supervisor excludes that host from every following generation until its
planned recovery — ``recover_after_s`` wall seconds after the failure is
observed (None = never), at which point the next generation boundary grows
the mesh back.

Every fault fires AT MOST ONCE (`fired` latches), so a replayed step
range after a restore does not re-trigger the same fault — which is what
makes trajectory-identity assertions possible. One plan can be shared by
all layers: each consumer takes only its kinds, so a single
``--fault_plan`` JSON drives the launcher's kill AND the children's
in-loop faults (the flag is forwarded like any train flag).

Wiring helpers (`hook()`, `wrap_batches()`, ...) import faults.inject
lazily so this module stays importable without jax-adjacent code.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

KINDS = (
    "preempt",
    "corrupt_checkpoint",
    "stall_input",
    "kill_process",
    "kill_host",
    "serve_error",
    "serve_replica_kill",
    "serve_replica_stall",
)


@dataclasses.dataclass
class Fault:
    kind: str
    step: int | None = None  # preempt/stall trigger; corrupt target step
    seconds: float | None = None  # stall_input duration
    process: int | None = None  # kill_process target index
    after_s: float | None = None  # kill_process delay after spawn
    request: int | None = None  # serve_error predict-call ordinal (0-based)
    replica: int | None = None  # serve_replica_* target replica id
    recover_after_s: float | None = None  # kill_host: planned recovery delay
    mode: str = "truncate"  # corrupt_checkpoint: truncate | delete
    fired: bool = False  # latched by the consumer on injection

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {KINDS}"
            )

    # -- constructors (the readable way to build plans in code) -------------

    @classmethod
    def preempt(cls, step: int) -> "Fault":
        return cls("preempt", step=step)

    @classmethod
    def corrupt_checkpoint(cls, step: int, mode: str = "truncate") -> "Fault":
        return cls("corrupt_checkpoint", step=step, mode=mode)

    @classmethod
    def stall_input(cls, step: int, seconds: float) -> "Fault":
        return cls("stall_input", step=step, seconds=seconds)

    @classmethod
    def kill_process(cls, process: int, after_s: float = 0.0) -> "Fault":
        return cls("kill_process", process=process, after_s=after_s)

    @classmethod
    def kill_host(
        cls,
        process: int,
        step: int,
        recover_after_s: float | None = None,
    ) -> "Fault":
        """Permanent loss of host ``process`` at train step ``step``;
        re-admitted ``recover_after_s`` seconds after the failure is seen
        by the supervisor (None = stays out for the whole run)."""
        return cls(
            "kill_host",
            process=process,
            step=step,
            recover_after_s=recover_after_s,
        )

    @classmethod
    def serve_error(cls, request: int = 0) -> "Fault":
        return cls("serve_error", request=request)

    @classmethod
    def serve_replica_kill(cls, replica: int, request: int = 0) -> "Fault":
        """Replica ``replica``'s engine dies permanently on predict call
        ``request`` (its ordinal, not the fleet's) — every later predict
        raises ReplicaKilledError, like a device loss under a live server."""
        return cls("serve_replica_kill", replica=replica, request=request)

    @classmethod
    def serve_replica_stall(cls, replica: int, seconds: float,
                            request: int = 0) -> "Fault":
        """Replica ``replica`` sleeps ``seconds`` inside predict call
        ``request`` (once) — a straggler, not a death; what a router's
        hedged requests are for."""
        return cls("serve_replica_stall", replica=replica, seconds=seconds,
                   request=request)

    def to_dict(self) -> dict:
        out = {"kind": self.kind}
        for field in (
            "step",
            "seconds",
            "process",
            "after_s",
            "request",
            "replica",
            "recover_after_s",
        ):
            v = getattr(self, field)
            if v is not None:
                out[field] = v
        if self.kind == "corrupt_checkpoint":
            out["mode"] = self.mode
        return out


class FaultPlan:
    """An ordered set of `Fault`s plus a seed (for consumers that need
    randomness, e.g. the supervisor's restart jitter)."""

    def __init__(self, faults=(), *, seed: int = 0):
        self.faults: list[Fault] = [
            f if isinstance(f, Fault) else Fault(**f) for f in faults
        ]
        self.seed = seed

    # -- (de)serialization: --fault_plan takes inline JSON or a file path --

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        obj = json.loads(text)
        if not isinstance(obj, dict):
            raise ValueError("fault plan JSON must be an object")
        return cls(obj.get("faults", ()), seed=obj.get("seed", 0))

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        spec = spec.strip()
        if not spec:
            return cls()
        if spec.startswith("{"):
            return cls.from_json(spec)
        return cls.from_json(Path(spec).read_text())

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "faults": [f.to_dict() for f in self.faults]}
        )

    # -- consumer queries ---------------------------------------------------

    def pending(self, kind: str) -> list[Fault]:
        return [f for f in self.faults if f.kind == kind and not f.fired]

    def fired(self) -> list[Fault]:
        return [f for f in self.faults if f.fired]

    def kill_spec(self) -> tuple[int, float] | None:
        """(process index, delay seconds) of the first pending kill fault —
        the launcher-level injection (cli/launch.py); None when the plan
        has none. NOT latched here: the launcher marks it fired when the
        kill actually lands."""
        for f in self.pending("kill_process"):
            return f.process or 0, f.after_s or 0.0
        return None

    def host_kill_spec(self) -> tuple[int, float | None] | None:
        """(host id, recover_after_s) of the first pending kill_host —
        the ATTRIBUTION side for the elastic supervisor (the kill itself
        lands in-child via FaultInjectionHook at the fault's step). Not
        latched: the victim latches its own copy of the plan when it
        fires."""
        for f in self.pending("kill_host"):
            return f.process or 0, f.recover_after_s
        return None

    # -- wiring helpers (lazy imports; see faults/inject.py) ----------------

    def hook(self):
        """The in-loop injector (preempt faults) as a train-loop Hook."""
        from dist_mnist_tpu.faults.inject import FaultInjectionHook

        return FaultInjectionHook(self)

    def wrap_batches(self, batches):
        if not self.pending("stall_input"):
            return batches
        from dist_mnist_tpu.faults.inject import FaultyBatches

        return FaultyBatches(batches, self)

    def wrap_checkpoint_manager(self, manager):
        if manager is None or not self.pending("corrupt_checkpoint"):
            return manager
        from dist_mnist_tpu.faults.inject import FaultyCheckpointManager

        return FaultyCheckpointManager(manager, self)

    def wrap_engine(self, engine, *, replica_id: int | None = None):
        """Wrap a serve engine when any serve-side fault is pending.
        ``replica_id`` scopes the replica-targeted kinds: a fleet shares
        ONE plan, and each replica's engine consumes only the faults whose
        ``replica`` matches (plain ``serve_error`` matches any)."""
        if not any(
            self.pending(k)
            for k in ("serve_error", "serve_replica_kill",
                      "serve_replica_stall")
        ):
            return engine
        from dist_mnist_tpu.faults.inject import FaultyEngine

        return FaultyEngine(engine, self, replica_id=replica_id)

    def wrap_step_fn(self, step_fn, *, initial_step: int = 0):
        from dist_mnist_tpu.faults.inject import FaultyStepFn

        return FaultyStepFn(step_fn, self, initial_step=initial_step)
