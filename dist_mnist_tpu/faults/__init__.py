"""Fault injection and resilience: chaos plans, injector shims, the
preemption handshake, and goodput accounting.

Modules:
- plan       — `FaultPlan`/`Fault`: the deterministic fault schedule
- inject     — shims that land each fault kind on its real seam
- preemption — SIGTERM/SIGINT -> step-boundary checkpoint-and-exit-0
- goodput    — productive/restore/replay/stall wall-time attribution

Exports resolve lazily (PEP 562): train/loop.py imports faults.goodput at
its module top, while faults.inject imports train.loop for
PreemptionError — eager re-exports here would close that cycle.
"""

from __future__ import annotations

_EXPORTS = {
    "Fault": "dist_mnist_tpu.faults.plan",
    "FaultPlan": "dist_mnist_tpu.faults.plan",
    "FaultInjectionHook": "dist_mnist_tpu.faults.inject",
    "FaultyBatches": "dist_mnist_tpu.faults.inject",
    "FaultyCheckpointManager": "dist_mnist_tpu.faults.inject",
    "FaultyEngine": "dist_mnist_tpu.faults.inject",
    "FaultyStepFn": "dist_mnist_tpu.faults.inject",
    "GoodputClock": "dist_mnist_tpu.faults.goodput",
    "GoodputHook": "dist_mnist_tpu.faults.goodput",
    "elastic_summary": "dist_mnist_tpu.faults.goodput",
    "PreemptionNotice": "dist_mnist_tpu.faults.preemption",
    "install_preemption_handlers": "dist_mnist_tpu.faults.preemption",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
