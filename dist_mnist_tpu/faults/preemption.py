"""Graceful preemption handshake: signal -> notice -> step-boundary stop.

The reference's chief consumed preemption via the session teardown path
(MonitoredTrainingSession close -> hooks' end, SURVEY.md §3.2); a SIGTERM
mid-step simply killed the process and the next start re-ran
prepare_session. Here the handshake is explicit and CLEAN:

1. SIGTERM/SIGINT sets a `PreemptionNotice` (a latch — async-signal-safe:
   the handler only sets an Event, no I/O, no locks beyond it).
2. `TrainLoop` checks the notice at each STEP BOUNDARY (train/loop.py):
   it saves a checkpoint, waits for it to be durable, records
   `preempted_at`, and requests a stop — hooks and the prefetch worker
   drain through the loop's normal finally path.
3. `cli.train` logs a ``preempted@step=N`` marker and exits 0 — a
   preempted-but-checkpointed run is a SUCCESS to the supervisor and to
   any cluster scheduler watching exit codes.

A SECOND signal of the same number means the operator is done waiting:
the previous disposition is restored and the signal re-raised (default
SIGTERM terminates; SIGINT raises KeyboardInterrupt).
"""

from __future__ import annotations

import signal
import threading


class PreemptionNotice:
    """One-way latch between an async notifier (signal handler, test hook,
    cluster agent thread) and the train loop's step-boundary check."""

    def __init__(self):
        self._event = threading.Event()
        self.reason: str | None = None

    def notify(self, reason: str = "preemption requested") -> None:
        self.reason = reason  # benign race: any writer's reason is fine
        self._event.set()

    def requested(self) -> bool:
        return self._event.is_set()


def install_preemption_handlers(
    notice: PreemptionNotice,
    signals: tuple = (signal.SIGTERM, signal.SIGINT),
):
    """Route `signals` to `notice`; returns an uninstall callable.

    Only valid in the main thread of the main interpreter (CPython signal
    rule) — cli.train's main() qualifies; in-process tests drive the
    notice directly instead."""
    previous: dict = {}

    def _handler(signum, frame):
        del frame
        if notice.requested():
            # second signal: restore the old disposition and re-raise so
            # the operator's escalation actually escalates
            old = previous.get(signum)
            signal.signal(signum, old if old is not None else signal.SIG_DFL)
            signal.raise_signal(signum)
            return
        notice.notify(f"signal {signal.Signals(signum).name}")

    for s in signals:
        previous[s] = signal.signal(s, _handler)

    def uninstall() -> None:
        for s, old in previous.items():
            try:
                signal.signal(s, old if old is not None else signal.SIG_DFL)
            except (ValueError, OSError):  # not main thread / torn down
                pass

    return uninstall
