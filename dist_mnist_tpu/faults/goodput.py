"""Goodput accounting: wall-time attribution for the train loop.

The reference had no notion of goodput — a preempted worker simply
re-ran `prepare_session` and the lost minutes were invisible (SURVEY.md
§3.2). Here every second of the loop's wall clock is attributed to one
of four buckets, so resilience work (faults/, checkpoint fallback,
supervised restarts) has a metric to move:

- ``productive_s`` — steps that advanced the FRONTIER of training.
- ``replay_s``     — steps re-executed after a restore to get back to
                     the pre-failure step (the recovered trajectory must
                     equal the uninterrupted one — train/loop.py re-seeks
                     the input stream — so these are real, correct steps,
                     but they produced no NEW progress).
- ``restore_s``    — checkpoint restore + input re-seek on recovery.
- ``stall_s``      — blocked pulling the next batch or on the runahead
                     bound (the InputPipelineHook's feed/runahead clocks,
                     summed).
- ``compile_s``    — synchronous XLA compile or executable-store load of
                     a step program (the warm-start tier, compilecache/;
                     reported by the step wrapper's `consume_compile_s`).
                     A restart generation that warm-starts shows
                     milliseconds here where a cold one shows seconds —
                     the compile cost PR 4's supervisor made recurring.
- ``resize_s``     — elastic mesh re-formation: the window between a
                     membership change (host lost or recovered) and the
                     first step of the re-formed generation. Priced
                     separately from restore/replay because it is the
                     cost the elastic supervisor (cli/launch.py
                     --elastic) is designed to shrink: no backoff, no
                     full-world restart, warm-started executables at the
                     new mesh shape.
- ``save_s``       — host-side checkpoint save time spent inside the
                     step window: the blocking orbax write on the sync
                     path, or only fork+dispatch (plus any attributed
                     write-behind ``save_stall``) on the async snapshot
                     path (checkpoint/snapshot.py). Split out of
                     "productive" so `bench.py --ckpt` can show the
                     async layer actually moving save cost off the
                     critical path.

``goodput_fraction = productive_s / total_wall_s`` — everything not in
the productive bucket (including untracked overhead: hook bodies, eval,
checkpoint saves) is lost goodput. Per-recovery events additionally
record ``latency_s = restore_s + replay_s`` — the wall time from the
failure to the first post-failure step that advanced the frontier —
which `bench.py --faults` reports as ``recovery_latency_ms``.

Stdlib-only on purpose: train/loop.py imports this module at its top,
so it must not pull jax or the rest of the faults package.
"""

from __future__ import annotations

import time


class GoodputClock:
    """Bucketed wall-clock attribution + per-recovery latency events.

    Owned and fed by `TrainLoop` (one instance per loop); read by
    `GoodputHook` and by bench harnesses via `snapshot()`.
    """

    def __init__(self):
        self.productive_s = 0.0
        self.replay_s = 0.0
        self.restore_s = 0.0
        self.stall_s = 0.0
        self.compile_s = 0.0
        self.resize_s = 0.0
        self.save_s = 0.0
        self.replayed_steps = 0
        #: one dict per recovery: failed_at_step, restored_step, restore_s,
        #: replay_s, replayed_steps, complete, latency_s (once known)
        self.events: list[dict] = []
        self._t0: float | None = None
        self._t_end: float | None = None
        self._open: dict | None = None  # recovery currently being replayed

    # -- loop feed points ---------------------------------------------------

    def start(self) -> None:
        if self._t0 is None:
            self._t0 = time.monotonic()

    def add_stall(self, dt: float) -> None:
        self.stall_s += dt

    def add_productive(self, dt: float) -> None:
        self.productive_s += dt

    def add_compile(self, dt: float) -> None:
        self.compile_s += dt

    def add_resize(self, dt: float) -> None:
        """Mesh re-formation time (elastic shrink/grow). Fed by harnesses
        that observe the whole supervised run — an individual generation
        cannot see its own bring-up window."""
        self.resize_s += dt

    def add_save(self, dt: float) -> None:
        """Checkpoint save time spent inside the step window (hook-side
        dispatch and/or blocking write; reported by CheckpointHook's
        `consume_save_s`, subtracted from the step's productive time by
        the loop exactly like compile_s)."""
        self.save_s += dt

    @property
    def in_replay(self) -> bool:
        return self._open is not None

    def begin_recovery(self, *, failed_at_step: int, restored_step: int,
                       restore_s: float) -> None:
        """A restore just completed: open a recovery event. Replay time is
        charged to it until the loop re-reaches `failed_at_step`."""
        self.restore_s += restore_s
        ev = {
            "failed_at_step": failed_at_step,
            "restored_step": restored_step,
            "restore_s": restore_s,
            "replay_s": 0.0,
            "replayed_steps": 0,
            "complete": False,
        }
        self.events.append(ev)
        self._open = ev
        if restored_step >= failed_at_step:
            # checkpoint landed exactly at the failure step: nothing to replay
            self._finish_open()

    def note_replay(self, dt: float, steps: int, *, at_step: int) -> None:
        """A step executed while catching back up to the failure point."""
        self.replay_s += dt
        self.replayed_steps += steps
        if self._open is not None:
            self._open["replay_s"] += dt
            self._open["replayed_steps"] += steps
            if at_step >= self._open["failed_at_step"]:
                self._finish_open()

    def _finish_open(self) -> None:
        ev, self._open = self._open, None
        if ev is not None:
            ev["complete"] = True
            ev["latency_s"] = ev["restore_s"] + ev["replay_s"]

    def close(self) -> None:
        """Freeze the clock (loop's finally). A recovery still open here
        means the loop ended mid-replay: its latency is recorded as the
        partial restore+replay, with ``complete`` left False."""
        if self._open is not None:
            ev, self._open = self._open, None
            ev["latency_s"] = ev["restore_s"] + ev["replay_s"]
        if self._t_end is None and self._t0 is not None:
            self._t_end = time.monotonic()

    # -- read side ----------------------------------------------------------

    def total_wall_s(self) -> float:
        if self._t0 is None:
            return 0.0
        end = self._t_end if self._t_end is not None else time.monotonic()
        return end - self._t0

    def goodput_fraction(self) -> float:
        total = self.total_wall_s()
        return self.productive_s / total if total > 0 else 0.0

    def recovery_latency_s(self) -> float:
        """Mean failure->frontier latency over recorded recoveries; 0.0
        when the run had none."""
        lats = [ev["latency_s"] for ev in self.events if "latency_s" in ev]
        return sum(lats) / len(lats) if lats else 0.0

    def snapshot(self) -> dict:
        return {
            "productive_s": self.productive_s,
            "replay_s": self.replay_s,
            "restore_s": self.restore_s,
            "stall_s": self.stall_s,
            "compile_s": self.compile_s,
            "resize_s": self.resize_s,
            "save_s": self.save_s,
            "total_wall_s": self.total_wall_s(),
            "goodput_fraction": self.goodput_fraction(),
            "recoveries": len(self.events),
            "replayed_steps": self.replayed_steps,
            "recovery_latency_ms": self.recovery_latency_s() * 1000.0,
        }


class GoodputHook:
    """Publish the loop's GoodputClock as ``goodput/*`` scalars.

    Same shape as the other observability hooks (hooks/builtin.py): reads
    host-side counters only — never a device value — writes one batched
    scalars() call per cadence, and keeps the latest snapshot in ``last``
    for bench harnesses."""

    def __init__(self, writer=None, *, every_steps: int | None = 100):
        from dist_mnist_tpu.hooks.base import EverySteps

        self._writer = writer
        self._timer = EverySteps(every_steps=every_steps or 100)
        self._loop = None
        self.last: dict = {}

    def begin(self, loop) -> None:
        self._loop = loop
        self._timer.prime(loop.initial_step)

    def before_step(self, step: int) -> None:
        pass

    def after_step(self, step: int, state, outputs) -> None:
        if self._timer.should_trigger(step):
            self._timer.mark()
            self._publish(step)

    def end(self, state) -> None:
        self._publish(None)

    def _publish(self, step: int | None) -> None:
        if self._loop is None:
            return
        snap = self._loop.goodput.snapshot()
        self.last = snap
        if self._writer is not None and step is not None:
            self._writer.scalars(
                {f"goodput/{k}": v for k, v in snap.items()}, step
            )


def elastic_summary(records) -> dict:
    """Whole-SUPERVISED-run goodput from a run journal's parsed records.

    A GoodputClock lives inside one generation's train loop; it cannot see
    the supervisor's re-formation windows (child spawn, coordinator
    bring-up, backoff) or sum across generations. This ledger can, because
    the supervisor and every child generation share one journal
    (obs/events.py ENV_JOURNAL):

    - wall        — ``supervisor_start`` .. last ``supervisor_stop`` ts.
    - productive  — FULL-MESH-EQUIVALENT seconds of frontier progress:
                    ``frontier_steps / healthy_rate``, where the healthy
                    rate is measured from this same journal's
                    generation-0 evidence (chief ``first_step`` to the
                    last gen-0 ``checkpoint_save``). Raw busy-seconds
                    would reward a DEGRADED world — a shrunken mesh steps
                    slower, banking more "productive" wall for the same
                    progress — so cross-world-size comparisons (elastic
                    shrink vs full restart) must price progress, not
                    occupancy. When the journal lacks the gen-0 evidence
                    (no first_step/checkpoint cadence), falls back to
                    summing the chief's per-generation ``run_stop``
                    ``goodput.productive_s``.
    - resize      — per membership/restart transition: the failed (or
                    drained) generation's ``generation_end`` ts to the
                    next chief ``first_step`` ts. This is the
                    failure→frontier recovery window, uniform across
                    elastic resizes and full restarts, so
                    ``recovery_latency_s`` is directly comparable.

    Returns goodput_fraction = productive / wall plus the resize ledger.
    Works on any journal: a run with no resizes just reports zero
    recoveries. Stdlib-only like the rest of this module.
    """
    recs = [r for r in records if isinstance(r, dict)]
    t0 = next(
        (r.get("ts") for r in recs if r.get("event") == "supervisor_start"),
        None,
    )
    t1 = next(
        (
            r.get("ts")
            for r in reversed(recs)
            if r.get("event") == "supervisor_stop"
        ),
        None,
    )
    wall = (t1 - t0) if (t0 is not None and t1 is not None) else 0.0

    busy = 0.0
    final_step = None
    for r in recs:
        if (
            r.get("event") == "run_stop"
            and r.get("process", 0) == 0
            and isinstance(r.get("goodput"), dict)
        ):
            busy += float(r["goodput"].get("productive_s", 0.0))  # lint: ok[host-sync] parses a journal JSON float, no device value
            if r.get("step") is not None:
                final_step = r["step"]

    # healthy full-mesh step rate from generation 0's own evidence: chief
    # first_step -> the last gen-0 checkpoint_save (cadence checkpoints
    # carry step + ts). Both sides of an elastic-vs-restart comparison
    # measure their own rate from an identical healthy generation 0, so
    # the normalization cancels out of the ratio.
    g0_first = next(
        (r for r in recs if r.get("event") == "first_step"
         and r.get("gen", 0) == 0 and r.get("process", 0) == 0),
        None,
    )
    g0_saves = [r for r in recs if r.get("event") == "checkpoint_save"
                and r.get("gen", 0) == 0 and r.get("step") is not None
                and r.get("ts") is not None]
    healthy_rate = 0.0
    if g0_first is not None and g0_first.get("ts") is not None and g0_saves:
        last = max(g0_saves, key=lambda r: r["ts"])
        dt = last["ts"] - g0_first["ts"]
        dstep = last["step"] - g0_first.get("step", 0)
        if dt > 0 and dstep > 0:
            healthy_rate = dstep / dt

    # frontier reached: prefer the final run_stop step, fall back to any
    # frontier evidence (a run killed before its run_stop still made
    # progress worth counting)
    frontier = final_step
    if frontier is None:
        frontier = max(
            (r.get("step", 0) for r in recs
             if r.get("event") in ("checkpoint_save", "first_step")),
            default=None,
        )
    if healthy_rate > 0 and frontier:
        productive = frontier / healthy_rate
    else:
        productive = busy

    # one recovery window per non-initial generation: previous
    # generation_end -> first chief first_step at or after the new start
    gen_starts = sorted(
        (
            r
            for r in recs
            if r.get("event") == "generation_start" and r.get("gen", 0) > 0
        ),
        key=lambda r: r.get("ts", 0.0),
    )
    gen_ends = sorted(
        (r for r in recs if r.get("event") == "generation_end"),
        key=lambda r: r.get("ts", 0.0),
    )
    first_steps = sorted(
        (
            r
            for r in recs
            if r.get("event") == "first_step" and r.get("process", 0) == 0
        ),
        key=lambda r: r.get("ts", 0.0),
    )
    latencies = []
    for s in gen_starts:
        ts = s.get("ts", 0.0)
        prev_end = next(
            (e for e in reversed(gen_ends) if e.get("ts", 0.0) <= ts), None
        )
        nxt = next((f for f in first_steps if f.get("ts", 0.0) >= ts), None)
        if prev_end is not None and nxt is not None:
            latencies.append(nxt["ts"] - prev_end["ts"])

    resizes = [
        {
            "kind": r.get("kind"),
            "old_world": r.get("old_world"),
            "new_world": r.get("new_world"),
            "host": r.get("host"),
        }
        for r in recs
        if r.get("event") == "generation_resize"
    ]
    n_gens = 1 + max(
        (
            r.get("gen", 0)
            for r in recs
            if r.get("event") == "generation_start"
        ),
        default=0,
    )
    return {
        "total_wall_s": wall,
        "productive_s": productive,
        "busy_s": busy,
        "healthy_steps_per_s": healthy_rate,
        "resize_s": sum(latencies),
        "goodput_fraction": productive / wall if wall > 0 else 0.0,
        "recovery_latency_s": (
            sum(latencies) / len(latencies) if latencies else 0.0
        ),
        "recoveries": len(latencies),
        "generations": n_gens,
        "resizes": resizes,
        "final_step": final_step,
    }
