"""Orbax-backed checkpointing of the TrainState pytree.

Reference mapping (SURVEY.md §3.5): graph-embedded SaveV2/RestoreV2 streamed
PS-resident variables through the chief to a sharded V2 file
(saver.py:233-312, 1186), `checkpoint` state proto tracked latest
(checkpoint_management.py:176), `SessionManager.prepare_session` auto-
restored (:186-257). Here: Orbax writes each process's shards in parallel
(tensorstore), keeps a step index, GCs to `max_to_keep`, saves async so the
TPU never waits on disk, and `restore_or_init` is the prepare_session
analogue.

Crash consistency (PR 11): a step directory is only RESTORE-ELIGIBLE once
its commit marker lands at ``<dir>/commits/<step>.committed`` (written
atomically via rename, only after the write is known durable — immediately
on the sync path, deferred to the next save()/wait() on the async path,
which is sound because orbax blocks a new save until the previous async
write finished). A kill mid-write leaves a step directory with no marker;
`restore()` quarantines it through the existing ladder without consuming a
fallback, and `latest_step()` never reports it. A checkpoint directory
that predates the protocol (steps present, no ``commits/``) is adopted on
open: its steps get markers, since they were written by a manager that
waited for durability before exiting.
"""

from __future__ import annotations

import logging
from pathlib import Path

import jax

from dist_mnist_tpu.obs import events

log = logging.getLogger(__name__)

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is expected in this env
    _HAVE_ORBAX = False


def _strip_metric_state(state, keep=frozenset()):
    """(state without top-level `_metric` model_state entries — except
    those in `keep` — and the full metric key set). Those entries are
    additive health stats (train/step.py metric contract) — a checkpoint
    written before a model grew them is still fully valid; restore
    without the ones it lacks and refill from the target. `keep` lets the
    healing ladder trim the target to exactly the checkpoint's OWN metric
    set (a checkpoint with SOME metrics can't restore into a target
    stripped of ALL of them — code review r5)."""
    import dataclasses

    ms = state.model_state
    if not isinstance(ms, dict):
        return state, set()
    keys = {k for k in ms if isinstance(k, str) and k.endswith("_metric")}
    if not keys:
        return state, set()
    stripped = {k: v for k, v in ms.items()
                if k not in keys or k in keep}
    return dataclasses.replace(state, model_state=stripped), keys


def _refill_metric_state(restored, target_state):
    """Put back any `_metric` entries the healed restore omitted, using the
    target's (initial) values."""
    import dataclasses

    ms, tms = restored.model_state, target_state.model_state
    if not isinstance(ms, dict) or not isinstance(tms, dict):
        return restored
    missing = {k: v for k, v in tms.items()
               if isinstance(k, str) and k.endswith("_metric")
               and k not in ms}
    if not missing:
        return restored
    return dataclasses.replace(restored, model_state={**ms, **missing})


def _flip_block_layouts(state, probe_only: bool = False):
    """A copy of `state` with every ViT-block-layout dict (params and the
    optimizer slots that mirror them) converted to the OTHER layout via
    models.vit.convert_block_layout; None when the state contains no block
    layout at all (the mismatch is then something else — re-raise).
    `probe_only=True` answers "would a flip apply?" WITHOUT materializing
    the converted copy (the conversion allocates a transient ~2x of
    params + optimizer slots on device)."""
    import dataclasses
    import re

    from dist_mnist_tpu.models.vit import convert_block_layout

    found = False

    def is_block_dict(node):
        return isinstance(node, dict) and (
            "blocks" in node or any(
                isinstance(k, str) and re.fullmatch(r"block\d+", k)
                for k in node
            )
        )

    def rec(node):
        nonlocal found
        if is_block_dict(node):
            found = True
            return node if probe_only else convert_block_layout(node)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if isinstance(node, tuple):  # chained optimizer states
            vals = (rec(v) for v in node)
            return (type(node)(*vals) if hasattr(node, "_fields")
                    else tuple(vals))
        if isinstance(node, list):
            return [rec(v) for v in node]
        return node

    converted = (rec(state.params), rec(state.model_state),
                 rec(state.opt_state))
    if not found:
        return None
    if probe_only:
        return True
    return dataclasses.replace(
        state, params=converted[0], model_state=converted[1],
        opt_state=converted[2],
    )


def _tree_key_names(tree) -> set[str]:
    """Every string dict key anywhere in `tree` (container keys, not
    leaves) — the vocabulary a *structural* KeyError out of a restore of
    this tree could possibly name."""
    names: set[str] = set()

    def rec(node):
        if isinstance(node, dict) or hasattr(node, "keys"):
            for k in node.keys():
                if isinstance(k, str):
                    names.add(k)
                rec(node[k])
        elif isinstance(node, (list, tuple)):
            for v in node:
                rec(v)

    rec(tree)
    return names


def _path_names(tree) -> set[str]:
    """Normalized "/"-joined key-path set of `tree`'s leaves — comparable
    across a dataclass pytree (GetAttrKey) and a metadata dict (DictKey)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {
        "/".join(str(getattr(k, "key", None) or getattr(k, "name", None)
                     or k) for k in path)
        for path, _ in flat
    }


def _is_read_corruption(err: Exception) -> bool:
    """Does `err` look like an UNREADABLE payload (truncated / missing /
    mangled array data) rather than a structure mismatch or a logic error?
    This gates the restore FALLBACK ladder (next-older step), which only
    makes sense for damage local to one step directory — a structural
    mismatch would fail identically on every older step and must propagate.

    OSError/EOFError are corruption by TYPE (the storage layer itself
    failed). KeyError/TypeError are structural by construction (the
    `_is_healable` territory) and never corruption. Tensorstore, however,
    surfaces short reads as a plain ValueError — for that one type the
    storage-layer markers in the message are the only evidence there is."""
    if isinstance(err, (EOFError, OSError)):  # FileNotFoundError is OSError
        return True
    if not isinstance(err, ValueError):
        return False
    msg = str(err).lower()
    return any(m in msg for m in (
        "out_of_range", "data_loss", "error reading", "failed to read",
        "tensorstore", "ocdbt", "zarr", "truncat", "corrupt", "checksum",
        "no such file", "could not open",
    ))


def _phrasing_matches(err: Exception) -> bool:
    """The fast path: Orbax's measured structure-mismatch wordings. Kept
    only as a zero-I/O shortcut — classification no longer DEPENDS on
    phrasing (ADVICE r5: an Orbax upgrade that rewords the ValueError
    must not turn healable restores into hard failures); the metadata
    probe in `CheckpointManager._is_healable` is the authority."""
    msg = str(err).lower()
    return ("tree structure" in msg or "structures do not match" in msg
            or "user-provided restore item" in msg
            or "dict key mismatch" in msg)


class CheckpointManager:
    """Save/restore `TrainState` with retention + async write.

    `max_to_keep` ≙ tf.train.Saver(max_to_keep=5) default; directory layout
    is Orbax's step-numbered tree (the analogue of model.ckpt-<step> files +
    the `checkpoint` proto).
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        max_to_keep: int = 5,
        async_save: bool = True,
        max_restore_fallbacks: int = 1,
    ):
        if not _HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is required for CheckpointManager")
        # how many OLDER steps restore() may fall back to when the latest
        # is unreadable (each unreadable step is quarantined); 0 disables
        # the ladder and restores the strict propagate-first-error behavior
        self.max_restore_fallbacks = max_restore_fallbacks
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        # Multiprocess: orbax's default barrier is
        # multihost_utils.sync_global_devices — a jitted device all-reduce.
        # AsyncSnapshotter calls save() from a background writer thread,
        # and a device collective there deadlocks against the main
        # thread's training collectives (the two processes enqueue them in
        # different orders). Naming active_processes explicitly switches
        # every orbax barrier to the distributed-client KV barrier, which
        # orbax documents as safe from independent background threads.
        mp_kwargs = {}
        if jax.process_count() > 1:
            mp_kwargs["multiprocessing_options"] = ocp.options.MultiprocessingOptions(
                active_processes=set(range(jax.process_count())),
            )
            # orbax refuses create=True together with active_processes;
            # the root was mkdir'd above, on every process
            mp_kwargs["create"] = False
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
            **mp_kwargs,
        )
        try:
            # declare the item handler up front: without it, a manager that
            # has not saved/restored in THIS process cannot read tree
            # metadata (`item_metadata` returns None) — which a fresh
            # serving process needs for the weights-only restore below
            self._mgr = ocp.CheckpointManager(
                self.directory, options=options,
                item_handlers=ocp.StandardCheckpointHandler(),
            )
        except TypeError:  # older orbax without item_handlers
            self._mgr = ocp.CheckpointManager(self.directory, options=options)
        self._last_saved: int | None = None
        self._async = bool(async_save)
        # step -> dispatch time.monotonic() of async saves whose commit
        # marker hasn't landed yet (flushed by the next save()/wait())
        self._pending_commits: dict[int, float] = {}
        self._commits_dir = self.directory / "commits"
        self._adopt_legacy_steps()

    # -- commit-marker protocol ---------------------------------------------

    def _adopt_legacy_steps(self) -> None:
        """First open of a pre-protocol directory (steps, no ``commits/``):
        mark every existing step committed — its writer waited for
        durability before exiting. Presence of ``commits/`` afterwards is
        what distinguishes 'uncommitted step' from 'legacy step'."""
        if self._commits_dir.exists():
            return
        self._commits_dir.mkdir(parents=True, exist_ok=True)
        for step in self._mgr.all_steps():
            self._write_marker(int(step))

    def _marker_path(self, step: int) -> Path:
        return self._commits_dir / f"{step}.committed"

    def _write_marker(self, step: int) -> None:
        import json
        import os

        tmp = self._commits_dir / f"{step}.committed.tmp-{os.getpid()}"
        tmp.write_text(json.dumps({"step": step}), encoding="utf-8")
        os.replace(tmp, self._marker_path(step))

    def _is_committed(self, step: int) -> bool:
        return (step in self._pending_commits
                or self._marker_path(step).exists())

    def _flush_commits(self) -> None:
        """Write markers for every async save known durable (callers
        guarantee durability: orbax waited for the previous save, or
        wait_until_finished just returned), emit the paired
        ``checkpoint_commit`` events, and prune markers orphaned by
        retention GC."""
        if not self._pending_commits:
            return
        import time as _time

        live = set(self._mgr.all_steps())
        for step, dispatch_ts in sorted(self._pending_commits.items()):
            dur_ms = round((_time.monotonic() - dispatch_ts) * 1e3, 3)
            if step in live:
                self._write_marker(step)
                events.emit("checkpoint_commit", step=step, dur_ms=dur_ms)
            # a pending step GC'd before its marker landed is simply gone
        self._pending_commits.clear()
        for p in self._commits_dir.glob("*.committed"):
            try:
                if int(p.stem.split(".")[0]) not in live:
                    p.unlink(missing_ok=True)
            except (ValueError, OSError):
                pass

    def flush_commits(self) -> None:
        """Opportunistic marker flush for the training loop (called every
        step by CheckpointHook): an async save's marker must land as soon
        as the write is durable, not at the NEXT save()/wait() — a kill
        inside the cadence window would otherwise quarantine a step that
        WAS durable, rolling the restore back a whole cadence interval.

        Durability authority here is the on-disk FINALIZED step directory
        (orbax's atomic rename from its ``*.orbax-checkpoint-tmp-*`` name;
        same plain-``str(step)`` layout `_quarantine` relies on) — NOT
        `all_steps()`, whose cached view already lists the still-writing
        step."""
        if not self._pending_commits:
            return
        import time as _time

        for step in sorted(self._pending_commits):
            if not (self.directory / str(step)).is_dir():
                continue
            dispatch_ts = self._pending_commits.pop(step)
            self._write_marker(step)
            events.emit(
                "checkpoint_commit", step=step,
                dur_ms=round((_time.monotonic() - dispatch_ts) * 1e3, 3),
            )

    def latest_step(self, *, refresh: bool = False) -> int | None:
        """Newest COMMITTED step on disk (in-process async saves count —
        their durability is guaranteed before this process exits). Orbax
        caches the step list at init; `refresh=True` rescans the
        directory — required when ANOTHER process/manager is writing
        (GlobalStepWaiterHook's cross-job observation; ≙ re-reading the
        `checkpoint` state proto, checkpoint_management.py:251)."""
        if refresh:
            self._mgr.reload()
        committed = [s for s in self._mgr.all_steps() if self._is_committed(s)]
        return max(committed) if committed else None

    def save(self, state, *, dispatch_ts: float | None = None) -> bool:
        """Save if this step isn't already on disk (re-saving an identical
        step is never useful — e.g. save-on-create right after a restore).

        Sharded state (FSDP/TP) is written WITHOUT host-gathering full
        replicas: Orbax serializes each addressable shard straight to
        tensorstore, so an fsdp state's checkpoint I/O per process is
        1/data-th of the dp case, matching its HBM footprint.

        `dispatch_ts` (time.monotonic) backdates the dispatch→durable span
        on the ``checkpoint_commit`` event — the async snapshot layer
        passes its fork time so the span covers the whole write-behind."""
        import time as _time

        step = state.step_int
        if step == self._last_saved or step == self.latest_step():
            return False
        t0 = dispatch_ts if dispatch_ts is not None else _time.monotonic()
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
        if saved:
            # orbax blocked until the PREVIOUS async save landed: those
            # pending markers are flushable now, this step's is not yet
            self._pending_commits.pop(step, None)
            self._flush_commits()
            if self._async:
                self._pending_commits[step] = t0
            else:
                self._write_marker(step)
                events.emit(
                    "checkpoint_commit", step=step,
                    dur_ms=round((_time.monotonic() - t0) * 1e3, 3),
                )
            self._last_saved = step
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
            events.emit("checkpoint_save", step=step)
        return bool(saved)

    def restore(self, target_state):
        """Restore the latest checkpoint into target_state's structure
        (shardings included — each leaf is restored with the sharding of the
        matching target leaf, so restore is collective on multi-host).
        Returns None when no checkpoint exists.

        A structure mismatch that is exactly the ViT scanned↔unrolled block
        layout flip (``blocks`` stack vs ``block0..N-1`` entries — the two
        layouts `scan_blocks` toggles between, models/vit.py
        ``convert_block_layout``) is healed transparently: the checkpoint is
        restored in ITS layout and converted to the target's (params AND the
        structurally-mirrored optimizer slots), so flipping `scan_blocks`
        between runs does not orphan checkpoints (VERDICT r3 weak 7).

        A latest step that is UNREADABLE for a non-structural reason
        (truncated/missing array files — `_is_read_corruption`) falls back
        to the next-older step, quarantining the bad directory under
        ``<dir>/quarantine/`` so no later restore trips on it again; at
        most `max_restore_fallbacks` times. Anything else — and corruption
        with no older step left — re-raises the ORIGINAL error.

        A step directory with NO commit marker (a writer died mid-write —
        the marker only lands after durability) is quarantined up front
        WITHOUT consuming a fallback: it never was a restore point, so it
        must not burn the ladder's budget for genuinely corrupted
        committed steps."""
        if self._pending_commits:
            self.wait()  # our own in-flight writes: make them committed
        for bad in [s for s in self._mgr.all_steps()
                    if not self._is_committed(s)]:
            log.warning(
                "checkpoint step %d has no commit marker (writer died "
                "mid-write?); quarantining it", bad,
            )
            self._quarantine(bad)
        step = self.latest_step()
        fallbacks = 0
        while step is not None:
            try:
                return self._restore_step(step, target_state)
            except Exception as err:  # noqa: BLE001 — classified below
                older = self._step_before(step)
                if (older is None
                        or fallbacks >= self.max_restore_fallbacks
                        or not _is_read_corruption(err)):
                    raise
                log.error(
                    "checkpoint step %d unreadable (%s: %s); quarantining "
                    "it and falling back to step %d",
                    step, type(err).__name__, str(err)[:200], older,
                )
                self._quarantine(step)
                fallbacks += 1
                step = older
        return None

    def _restore_step(self, step: int, target_state):
        """Restore ONE specific step (structure healing included)."""
        import time as _time

        t0 = _time.monotonic()
        try:
            restored = self._restore_into(step, target_state)
        except Exception as err:
            # only tree-structure mismatches enter the healing ladder
            # (advisor r4: transient I/O or data corruption used to burn
            # up to 3 more full restore attempts before the original
            # error re-raised)
            if not self._is_healable(err, step, target_state):
                raise
            restored = self._restore_with_structure_healing(
                step, target_state, err
            )
        log.info("restored checkpoint step %d from %s", step, self.directory)
        events.emit("checkpoint_restore", step=step, source="store",
                    dur_ms=round((_time.monotonic() - t0) * 1e3, 3))
        return restored

    def _step_before(self, step: int) -> int | None:
        older = [s for s in self._mgr.all_steps()
                 if s < step and self._is_committed(s)]
        return max(older) if older else None

    def _quarantine(self, step: int) -> None:
        """Move the step's directory out of Orbax's step namespace — to
        ``<dir>/quarantine/step_<N>`` — so retention, latest_step and any
        later restore never see it again, then reset the manager's cached
        step view. Moved, not deleted: the payload stays available for
        post-mortem."""
        import shutil

        src = self.directory / str(step)
        dst_root = self.directory / "quarantine"
        dst_root.mkdir(exist_ok=True)
        dst = dst_root / f"step_{step}"
        if dst.exists():
            shutil.rmtree(dst)
        if src.exists():
            shutil.move(str(src), str(dst))
        self._marker_path(step).unlink(missing_ok=True)
        self._pending_commits.pop(step, None)
        if self._last_saved == step:
            self._last_saved = None  # a re-save of this step must not dedupe
        self._mgr.reload()
        events.emit("checkpoint_quarantine", step=step)

    def _restore_with_structure_healing(self, step, target_state, err):
        """Fallback ladder for known benign structure drifts, tried in
        order; anything else re-raises the ORIGINAL error (never the
        fallback attempts' — a corrupted checkpoint must not be
        misdiagnosed as a layout mismatch):
        1. checkpoint carries an older `_metric` model-state set (additive
           health stats, parallel/moe.py) — trim the target's metric keys
           to exactly the on-disk set (read from checkpoint metadata)
           when known, else strip them all; restore, then fill the rest
           from the target's initial values;
        2. ViT scanned<->unrolled block layout flip;
        3. both at once."""
        stripped, metric_keys = _strip_metric_state(target_state)
        ondisk = self._ondisk_model_state_keys(step)
        keep = (metric_keys & ondisk) if ondisk is not None else set()
        trimmed = (_strip_metric_state(target_state, keep=keep)[0]
                   if keep and keep != metric_keys else None)
        has_blocks = _flip_block_layouts(target_state, probe_only=True)
        # alt targets built LAZILY and the flip MEMOIZED: the conversion
        # materializes a transient ~2x copy of params + optimizer slots on
        # device (stack/slice ops), so it must run at most once, and only
        # when a flip attempt is actually tried
        flip_cache: list = []

        def flipped():
            if not flip_cache:
                flip_cache.append(_flip_block_layouts(target_state))
            return flip_cache[0]

        # metadata showing the on-disk metric set already equals the
        # target's proves the strip rungs can't help — skip them
        strip_can_help = metric_keys and (ondisk is None
                                          or keep != metric_keys)
        attempts = []
        if trimmed is not None:
            attempts.append(("with only the on-disk _metric entries "
                             f"{sorted(keep)}",
                             lambda: trimmed, False))
        if strip_can_help:
            attempts.append(("without the _metric model-state entries "
                             f"{sorted(metric_keys)}",
                             lambda: stripped, False))
        if has_blocks:
            attempts.append(("in the flipped ViT block layout",
                             flipped, True))
        if strip_can_help and has_blocks:
            if trimmed is not None:
                attempts.append(
                    ("flipped layout + on-disk _metric entries only",
                     lambda: _strip_metric_state(flipped(), keep=keep)[0],
                     True))
            attempts.append(("flipped layout + no _metric entries",
                             lambda: _strip_metric_state(flipped())[0],
                             True))
        for what, make_target, is_flipped in attempts:
            try:
                restored = self._restore_into(step, make_target())
            except Exception:
                continue
            log.warning(
                "checkpoint step %d did not match the target structure "
                "(%s: %s); restored %s",
                step, type(err).__name__, str(err)[:200], what,
            )
            if is_flipped:
                restored = _flip_block_layouts(restored)
            restored = _refill_metric_state(restored, target_state)
            # healed leaves may have come off stack/slice ops — re-place
            # them on the target's shardings so downstream jits see the
            # right layout
            shardings = jax.tree.map(
                lambda x: x.sharding if isinstance(x, jax.Array) else None,
                target_state,
            )
            return jax.tree.map(
                lambda a, s: jax.device_put(a, s) if s is not None else a,
                restored, shardings,
            )
        raise err

    def _is_healable(self, err: Exception, step: int, target_state) -> bool:
        """Should `err` (raised by a restore of `target_state`) enter the
        structure-healing ladder?

        Decided by exception TYPE plus evidence, never by wording alone
        (ADVICE r5 — an Orbax upgrade rewording its errors must not turn
        healable restores into hard failures):

        - ``KeyError``: structural only when the missing key is an actual
          tree key of the target (or of the on-disk metadata tree) — a
          KeyError naming a key NEITHER tree contains came from somewhere
          else (e.g. a bug in target construction) and must propagate, not
          buy up to 5 extra full restore attempts.
        - ``ValueError``/``TypeError``: the known phrasings short-circuit
          (zero I/O); otherwise the on-disk tree metadata is probed
          directly — a leaf-path set differing from the target's IS a
          structure mismatch, whatever the message said.
        - anything else (OSError, tensorstore read/checksum failures, …)
          is not healable and propagates immediately.
        """
        if isinstance(err, KeyError):
            key = err.args[0] if err.args else None
            if not isinstance(key, str):
                return False
            names = _tree_key_names(
                {"params": target_state.params,
                 "model_state": target_state.model_state}
            ) | {"params", "model_state", "opt_state", "step", "rng"}
            if key in names:
                return True
            ondisk = self._ondisk_tree(step)
            return ondisk is not None and key in _tree_key_names(ondisk)
        if not isinstance(err, (ValueError, TypeError)):
            return False
        if _phrasing_matches(err):
            return True
        ondisk = self._ondisk_tree(step)
        if ondisk is None:
            return False  # no evidence either way: don't retry blindly
        return _path_names(ondisk) != _path_names(target_state)

    def _ondisk_tree(self, step: int):
        """The checkpoint's metadata tree (no array reads), or None when
        unreadable. Orbax >=0.6 wraps it in an object with a ``.tree``
        attribute; older managers hand back the tree itself."""
        try:
            meta = self._mgr.item_metadata(step)
            tree = getattr(meta, "tree", meta)
            return tree if hasattr(tree, "keys") else None
        except Exception:
            return None

    def _ondisk_model_state_keys(self, step: int):
        """Top-level model_state key set of the checkpoint on disk (from
        Orbax tree metadata — no array reads), or None when metadata
        isn't readable; the healing ladder then falls back to the
        strip-everything rung."""
        tree = self._ondisk_tree(step)
        if tree is None:
            return None
        try:
            ms = tree.get("model_state")
            return set(ms.keys()) if hasattr(ms, "keys") else None
        except Exception:
            return None

    def _restore_into(self, step: int, target_state):
        """Restore `step` into the TARGET's structure AND shardings.

        The abstract tree below carries each target leaf's sharding, which
        makes restore a RESHARDING operation by construction: a checkpoint
        written under `dp` (every leaf replicated) restores into an `fsdp`
        target with each device reading only ITS 1/data-th shard from
        tensorstore, and vice versa — no host-side gather/scatter of full
        replicas in either direction, and no "saved layout must equal
        restored layout" coupling (the V2-file analogue of which forced the
        reference to restore onto the same ps partitioning it saved from).
        The dp↔fsdp round-trip is pinned by tests/test_fsdp.py."""
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array)
            else x,
            target_state,
        )
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def restore_weights(self, abstract_params, abstract_model_state, *,
                        step: int | None = None):
        """Weights-only restore for inference (serve/loader.py): returns
        ``(step, params, model_state)`` — or None when no checkpoint exists.

        No optimizer is ever constructed: this orbax's StandardRestore
        refuses a target missing top-level keys, so the non-weight entries
        (opt_state, rng, step) get *metadata-derived* abstract leaves
        (shape/dtype read from the checkpoint's own tree metadata, zero
        optimizer code involved) and the restored slots are dropped on the
        floor. For an Adam state that halves restore-target memory; more
        importantly serving needs no optimizer import at all.

        `abstract_params`/`abstract_model_state` are ShapeDtypeStruct trees
        (shardings included) — build them with `jax.eval_shape` over
        `model.init` so no throwaway init allocation happens either."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        tree = self._ondisk_tree(step)
        if tree is None:
            raise RuntimeError(
                f"checkpoint step {step} in {self.directory} has no readable "
                "tree metadata; cannot build a weights-only restore target"
            )

        def absify(meta):
            return jax.ShapeDtypeStruct(tuple(meta.shape), meta.dtype)

        abstract = {
            k: jax.tree.map(absify, tree[k])
            for k in tree.keys()
            if k not in ("params", "model_state")
        }
        abstract["params"] = abstract_params
        abstract["model_state"] = abstract_model_state
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        return step, restored["params"], restored["model_state"]

    def restore_or_init(self, init_state):
        """≙ SessionManager.prepare_session (session_manager.py:259): try the
        latest checkpoint, else the freshly-initialized state."""
        restored = self.restore(init_state)
        return (restored, True) if restored is not None else (init_state, False)

    def wait(self) -> None:
        """Block until every dispatched save is durable AND committed —
        the durability point `TrainLoop._honor_preemption` and
        `CheckpointHook.end` rely on before the process may exit."""
        self._mgr.wait_until_finished()
        self._flush_commits()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_commits()
        self._mgr.close()
