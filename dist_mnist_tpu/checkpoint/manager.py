"""Orbax-backed checkpointing of the TrainState pytree.

Reference mapping (SURVEY.md §3.5): graph-embedded SaveV2/RestoreV2 streamed
PS-resident variables through the chief to a sharded V2 file
(saver.py:233-312, 1186), `checkpoint` state proto tracked latest
(checkpoint_management.py:176), `SessionManager.prepare_session` auto-
restored (:186-257). Here: Orbax writes each process's shards in parallel
(tensorstore), keeps a step index, GCs to `max_to_keep`, saves async so the
TPU never waits on disk, and `restore_or_init` is the prepare_session
analogue.
"""

from __future__ import annotations

import logging
from pathlib import Path

import jax

log = logging.getLogger(__name__)

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is expected in this env
    _HAVE_ORBAX = False


class CheckpointManager:
    """Save/restore `TrainState` with retention + async write.

    `max_to_keep` ≙ tf.train.Saver(max_to_keep=5) default; directory layout
    is Orbax's step-numbered tree (the analogue of model.ckpt-<step> files +
    the `checkpoint` proto).
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        max_to_keep: int = 5,
        async_save: bool = True,
    ):
        if not _HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is required for CheckpointManager")
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        self._last_saved: int | None = None

    def latest_step(self, *, refresh: bool = False) -> int | None:
        """Newest step on disk. Orbax caches the step list at init;
        `refresh=True` rescans the directory — required when ANOTHER
        process/manager is writing (GlobalStepWaiterHook's cross-job
        observation; ≙ re-reading the `checkpoint` state proto,
        checkpoint_management.py:251)."""
        if refresh:
            self._mgr.reload()
        return self._mgr.latest_step()

    def save(self, state) -> bool:
        """Save if this step isn't already on disk (re-saving an identical
        step is never useful — e.g. save-on-create right after a restore)."""
        step = state.step_int
        if step == self._last_saved or step == self.latest_step():
            return False
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
        if saved:
            self._last_saved = step
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
        return bool(saved)

    def restore(self, target_state):
        """Restore the latest checkpoint into target_state's structure
        (shardings included — each leaf is restored with the sharding of the
        matching target leaf, so restore is collective on multi-host).
        Returns None when no checkpoint exists."""
        step = self.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array)
            else x,
            target_state,
        )
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        log.info("restored checkpoint step %d from %s", step, self.directory)
        return restored

    def restore_or_init(self, init_state):
        """≙ SessionManager.prepare_session (session_manager.py:259): try the
        latest checkpoint, else the freshly-initialized state."""
        restored = self.restore(init_state)
        return (restored, True) if restored is not None else (init_state, False)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
