"""Peer-replicated shards: elastic recovery at local-disk speed.

PR 8's shrink path restores a dead host's shards from the checkpoint
STORE — the disk round-trip dominates its 2.39 s recovery. The reference
had nothing faster to offer (one chief owned all V2 files, SURVEY.md
§3.5); an SPMD fleet does: every host already holds 1/N of the state in
memory, so each host additionally keeps a REPLICA of its ring neighbor's
shards (`ring_peer` over cluster/membership.py host ids), and a shrink
restores the dead host's shards from the surviving peer instead of the
store — falling back to the store when the peer died with it.

Layout (this repo models "host h's local disk" as ``<root>/h<h>/``; in a
real fleet the replica write is a neighbor-to-neighbor send):

    <root>/h<holder>/s<src>/step_<N>.npz

``holder`` is whose disk it is, ``src`` is whose shards the file holds —
each host pushes its own shards to its own dir AND its ring peer's.
The atomic rename into place IS the commit marker: readers only ever see
complete files, a kill mid-write leaves a ``.tmp-<pid>`` that no restore
considers. A restore assembles every source host's pieces from dirs whose
holder is ALIVE (`DIST_MNIST_TPU_ALIVE_HOSTS`, stamped per generation by
the elastic supervisor) and verifies full element coverage per leaf; any
gap — peer and owner both gone, partial write set, src that never wrote —
returns None and the caller falls back to the store.
"""

from __future__ import annotations

import io
import json
import logging
import os
from pathlib import Path

import jax
import numpy as np

from dist_mnist_tpu.cluster.membership import ENV_ALIVE_HOSTS, ring_peer

log = logging.getLogger(__name__)

#: in-flight atomic-write temp files (conftest leak check: a pending entry
#: after a test means a write path skipped its finally)
_PENDING_TMP: set = set()


def _default_host_of(device) -> int:
    return int(getattr(device, "process_index", 0))


def alive_hosts_from_env(default=None) -> list[int] | None:
    """Parse the supervisor-stamped alive-host list; `default` when the
    env is absent (single-generation runs outside the supervisor)."""
    raw = os.environ.get(ENV_ALIVE_HOSTS)
    if not raw:
        return default
    try:
        return sorted({int(tok) for tok in raw.split(",") if tok.strip()})
    except ValueError:
        log.warning("unparseable %s=%r; ignoring", ENV_ALIVE_HOSTS, raw)
        return default


def _leaf_path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", None) or getattr(k, "name", None) or k)
        for k in path
    )


def _normalize_index(index, shape):
    """A shard's index as concrete (start, stop) per dim (Nones resolved)."""
    spans = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        spans.append((start, stop))
    return tuple(spans)


class PeerReplicator:
    """Serialize THIS host's addressable shards to its own dir and its
    ring peer's; assemble any host set's shards back on restore.

    `host_of` maps a jax device to a stable host id — defaults to
    `device.process_index`; injectable so single-process tests can fake a
    multi-host fleet over the 8-device CPU mesh."""

    def __init__(self, root: str | Path, host_id: int, hosts, *,
                 host_of=None, max_to_keep: int = 5):
        self.root = Path(root).absolute()
        self.host_id = int(host_id)
        self.hosts = sorted({int(h) for h in hosts})
        self.peer = ring_peer(self.host_id, self.hosts)
        self._host_of = host_of or _default_host_of
        self.max_to_keep = max(1, int(max_to_keep))

    # -- write side ---------------------------------------------------------

    def write(self, step: int, state) -> None:
        """Serialize this host's shards of `state` at `step` to local disk
        and the ring peer's. Runs on the snapshot writer thread — the only
        host sync in the save path happens here, off the loop."""
        payload, meta = self._serialize(state)
        holders = [self.host_id] if self.peer is None else [
            self.host_id, self.peer,
        ]
        for holder in holders:
            d = self.root / f"h{holder}" / f"s{self.host_id}"
            d.mkdir(parents=True, exist_ok=True)
            tmp = d / f"step_{int(step)}.npz.tmp-{os.getpid()}"
            _PENDING_TMP.add(tmp)
            try:
                buf = io.BytesIO()
                np.savez(buf, __meta__=np.frombuffer(
                    json.dumps(meta).encode("utf-8"), dtype=np.uint8),
                    **payload)
                tmp.write_bytes(buf.getvalue())
                os.replace(tmp, d / f"step_{int(step)}.npz")
            finally:
                _PENDING_TMP.discard(tmp)
                tmp.unlink(missing_ok=True)
            self._prune(d)

    def _serialize(self, state):
        """(npz payload dict, meta list) for every shard this host owns.

        Replicated leaves dedupe to one piece per distinct index span, so
        a pure-DP state costs each host one full copy (same as orbax's
        per-process write), an FSDP state 1/data-th."""
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        payload: dict = {}
        meta: list = []
        n = 0
        for path, leaf in flat:
            if not isinstance(leaf, jax.Array):
                continue
            pieces = []
            seen = set()
            for shard in leaf.addressable_shards:
                if self._host_of(shard.device) != self.host_id:
                    continue
                spans = _normalize_index(shard.index, leaf.shape)
                if spans in seen:
                    continue  # replicated across this host's devices
                seen.add(spans)
                key = f"a{n}"
                n += 1
                payload[key] = np.asarray(shard.data)
                pieces.append({"key": key,
                               "start": [s for s, _ in spans],
                               "stop": [e for _, e in spans]})
            meta.append({
                "path": _leaf_path_str(path),
                "shape": list(leaf.shape),
                "dtype": str(leaf.dtype),
                "pieces": pieces,
            })
        return payload, meta

    def _prune(self, d: Path) -> None:
        files = sorted(d.glob("step_*.npz"),
                       key=lambda p: int(p.stem.split("_")[1]))
        for p in files[:-self.max_to_keep]:
            p.unlink(missing_ok=True)

    # -- read side ----------------------------------------------------------

    def restore(self, target_state, *, alive=None, min_step=None):
        return restore_from_peers(
            self.root, target_state, alive=alive, min_step=min_step,
        )


def _scan(root: Path) -> dict:
    """{step: {src: [readable file, ...]}} over the whole peer root."""
    out: dict = {}
    for holder_dir in root.glob("h*"):
        for src_dir in holder_dir.glob("s*"):
            try:
                src = int(src_dir.name[1:])
            except ValueError:
                continue
            for f in src_dir.glob("step_*.npz"):
                try:
                    step = int(f.stem.split("_")[1])
                except (ValueError, IndexError):
                    continue
                out.setdefault(step, {}).setdefault(src, []).append(f)
    return out


def restore_from_peers(root: str | Path, target_state, *, alive=None,
                       min_step: int | None = None):
    """Assemble the freshest fully-covered step from alive holders' dirs
    into `target_state`'s structure and shardings.

    Returns ``(state, step, sources)`` — sources maps src host -> the
    holder dir its pieces were read from — or None when no step at or
    above `min_step` has full element coverage from alive holders (the
    caller then falls back to the checkpoint store). `alive` is a host-id
    collection; default comes from DIST_MNIST_TPU_ALIVE_HOSTS, else every
    holder dir present is considered reachable."""
    root = Path(root).absolute()
    if not root.exists():
        return None
    if alive is None:
        alive = alive_hosts_from_env()
    catalog = _scan(root)
    if alive is not None:
        alive = {int(h) for h in alive}
        for step, by_src in catalog.items():
            for src in list(by_src):
                by_src[src] = [
                    f for f in by_src[src]
                    if int(f.parent.parent.name[1:]) in alive
                ]
    for step in sorted(catalog, reverse=True):
        if min_step is not None and step < min_step:
            break  # staler than the store's frontier: not worth assembling
        by_src = {s: fs for s, fs in catalog[step].items() if fs}
        if not by_src:
            continue
        got = _assemble(by_src, target_state)
        if got is not None:
            state, sources = got
            return state, step, sources
    return None


def _assemble(by_src: dict, target_state):
    """Fill `target_state`-shaped buffers from per-source npz files; None
    unless every element of every leaf is covered."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(target_state)
    targets = {}
    for path, leaf in flat:
        if isinstance(leaf, jax.Array):
            targets[_leaf_path_str(path)] = leaf
    # target leaf dtypes are already numpy-compatible dtype objects (jax
    # arrays carry np.dtype, extended dtypes via ml_dtypes)
    bufs = {p: np.empty(l.shape, dtype=l.dtype) for p, l in targets.items()}
    masks = {p: np.zeros(l.shape, dtype=bool) for p, l in targets.items()}
    sources = {}
    for src, files in sorted(by_src.items()):
        f = files[0]
        sources[src] = str(f.parent.parent.name)
        try:
            with np.load(f) as z:
                meta = json.loads(z["__meta__"].tobytes().decode("utf-8"))
                for leaf_meta in meta:
                    p = leaf_meta["path"]
                    if p not in bufs:
                        continue  # structure drift: extra leaf, ignore
                    buf, mask = bufs[p], masks[p]
                    if list(buf.shape) != list(leaf_meta["shape"]):
                        log.warning(
                            "peer shard %s has shape %s, target %s; "
                            "falling back to the store",
                            p, leaf_meta["shape"], list(buf.shape),
                        )
                        return None
                    for piece in leaf_meta["pieces"]:
                        idx = tuple(
                            slice(a, b) for a, b in
                            zip(piece["start"], piece["stop"])
                        )
                        data = z[piece["key"]]
                        buf[idx] = data.astype(buf.dtype, copy=False)
                        mask[idx] = True
        except (OSError, ValueError, KeyError) as err:
            log.warning("unreadable peer file %s (%s: %s)",
                        f, type(err).__name__, str(err)[:200])
            return None
    for p, mask in masks.items():
        if not mask.all():
            log.info("peer restore incomplete: leaf %s covered %.1f%%",
                     p, 100.0 * mask.mean())
            return None

    def place(path, leaf):
        if not isinstance(leaf, jax.Array):
            return leaf
        buf = bufs[_leaf_path_str(path)]
        return jax.make_array_from_callback(
            buf.shape, leaf.sharding,
            lambda idx, b=buf: np.asarray(b[idx]),
        )

    leaves = [place(path, leaf) for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves), sources
