"""Checkpoint/resume — Saver + CheckpointSaverHook + SessionManager restore,
rebuilt on Orbax/tensorstore (SURVEY.md §2.4 row 19, §3.5, §5.4), plus the
async write-behind layer (snapshot.py) and peer-ring redundancy (peer.py)
added by PR 11 (docs/RESILIENCE.md)."""

from dist_mnist_tpu.checkpoint.manager import CheckpointManager
from dist_mnist_tpu.checkpoint.peer import PeerReplicator, restore_from_peers
from dist_mnist_tpu.checkpoint.snapshot import AsyncSnapshotter, fork_state

__all__ = [
    "AsyncSnapshotter",
    "CheckpointManager",
    "PeerReplicator",
    "fork_state",
    "restore_from_peers",
]
