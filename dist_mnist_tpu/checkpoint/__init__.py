"""Checkpoint/resume — Saver + CheckpointSaverHook + SessionManager restore,
rebuilt on Orbax/tensorstore (SURVEY.md §2.4 row 19, §3.5, §5.4)."""

from dist_mnist_tpu.checkpoint.manager import CheckpointManager

__all__ = ["CheckpointManager"]
