"""Async snapshotting: take the checkpoint write off the step critical path.

The reference's chief saved synchronously inside the monitored-session
loop (SURVEY.md §3.5) — every cadence save stalled training for the full
host-gather + file write. Here the loop thread pays only for a
DONATION-SAFE ON-DEVICE FORK of the TrainState (`fork_state`: one
`jnp.copy` per leaf, dispatched asynchronously, no host gather — the next
step is free to donate the original buffers) plus a queue handoff; a
background writer owns the slow part (orbax serialization, commit marker,
peer replication).

Write-behind is BOUNDED: at most `window` snapshots may be
forked-but-not-durable at once (queued + in flight). A save that would
exceed the bound either blocks — the stall is attributed (``save_stall``
journal event, `save_stall_s` counter, and it lands in the caller's
`consume_save_s` goodput bucket since the block happens inside `save`) —
or drops the oldest QUEUED snapshot (``drop_oldest`` policy; the in-flight
write is never abandoned, so with an empty queue the new fork is admitted
with a transient one-snapshot overshoot rather than silently discarded).

Durability contract: `wait()` returns only after every accepted snapshot
is written AND committed (markers flushed — checkpoint/manager.py), and
re-raises the first writer error. `TrainLoop._honor_preemption` and
`CheckpointHook.end` already call save+wait, so preemption drain works
unchanged through this wrapper.

`AsyncSnapshotter` implements the CheckpointManager protocol (save /
restore / restore_or_init / wait / close / latest_step) and forwards
everything else to the wrapped manager, so it slots in as both
`TrainLoop.checkpoint_manager` and `CheckpointHook`'s manager. With a
`PeerReplicator` attached, the writer additionally serializes the local
shards to the peer ring after each durable write, and `restore()` tries
peer assembly (memory/local-disk speed) before the store — see
checkpoint/peer.py.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp

from dist_mnist_tpu.obs import events

log = logging.getLogger(__name__)

#: writer threads are named <prefix>-<n> so tests can assert none leak
THREAD_NAME_PREFIX = "SnapshotWriter"

_POLICIES = ("block", "drop_oldest")


def fork_state(state):
    """Device-side copy of every jax.Array leaf of `state`.

    `jnp.copy` dispatches asynchronously and allocates fresh buffers, so
    the fork is safe against the train step's buffer donation: the loop
    may donate/overwrite the ORIGINAL state the moment this returns,
    while the background writer reads the fork at its leisure. Shardings
    are preserved leaf-by-leaf. No host transfer happens here — that cost
    stays on the writer thread (orbax reads addressable shards there)."""
    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, state
    )


class AsyncSnapshotter:
    """Bounded write-behind checkpointing over a `CheckpointManager`.

    Parameters
    ----------
    manager:
        The durable store (CheckpointManager, possibly fault-wrapped).
        Constructed with ``async_save=False`` is fine — asyncness is owned
        by this layer's writer thread, and a synchronous inner write makes
        the commit marker land in the same writer pass.
    window:
        Max snapshots forked-but-not-durable at once (>= 1).
    policy:
        ``"block"`` (default) or ``"drop_oldest"`` — what `save` does when
        the window is full.
    peer:
        Optional `PeerReplicator` for ring redundancy + peer-first restore.
    """

    def __init__(self, manager, *, window: int = 1, policy: str = "block",
                 peer=None):
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}: {policy!r}")
        if policy == "drop_oldest" and jax.process_count() > 1:
            # Drops are decided by LOCAL queue occupancy; two processes can
            # drop different steps, and the store's per-step cross-process
            # barriers then wait on a save that one side will never issue.
            log.warning("drop_oldest is unsafe with %d processes "
                        "(divergent drops desync the store's per-step "
                        "barriers); using policy=block",
                        jax.process_count())
            policy = "block"
        self._inner = manager
        self._peer = peer
        self._window = max(1, int(window))
        self._policy = policy
        self._cond = threading.Condition()
        self._q: deque = deque()  # (step, forked_state, dispatch_ts)
        self._busy = False        # writer holds an item (popped, not durable)
        self._stop = False
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self._last_step: int | None = None
        #: attributed write-behind stalls (block policy) / drops
        self.save_stall_s = 0.0
        self.dropped = 0

    # -- manager protocol ---------------------------------------------------

    def save(self, state) -> bool:
        """Fork + enqueue; never writes on the caller's thread.

        Returns True when a snapshot was accepted (the usual case — the
        fork itself cannot be deduped against a write that hasn't happened
        yet, so dedupe is by step against this layer's own history)."""
        if self._error is not None:
            raise RuntimeError("snapshot writer failed") from self._error
        step = state.step_int
        if step == self._last_step:
            return False
        t0 = time.monotonic()
        fork = fork_state(state)
        events.emit("snapshot_fork", step=int(step),
                    fork_ms=round((time.monotonic() - t0) * 1e3, 3))
        stall = 0.0
        with self._cond:
            while len(self._q) + (1 if self._busy else 0) >= self._window:
                if self._policy == "drop_oldest":
                    if not self._q:
                        break  # only the in-flight write remains: overshoot
                    dropped_step, _, _ = self._q.popleft()
                    self.dropped += 1
                    events.emit("snapshot_drop", step=int(dropped_step))
                    continue
                t_stall = time.monotonic()
                self._cond.wait(timeout=0.05)
                stall += time.monotonic() - t_stall
                if self._error is not None:
                    self.save_stall_s += stall
                    raise RuntimeError(
                        "snapshot writer failed") from self._error
            self._q.append((int(step), fork, t0))
            self._last_step = step
            self._cond.notify_all()
        if stall > 0.0:
            self.save_stall_s += stall
            events.emit("save_stall", step=int(step),
                        stall_ms=round(stall * 1e3, 3))
        self._ensure_thread()
        return True

    def restore(self, target_state):
        """Peer-first restore: assemble from the ring when it has a step at
        least as fresh as the store's committed frontier, else (peer gone,
        stale, or incomplete) fall through to the store ladder.

        Drains the write-behind queue first: the freshest pre-failure
        snapshot must be durable before deciding where to restore from
        (this also keeps fault-injected corrupt-at-restore deterministic —
        the corruptor targets a settled latest step, not a racing write)."""
        self.wait()
        if self._peer is not None:
            try:
                store_step = self._inner.latest_step()
            except Exception:
                store_step = None
            t0 = time.monotonic()
            try:
                got = self._peer.restore(target_state, min_step=store_step)
            except Exception as err:  # peer is redundancy, never fatal
                log.warning("peer restore failed (%s: %s); using the store",
                            type(err).__name__, str(err)[:200])
                got = None
            if got is not None:
                restored, step, sources = got
                events.emit(
                    "peer_restore", step=int(step),
                    dur_ms=round((time.monotonic() - t0) * 1e3, 3),
                    sources=sources,
                )
                log.info("restored step %d from peer ring (sources=%s)",
                         step, sources)
                return restored
        return self._inner.restore(target_state)

    def restore_or_init(self, init_state):
        restored = self.restore(init_state)
        return (restored, True) if restored is not None else (init_state, False)

    def latest_step(self, *, refresh: bool = False):
        return self._inner.latest_step(refresh=refresh)

    def wait(self) -> None:
        """Drain: every accepted snapshot durable + committed (peer writes
        included) before return. Re-raises the first writer error."""
        with self._cond:
            while self._q or self._busy:
                self._cond.wait(timeout=0.05)
        self._inner.wait()
        if self._error is not None:
            raise RuntimeError("snapshot writer failed") from self._error

    def consume_save_stall_s(self) -> float:
        """Drain the attributed stall counter (bench reporting)."""
        s, self.save_stall_s = self.save_stall_s, 0.0
        return s

    def close(self) -> None:
        try:
            with self._cond:
                while (self._q or self._busy) and self._error is None:
                    self._cond.wait(timeout=0.05)
        finally:
            with self._cond:
                self._stop = True
                self._cond.notify_all()
            if self._thread is not None:
                self._thread.join(timeout=10.0)
                self._thread = None
            if self._error is not None:
                log.error("snapshot writer error at close: %r", self._error)
            self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # -- writer thread ------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop,
                name=f"{THREAD_NAME_PREFIX}-{id(self) & 0xFFFF}",
                daemon=True,
            )
            self._thread.start()

    def _writer_loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait(timeout=0.1)
                if self._stop and not self._q:
                    return
                step, fork, dispatch_ts = self._q.popleft()
                self._busy = True
                self._cond.notify_all()
            try:
                # sync inner write + wait: when this returns, the step is
                # durable and its commit marker has landed (manager.wait
                # flushes markers), so `checkpoint_commit`'s dur_ms — back-
                # dated to the fork via dispatch_ts — spans dispatch→durable
                self._inner.save(fork, dispatch_ts=dispatch_ts)
                self._inner.wait()
                if self._peer is not None:
                    try:
                        self._peer.write(step, fork)
                    except Exception as err:  # redundancy only, never fatal
                        log.warning(
                            "peer replication of step %d failed (%s: %s)",
                            step, type(err).__name__, str(err)[:200],
                        )
            except BaseException as err:  # noqa: BLE001 — surfaced in wait()
                if self._error is None:
                    self._error = err
                log.error("snapshot write of step %d failed: %r", step, err)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()
