"""Device-mesh construction — the SPMD replacement for ClusterSpec.

The reference maps work to processes by name (`{"ps": [...], "worker": [...]}`,
server_lib.py:242) and places ops with replica_device_setter
(device_setter.py:128-223). Here the topology is a logical `Mesh` with named
axes, and placement is a `PartitionSpec` per array (see parallel/sharding.py).

Axes (any may be size 1 and is then squeezed out of collectives by XLA):
- ``data``  — data parallelism; gradients are all-reduced over it.
- ``model`` — tensor parallelism; weight matrices are sharded over it.
- ``seq``   — sequence/context parallelism (ring attention, all-to-all).
- ``pipe``  — pipeline parallelism; layer stages are sharded over it
  (GPipe microbatch schedule, parallel/pipeline.py).
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
AXES = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. ``data=-1`` means "all remaining devices"."""

    data: int = -1
    model: int = 1
    seq: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int]:
        fixed = self.model * self.seq * self.pipe
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"model*seq*pipe={fixed}"
                )
            data = n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{self.model}x{self.seq}x{self.pipe} != "
                f"{n_devices} devices"
            )
        return (data, self.model, self.seq, self.pipe)


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """The whole-cluster topology description.

    Counterpart of the reference's flag triple
    (``--ps_hosts --worker_hosts --task_index``, SURVEY.md §0.1): here a
    cluster is processes × local devices, with no job-name distinction —
    every process runs the same SPMD program (process 0 is "chief" only for
    host-side side effects: logging, checkpoint writes).
    """

    mesh: MeshSpec = MeshSpec()
    coordinator_address: str | None = None  # host:port of process 0, multi-host only
    num_processes: int = 1
    process_id: int = 0

    @property
    def is_multihost(self) -> bool:
        return self.num_processes > 1


def device_count() -> int:
    return jax.device_count()


def slice_count(devices: Sequence[jax.Device]) -> int:
    """Number of distinct TPU slices among `devices` (1 when the backend
    doesn't expose `slice_index` — CPU, single slice, older libtpu)."""
    idx = {getattr(d, "slice_index", None) for d in devices}
    return 1 if None in idx else max(len(idx), 1)


class _SliceFacade:
    """Proxy device carrying a synthetic ``slice_index`` over a real device.

    Lets the multislice (DCN) layout path run on hardware that has no
    slices: `mesh_utils.create_hybrid_device_mesh` only reads attributes
    (`slice_index` to group granules, `platform`/`device_kind` for layout),
    so a facade is indistinguishable from a multislice device during layout.
    `make_mesh` unwraps facades before building the Mesh, so the resulting
    mesh executes on the real underlying devices.
    """

    __slots__ = ("_device", "slice_index")

    def __init__(self, device, slice_index: int):
        object.__setattr__(self, "_device", device)
        object.__setattr__(self, "slice_index", slice_index)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_device"), name)

    def __repr__(self):
        return f"SliceFacade(slice={self.slice_index}, {self._device!r})"


def with_fake_slices(devices: Sequence[jax.Device], n_slices: int) -> list:
    """Tag `devices` with synthetic slice indices (contiguous blocks) so
    `make_mesh` takes the hybrid ICI×DCN branch without multislice hardware.
    The driver's `dryrun_multichip` and the multislice tests use this to
    execute `create_hybrid_device_mesh` placements on CPU devices."""
    devices = list(devices)
    if n_slices < 1 or len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices"
        )
    per = len(devices) // n_slices
    return [_SliceFacade(d, i // per) for i, d in enumerate(devices)]


def _unwrap_facades(dev_array: np.ndarray) -> np.ndarray:
    unwrap = lambda d: d._device if isinstance(d, _SliceFacade) else d
    return np.vectorize(unwrap, otypes=[object])(dev_array)


def hybrid_mesh_shapes(
    shape: tuple[int, int, int, int], num_slices: int
) -> tuple[tuple[int, int, int, int], tuple[int, int, int, int]] | None:
    """Factor a resolved (data, model, seq, pipe) shape into per-slice ICI
    and cross-slice DCN shapes for `mesh_utils.create_hybrid_device_mesh`.

    The DCN factor goes on the DATA axis when it divides it (gradient
    all-reduce tolerates DCN latency — hierarchical psum: reduce-scatter
    inside each slice over ICI, all-reduce partials across slices over DCN,
    all-gather back over ICI), else on the PIPE axis (GPipe activation
    point-to-point is likewise DCN-tolerant). model/seq collectives are
    latency-critical and always stay inside a slice. Returns None when
    neither axis can absorb the slice count — caller decides the fallback.
    """
    data, model, seq, pipe = shape
    if data % num_slices == 0:
        return (data // num_slices, model, seq, pipe), (num_slices, 1, 1, 1)
    if pipe % num_slices == 0:
        return (data, model, seq, pipe // num_slices), (1, 1, 1, num_slices)
    # split the slice factor across BOTH DCN-tolerant axes (e.g. 4 slices
    # over data=2, pipe=2)
    d = math.gcd(data, num_slices)
    rest = num_slices // d
    if d > 1 and pipe % rest == 0:
        return (data // d, model, seq, pipe // rest), (d, 1, 1, rest)
    return None


def make_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    axis_names: tuple[str, ...] = AXES,
) -> Mesh:
    """Build a named device mesh.

    Uses ``jax.experimental.mesh_utils`` device ordering when available so
    that the ``data`` axis rides the slowest links and ``model``/``seq``
    (which carry per-step collectives with tighter latency needs) ride
    contiguous ICI neighbours. On a multislice topology (devices report
    distinct ``slice_index``), the mesh is hybrid: the data axis's
    cross-slice factor is laid out over DCN and everything else stays
    inside a slice (`hybrid_mesh_shapes`).
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    if spec.data != -1:
        # fully-specified mesh may use a subset of visible devices (e.g. the
        # 4-way config on an 8-device host — ≙ a worker_hosts list shorter
        # than the machine pool)
        want = spec.data * spec.model * spec.seq * spec.pipe
        if want > len(devices):
            raise ValueError(
                f"mesh needs {want} devices, only {len(devices)} visible"
            )
        devices = devices[:want]
    shape = spec.resolve(len(devices))
    # Squeeze trailing singleton axes out of the mesh? No — keep all four
    # axes so PartitionSpecs are uniform across configs; XLA elides
    # collectives over size-1 axes.
    n_slices = slice_count(devices)
    hybrid = hybrid_mesh_shapes(shape, n_slices) if n_slices > 1 else None
    if n_slices > 1 and hybrid is None:
        # neither DCN-tolerant axis (data, pipe) can absorb the slice
        # count: the mesh is still legal, but model/seq collectives will
        # cross DCN — build it, loudly
        log.warning(
            "mesh %s cannot place the %d-slice DCN factor on the data or "
            "pipe axis; latency-critical collectives may cross DCN",
            dict(zip(axis_names, shape)), n_slices,
        )
    try:
        from jax.experimental import mesh_utils

        if hybrid is not None:
            ici_shape, dcn_shape = hybrid
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices
            )
        else:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, NotImplementedError, RuntimeError, AssertionError) as exc:
        # AssertionError included: mesh_utils' TPU physical-topology walk
        # asserts cuboid/contiguous device sets, which a devices[:want]
        # prefix subset (the supported "4-way config on an 8-device host"
        # case) can violate
        # topology-aware layout can reject unusual shapes/backends; the
        # enumeration-order fallback is correct but may be slow (wrong axes
        # on the slow links) — never take it silently
        log.warning(
            "topology-aware mesh layout failed (%s); falling back to "
            "enumeration order%s",
            exc,
            " — MULTISLICE topology: per-step collectives may cross DCN"
            if n_slices > 1 else "",
        )
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(_unwrap_facades(dev_array), axis_names=axis_names)


def local_batch_slice(global_batch: int, mesh: Mesh) -> tuple[int, int]:
    """(per-process batch, per-device batch) for a global batch size.

    The reference's ``--batch_size`` was *per worker* (SURVEY.md §0.1 row
    batch_size); our configs state the *global* batch and shard it over the
    ``data`` axis. This helper gives each process its slice for host-side
    loading (`jax.make_array_from_process_local_data` consumes it).
    """
    data = mesh.shape[DATA_AXIS]
    if global_batch % data != 0:
        raise ValueError(f"global batch {global_batch} % data axis {data} != 0")
    per_device = global_batch // data
    n_proc = jax.process_count()
    if global_batch % n_proc != 0:
        raise ValueError(f"global batch {global_batch} % processes {n_proc} != 0")
    return global_batch // n_proc, per_device


def activate(mesh: Mesh):
    """Context manager installing `mesh` as the ambient mesh
    (`jax.set_mesh`): mesh-adaptive code (parallel/ring_attention.ring_
    attention) discovers it via `ambient_mesh()` below, and raw
    PartitionSpecs become accepted wherever a sharding is expected. The
    plain `with mesh:` context does NOT set the abstract mesh on jax>=0.5
    — use this. On older jax (no `jax.set_mesh`) the plain context IS the
    discovery mechanism `ambient_mesh` falls back to, so this degrades to
    it."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def ambient_mesh():
    """The ambient (activated) mesh, or None.

    jax>=0.5: `jax.sharding.get_abstract_mesh()`. Older jax: the `with
    mesh:` context's physical mesh from the thread-local resource env —
    the same thread-local `activate` degrades to there, so mesh-adaptive
    modules (flash/moe/vit) discover the mesh identically on both."""
    try:
        from jax.sharding import get_abstract_mesh
    except ImportError:  # jax<0.5
        from jax._src.mesh import thread_resources

        m = thread_resources.env.physical_mesh
        return None if m.empty else m
    return get_abstract_mesh()


def compat_shard_map(f, *, mesh, in_specs, out_specs):
    """`jax.shard_map` across jax versions: the public `jax.shard_map`
    (jax>=0.6, `check_vma=`) when present, else the experimental one
    (`check_rep=`). Both flags off: the mesh-adaptive callers close over
    collectives whose replication jax cannot always infer."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


def compat_axis_size(axis_name) -> int:
    """Static mapped-axis size inside shard_map, across jax versions:
    `lax.axis_size` (jax>=0.6) when present, else the axis-env frame
    (which IS the size — an int — on jax 0.4/0.5). Static because callers
    use it in shapes (per-device head counts, ring steps)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return getattr(frame, "size", frame)


def validate_mesh(mesh: Mesh) -> None:
    n = math.prod(mesh.devices.shape)
    if n != len(np.unique([d.id for d in mesh.devices.flat])):
        raise ValueError("mesh contains duplicate devices")
