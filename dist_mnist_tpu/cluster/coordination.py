"""Multi-host bootstrap and chief election.

Replaces the reference's process-bootstrap path: `tf.train.Server` startup
(server_lib.py:107-146 → GrpcServer, grpc_server_lib.h:78-239) and the
implicit "chief = worker task 0" convention (SURVEY.md §0.1 step 4).

In the SPMD model there is exactly one control-plane service — the TSL
coordination service reached through `jax.distributed.initialize` — and it
does only bootstrap, health (heartbeats), and barrier duty over DCN. All
tensor traffic is in-program XLA collectives over ICI (SURVEY.md §5.8).
"""

from __future__ import annotations

import logging

import jax

log = logging.getLogger(__name__)

_initialized = False


def force_platform(platform: str) -> None:
    """Pin the jax backend BEFORE first use (must precede any jax op).

    Needed because site hooks in hosted images may pre-select an
    accelerator platform; tests and the CPU-simulated cluster
    (`cli/launch.py --platform=cpu`) must win that fight in-process —
    the JAX_PLATFORMS env var alone can be overridden by such hooks.
    """
    jax.config.update("jax_platforms", platform)


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    platform: str | None = None,
    host_device_count: int | None = None,
) -> None:
    """Connect this process to the cluster (no-op single-process).

    Counterpart of `tf.train.Server(cluster, job_name, task_index)` — but
    symmetric: there is no ps/worker split and nothing to `join()`; the
    coordination service (heartbeats, "Unavailable: Heartbeat timeout"
    semantics — coordination_service_agent.h:358-365 lineage) detects dead
    peers instead of the PS surviving them.

    `platform="cpu"` additionally selects gloo for cross-process CPU
    collectives, so an N-process cluster can be exercised on one machine
    with no accelerator — the analogue of the reference's
    `create_local_cluster` in-process gRPC servers (SURVEY.md §4), but as
    real OS processes.
    """
    global _initialized
    if _initialized:
        return
    if platform:
        force_platform(platform)
    if host_device_count:
        if platform in (None, "cpu"):
            # N virtual host devices in THIS process (multi-device configs
            # on the CPU backend without the launcher, e.g.
            # `--platform=cpu --host_device_count=8`); must precede backend
            # init. Only the cpu backend reads this setting. The config
            # option only exists on jax>=0.5; older jax takes the same
            # value through XLA_FLAGS (also read at backend init).
            try:
                jax.config.update("jax_num_cpu_devices", host_device_count)
            except AttributeError:
                import os

                flag = (f"--xla_force_host_platform_device_count="
                        f"{host_device_count}")
                prev = os.environ.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in prev:
                    os.environ["XLA_FLAGS"] = f"{prev} {flag}".strip()
        else:
            log.warning(
                "--host_device_count only applies to the cpu backend; "
                "ignored for platform=%s", platform,
            )
    if coordinator_address is None and (num_processes is None or num_processes <= 1):
        log.info("single-process run; skipping jax.distributed.initialize")
        _initialized = True
        return
    if platform == "cpu":
        # cross-process collectives on the CPU backend need an explicit
        # transport; gloo ships in jaxlib
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "distributed init: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def is_chief() -> bool:
    """Process 0 is chief — it owns host-side side effects (checkpoint
    writes, summary files), mirroring `is_chief = (task_index == 0)` in the
    reference (SURVEY.md §0.1 step 4). Unlike the reference chief it does NOT
    own variable init: params are materialized identically on all processes
    from the same seed, and restore is collective (checkpoint/manager.py)."""
    return jax.process_index() == 0
