"""Cluster topology: device meshes, multi-host bootstrap, and elastic
membership.

Replaces the reference's cluster layer (SURVEY.md §2.2):
- `tf.train.ClusterSpec` (server_lib.py:242-493) — a job→task→address map —
  becomes `ClusterConfig` + a `jax.sharding.Mesh` over the visible devices.
- `tf.train.Server` (server_lib.py:107-239, TF_NewServer → GrpcServer) — the
  per-process gRPC server whose `join()` was the whole PS main loop — has no
  equivalent: there are no parameter servers. Multi-host control plane is
  `jax.distributed.initialize` (the TSL coordination service, the direct
  descendant of coordination_service_agent.h — SURVEY.md §2.5 row 29).
- `Membership` (membership.py) is the elastic-generation ledger the
  supervisor uses to decide shrink/grow (docs/RESILIENCE.md).

Exports resolve lazily (PEP 562): `cli/launch.py` — a jax-free process
supervisor — imports `cluster.membership`, and importing this package
eagerly would drag `cluster.mesh`'s jax import into it.
"""

from __future__ import annotations

_EXPORTS = {
    "ClusterConfig": "dist_mnist_tpu.cluster.mesh",
    "MeshSpec": "dist_mnist_tpu.cluster.mesh",
    "make_mesh": "dist_mnist_tpu.cluster.mesh",
    "activate": "dist_mnist_tpu.cluster.mesh",
    "local_batch_slice": "dist_mnist_tpu.cluster.mesh",
    "device_count": "dist_mnist_tpu.cluster.mesh",
    "force_platform": "dist_mnist_tpu.cluster.coordination",
    "initialize_distributed": "dist_mnist_tpu.cluster.coordination",
    "is_chief": "dist_mnist_tpu.cluster.coordination",
    "ENV_HOST_ID": "dist_mnist_tpu.cluster.membership",
    "Membership": "dist_mnist_tpu.cluster.membership",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
