"""Cluster topology: device meshes and multi-host bootstrap.

Replaces the reference's cluster layer (SURVEY.md §2.2):
- `tf.train.ClusterSpec` (server_lib.py:242-493) — a job→task→address map —
  becomes `ClusterConfig` + a `jax.sharding.Mesh` over the visible devices.
- `tf.train.Server` (server_lib.py:107-239, TF_NewServer → GrpcServer) — the
  per-process gRPC server whose `join()` was the whole PS main loop — has no
  equivalent: there are no parameter servers. Multi-host control plane is
  `jax.distributed.initialize` (the TSL coordination service, the direct
  descendant of coordination_service_agent.h — SURVEY.md §2.5 row 29).
"""

from dist_mnist_tpu.cluster.mesh import (
    ClusterConfig,
    MeshSpec,
    make_mesh,
    activate,
    local_batch_slice,
    device_count,
)
from dist_mnist_tpu.cluster.coordination import (
    force_platform,
    initialize_distributed,
    is_chief,
)

__all__ = [
    "ClusterConfig",
    "MeshSpec",
    "make_mesh",
    "activate",
    "local_batch_slice",
    "device_count",
    "force_platform",
    "initialize_distributed",
    "is_chief",
]
