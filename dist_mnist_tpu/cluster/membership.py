"""Elastic cluster membership: which hosts may join the next generation.

The supervisor (`cli/launch.py --elastic`) treats each launch generation as
a membership snapshot: host ids are STABLE labels 0..N-1 assigned at
supervisor start, while process ranks are assigned per generation by
position in the surviving-host list. Host 0 is the chief; its death is
always fatal (it owns the run directory and checkpoint commits), so host 0
can never be excluded here.

A host that dies abnormally is `fail()`ed out of the membership, optionally
with a recovery deadline (`recover_after_s` wall seconds from the failure).
`due(now)` lists hosts whose deadline has passed — the supervisor restores
them at the next generation boundary and grows the mesh back. A deadline of
None means the host never auto-recovers (permanent loss, e.g. a seeded
`kill_host` fault with no planned recovery).

Time is always injected (`now`) so the resize/regrow decision sequence is
unit-testable without sleeping.
"""

from __future__ import annotations

# Per-child env var carrying the stable host id across generations (the
# rank, by contrast, is positional and changes when the mesh resizes).
ENV_HOST_ID = "DIST_MNIST_TPU_HOST_ID"

# Per-generation env var: comma-separated stable host ids admitted to THIS
# generation (the supervisor's membership.alive() at launch). Children use
# it to decide which peer-ring replica dirs are reachable after a shrink —
# a dead host's local disk is gone with it (checkpoint/peer.py).
ENV_ALIVE_HOSTS = "DIST_MNIST_TPU_ALIVE_HOSTS"


def ring_peer(host: int, hosts) -> int | None:
    """The ring neighbor that holds `host`'s replica shards: the next id in
    the sorted host list, wrapping. None when `host` is alone (a 1-host
    world has no distinct peer) or not a member."""
    ring = sorted(set(hosts))
    if host not in ring or len(ring) < 2:
        return None
    return ring[(ring.index(host) + 1) % len(ring)]


class Membership:
    """Tracks alive/excluded hosts and their recovery deadlines."""

    def __init__(self, num_hosts: int):
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        self.num_hosts = num_hosts
        # host id -> recovery deadline (absolute seconds) or None = never
        self._excluded: dict[int, float | None] = {}

    # -- queries ----------------------------------------------------------

    def alive(self) -> list[int]:
        """Sorted host ids eligible for the next generation."""
        return [h for h in range(self.num_hosts) if h not in self._excluded]

    @property
    def world_size(self) -> int:
        return self.num_hosts - len(self._excluded)

    def is_alive(self, host: int) -> bool:
        return 0 <= host < self.num_hosts and host not in self._excluded

    def rank_of(self, host: int) -> int | None:
        """Positional rank of `host` in the next generation (None if dead)."""
        alive = self.alive()
        return alive.index(host) if host in alive else None

    def due(self, now: float) -> list[int]:
        """Excluded hosts whose recovery deadline has passed."""
        return sorted(
            h
            for h, deadline in self._excluded.items()
            if deadline is not None and now >= deadline
        )

    def next_recovery_in(self, now: float) -> float | None:
        """Seconds until the earliest pending recovery (None if nothing
        will ever recover). Clamped at 0 for already-due hosts."""
        deadlines = [d for d in self._excluded.values() if d is not None]
        if not deadlines:
            return None
        return max(0.0, min(deadlines) - now)

    # -- transitions ------------------------------------------------------

    def fail(
        self, host: int, *, now: float, recover_after_s: float | None = None
    ) -> None:
        """Exclude `host` from future generations.

        `recover_after_s` schedules automatic re-admission that many wall
        seconds from `now`; None means the host stays out until an explicit
        `restore()`.
        """
        if host == 0:
            raise ValueError("host 0 is the chief and cannot be excluded")
        if not 0 <= host < self.num_hosts:
            raise ValueError(f"host {host} out of range [0, {self.num_hosts})")
        self._excluded[host] = (
            None if recover_after_s is None else now + recover_after_s
        )

    def restore(self, host: int) -> None:
        """Re-admit a host (no-op if already alive)."""
        self._excluded.pop(host, None)

    def restore_due(self, now: float) -> list[int]:
        """Re-admit every host whose deadline has passed; returns them."""
        due = self.due(now)
        for h in due:
            self.restore(h)
        return due

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Membership(alive={self.alive()}, "
            f"excluded={sorted(self._excluded)})"
        )
