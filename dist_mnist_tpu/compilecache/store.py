"""Serialized-AOT-executable store + the XLA persistent-cache switch.

The store is deliberately dumb durable storage: one file per key, atomic
replace on write, every read failure (missing, truncated, corrupt pickle,
incompatible serialized executable) degrades to a MISS — the caller
recompiles and overwrites. The interesting contract is the KEY: callers
must fold in everything that changes the compiled program (see
`cache_key`); jax/jaxlib/backend versions are folded in automatically so
an upgraded runtime can never deserialize a stale binary.

Entry format: pickle of ``{"exe": bytes, "in_tree": PyTreeDef,
"out_tree": PyTreeDef, "meta": dict}`` — the three values
`jax.experimental.serialize_executable.serialize` returns, plus
provenance (compile wall ms, jax version) so a warm load can report how
much compile time it saved (`compile_ms_saved`).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time
from pathlib import Path

from dist_mnist_tpu.obs import events

log = logging.getLogger(__name__)

#: suffix for store entries (one serialized executable each)
ENTRY_SUFFIX = ".jaxexe"
#: prefix of in-flight atomic-write temp files (conftest leak check)
TMP_PREFIX = ".tmp-"
#: temp files currently being written, for the test-suite leak check —
#: a non-empty set after a test means some save path skipped its finally
_PENDING_TMP: set = set()


def cache_key(fields: dict) -> str:
    """Stable hex key over `fields` + the runtime's own identity.

    `fields` must contain everything that changes the compiled program:
    model config, mesh shape, sharding strategy, dtype, donation, scan
    chunk, batch geometry. The jax/jaxlib versions and active backend are
    merged in automatically (a serialized executable is only valid on the
    runtime that produced it); pass the same names explicitly to override
    — tests use this to pin cross-version invalidation.
    """
    import jax
    import jaxlib

    full = {
        "jax_version": jax.__version__,
        "jaxlib_version": getattr(jaxlib.version, "__version__", "unknown"),
        "backend": jax.default_backend(),
        **fields,
    }
    blob = json.dumps(full, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def enable_persistent_cache(directory, *, min_compile_secs: float = 0.5) -> None:
    """Point JAX's persistent compilation cache at `directory` (the
    XLA-level warm-start tier — transparent to every jit in the process).
    Best-effort: an older jax without the knobs just stays cold."""
    try:
        import jax

        Path(directory).mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(directory))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs
        )
    except Exception as e:  # noqa: BLE001 — warm-start aid, never fatal
        log.warning("persistent compilation cache unavailable: %s", e)


class ExecutableStore:
    """key -> serialized AOT executable on disk, with hit/miss/corrupt
    counters and load-vs-compile wall-time attribution.

    Thread-safe; failure-soft on BOTH sides: `load` returns None on any
    problem (the caller compiles fresh and `save` overwrites the bad
    entry), `save` logs and returns 0 instead of raising — a full disk
    must not kill a training run that was going to compile anyway."""

    def __init__(self, directory):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._stats = {
            "hits": 0,
            "misses": 0,
            "corrupt": 0,
            "bytes_read": 0,
            "bytes_written": 0,
            "load_ms": 0.0,
            "save_ms": 0.0,
            # compile wall time the hits avoided, as recorded by whoever
            # saved the entry (meta["compile_ms"]) — the warm-start win
            "compile_ms_saved": 0.0,
        }

    def _path(self, key: str) -> Path:
        return self.dir / f"{key}{ENTRY_SUFFIX}"

    def load(self, key: str):
        """The deserialized executable for `key`, or None on miss OR on any
        corrupt/unreadable/incompatible entry (which is deleted so the
        subsequent `save` starts clean)."""
        from jax.experimental import serialize_executable

        path = self._path(key)
        t0 = time.perf_counter()
        try:
            blob = path.read_bytes()
        except OSError:
            with self._lock:
                self._stats["misses"] += 1
            events.emit("compile_cache", outcome="miss", key=key)
            return None
        try:
            entry = pickle.loads(blob)
            exe = serialize_executable.deserialize_and_load(
                entry["exe"], entry["in_tree"], entry["out_tree"]
            )
        except Exception as e:  # noqa: BLE001 — corrupt entry => recompile
            log.warning(
                "compile-cache entry %s unreadable (%s: %s); treating as a "
                "miss and removing it", path.name, type(e).__name__, e,
            )
            try:
                path.unlink()
            except OSError:
                pass
            with self._lock:
                self._stats["corrupt"] += 1
                self._stats["misses"] += 1
            events.emit("compile_cache", outcome="corrupt", key=key)
            return None
        dt_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._stats["hits"] += 1
            self._stats["bytes_read"] += len(blob)
            self._stats["load_ms"] += dt_ms
            self._stats["compile_ms_saved"] += float(
                entry.get("meta", {}).get("compile_ms", 0.0)
            )
        events.emit("compile_cache", outcome="hit", key=key,
                    load_ms=round(dt_ms, 3))
        return exe

    def save(self, key: str, compiled, meta: dict | None = None) -> int:
        """Serialize `compiled` under `key` (atomic replace — a concurrent
        reader sees the old entry or the new one, never a torn write).
        Returns bytes written (0 on any failure)."""
        from jax.experimental import serialize_executable

        t0 = time.perf_counter()
        tmp = self.dir / f"{TMP_PREFIX}{key}-{os.getpid()}"
        try:
            payload, in_tree, out_tree = serialize_executable.serialize(compiled)
            import jax

            blob = pickle.dumps({
                "exe": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
                "meta": {"jax_version": jax.__version__, **(meta or {})},
            })
            _PENDING_TMP.add(tmp)
            try:
                tmp.write_bytes(blob)
                os.replace(tmp, self._path(key))
            finally:
                _PENDING_TMP.discard(tmp)
                if tmp.exists():
                    tmp.unlink()
        except Exception as e:  # noqa: BLE001 — warm-start aid, never fatal
            log.warning("compile-cache save %s failed (%s: %s); continuing "
                        "uncached", key, type(e).__name__, e)
            return 0
        with self._lock:
            self._stats["bytes_written"] += len(blob)
            self._stats["save_ms"] += (time.perf_counter() - t0) * 1e3
        events.emit("compile_cache", outcome="save", key=key,
                    bytes=len(blob))
        return len(blob)

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
        out["entries"] = len(list(self.dir.glob(f"*{ENTRY_SUFFIX}")))
        return out
