"""Cold-start attribution: where does process wall time go before the
first useful step?

`StartupClock` buckets the time from process start (or whatever `t0` the
caller anchors) into:

- ``import``     — module imports up to the driver's entry (cli/train.py
                   anchors t0 at its own module top, so this covers absl +
                   stdlib; jax's import lands in ``init``).
- ``init``       — backend/distributed bring-up, dataset load, model +
                   state build, sharding placement.
- ``restore``    — checkpoint restore at startup.
- ``compile``    — AOT compile OR executable-store load of the step
                   (train/step.py records it; the loop charges it, so a
                   warm start shows the load ms where a cold start shows
                   the compile ms).
- ``first_step`` — the residual: everything between t0 and the first
                   completed step not attributed above (first dispatch,
                   hook bring-up, lazy-jit compile when no store is wired).

``time_to_first_step_ms`` is the headline (`bench.py --coldstart`);
``unattributed_ms`` is wall time AFTER the first step not covered by the
buckets — by construction 0 until then, it exists so the snapshot always
sums honestly.

Stdlib-only, like faults/goodput.py: train/loop.py must stay importable
without jax.
"""

from __future__ import annotations

import contextlib
import time


class StartupClock:
    """Bucketed process-startup wall clock; feed via `phase`/`note`, freeze
    the headline with `first_step_done`, read with `snapshot`."""

    BUCKETS = ("import", "init", "restore", "compile", "first_step")

    def __init__(self, t0: float | None = None):
        self.t0 = time.monotonic() if t0 is None else t0
        self.buckets = {b: 0.0 for b in self.BUCKETS}
        self.time_to_first_step_s: float | None = None

    def note(self, bucket: str, seconds: float) -> None:
        self.buckets[bucket] += max(0.0, seconds)

    @contextlib.contextmanager
    def phase(self, bucket: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.note(bucket, time.monotonic() - t0)

    def first_step_done(self) -> None:
        """Freeze time-to-first-step (first call wins); the ``first_step``
        bucket becomes the residual over the attributed phases."""
        if self.time_to_first_step_s is None:
            self.time_to_first_step_s = time.monotonic() - self.t0

    def snapshot(self) -> dict:
        ttfs = self.time_to_first_step_s
        attributed = sum(
            v for b, v in self.buckets.items() if b != "first_step"
        )
        out = {f"{b}_ms": v * 1e3 for b, v in self.buckets.items()}
        if ttfs is not None:
            out["first_step_ms"] = max(0.0, ttfs - attributed) * 1e3
            out["time_to_first_step_ms"] = ttfs * 1e3
        return out


class StartupHook:
    """Publish `startup/*` and `compile_cache/*` once, at the first step.

    Same shape as the other observability hooks (hooks/builtin.py): reads
    host-side counters only, one batched scalars() call. The compile
    bucket is read off the loop's GoodputClock (train/loop.py charges AOT
    compile/store-load time there BEFORE after_step fires), so cold vs
    warm starts attribute truthfully without the hook knowing the step's
    internals. `last` keeps the published snapshot for bench harnesses."""

    def __init__(self, writer=None, clock: StartupClock | None = None, *,
                 store=None):
        self._writer = writer
        self.clock = clock or StartupClock()
        self._store = store
        self._loop = None
        self._published = False
        self.last: dict = {}

    def begin(self, loop) -> None:
        self._loop = loop

    def before_step(self, step: int) -> None:
        pass

    def after_step(self, step: int, state, outputs) -> None:
        if self._published:
            return
        self._published = True
        if self._loop is not None:
            # mirror the goodput clock's compile charge (AOT compile or
            # store load, whichever the warm-start tier produced)
            already = self.clock.buckets["compile"]
            self.clock.note(
                "compile", self._loop.goodput.compile_s - already
            )
        self.clock.first_step_done()
        snap = dict(self.clock.snapshot())
        if self._store is not None:
            snap.update(
                {f"cache_{k}": v for k, v in self._store.stats().items()}
            )
        self.last = snap
        if self._writer is not None:
            scalars = {
                f"startup/{k}": v for k, v in self.clock.snapshot().items()
            }
            if self._store is not None:
                scalars.update({
                    f"compile_cache/{k}": float(v)
                    for k, v in self._store.stats().items()
                })
            self._writer.scalars(scalars, step)

    def end(self, state) -> None:
        pass
