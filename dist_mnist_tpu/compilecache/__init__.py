"""Warm-start engine: durable compiled programs + startup attribution.

Two layers make compilation a once-per-program-change cost instead of a
once-per-process cost (docs/PERF.md "Cold start & warm restarts"):

- `enable_persistent_cache` turns on JAX's own persistent compilation
  cache (`jax_compilation_cache_dir`) — XLA-level, transparent, shared by
  every jit in the process.
- `ExecutableStore` is the explicit tier above it: serialized AOT
  executables (`jax.experimental.serialize_executable`) keyed by
  `cache_key(...)` over everything that changes the compiled program
  (model config, mesh shape, sharding strategy, dtype, donation, scan
  chunk, jax/backend version). A warm process deserializes in
  milliseconds instead of re-lowering + re-compiling; a corrupt entry is
  quarantined to a recompile + overwrite, never a crash.

`StartupClock`/`StartupHook` are the attribution side: process wall time
bucketed into import/init/restore/compile/first-step, published as
`startup/*` and `compile_cache/*` metrics so `bench.py --coldstart` and
restart generations (`cli/launch.py --max_restarts`) can show exactly
where cold-start time went and how much a warm start saved.
"""

from dist_mnist_tpu.compilecache.store import (
    ExecutableStore,
    cache_key,
    enable_persistent_cache,
)
from dist_mnist_tpu.compilecache.startup import StartupClock, StartupHook

__all__ = [
    "ExecutableStore",
    "StartupClock",
    "StartupHook",
    "cache_key",
    "enable_persistent_cache",
]
