"""`compile_cache_key_fields` — everything that changes the compiled
step program, as a flat dict. Lives here (not in cli/train.py, its
historical home) because non-train processes need the key builder too:
the tuner's geometry key (`tune/store.tuned_key_fields`) hashes these
fields from `cli/serve.py` and `python -m dist_mnist_tpu.tune`, and
importing cli/train.py from another absl CLI re-executes its
`flags.DEFINE_*` block — a DuplicateFlagError under `python -m`, a flag
collision (`--config` et al.) from serve. This module is import-pure:
no flags, no jax. cli/train.py re-exports the name, so
`from dist_mnist_tpu.cli.train import compile_cache_key_fields`
keeps working everywhere train is already imported.
"""

from __future__ import annotations

__all__ = ["compile_cache_key_fields"]


def compile_cache_key_fields(cfg, mesh, *, scan_chunk=0,
                             input_pipeline="python", quant="none"):
    """Everything that changes the compiled step program, as a flat dict —
    the ExecutableStore key is `cache_key({"kind": ..., **fields})`. The
    overlap knobs are in here so a cached serial executable can never be
    served to an overlapped run (or vice versa): the two lower to different
    HLO even though they are value-identical. `quant` likewise: an int8
    weight-only program takes (int8, scale) weight arguments, so it can
    never satisfy a float key (or vice versa); "none" keeps the field OUT
    of the payload entirely — every pre-quant disk key stays warm."""
    fields = {
        "config": cfg.name,
        "model": cfg.model,
        "model_kwargs": cfg.model_kwargs,
        "batch_size": cfg.batch_size,
        "optimizer": cfg.optimizer,
        "loss": cfg.loss,
        "remat": cfg.remat,
        "remat_policy": cfg.remat_policy,
        "augment": cfg.augment,
        "mesh": tuple(sorted(mesh.shape.items())),
        "sharding": cfg.sharding_rules,
        "overlap": cfg.overlap,
        "overlap_bucket_mb": cfg.overlap_bucket_mb,
        "overlap_chunk": cfg.overlap_chunk,
        "dtype": "float32",
        "donate": True,
        "scan_chunk": scan_chunk,
        "input_pipeline": input_pipeline,
        "prng": cfg.prng_impl,
        # the optimizer chain closes over these as Python scalars, so they
        # are constant-folded into the jitted update: a cached executable
        # from a different schedule/regularization would train wrong —
        # silently. Likewise dataset (input shapes) and
        # replicas_to_aggregate (accumulation loop structure).
        "dataset": cfg.dataset,
        "train_steps": cfg.train_steps,
        "learning_rate": cfg.learning_rate,
        "lr_schedule": cfg.lr_schedule,
        "warmup_steps": cfg.warmup_steps,
        "replicas_to_aggregate": cfg.replicas_to_aggregate,
        "grad_clip_norm": cfg.grad_clip_norm,
        "weight_decay": cfg.weight_decay,
    }
    if quant and quant != "none":
        fields["quant"] = quant
    return fields
