"""Metrics computed inside compiled steps (scalars come back as f32)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def correct_count(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.int32))


def topk_accuracy(logits: jax.Array, labels: jax.Array, k: int = 5) -> jax.Array:
    _, idx = jax.lax.top_k(logits, k)
    hit = jnp.any(idx == labels[:, None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))
