"""Loss functions.

Includes bit-comparable parity with the reference driver's clipped
cross-entropy (SURVEY.md §0.1 step 5:
``loss = -Σ y_·log(clip(softmax(logits), 1e-10, 1.0))``) alongside the
numerically-sound log-softmax form used by default.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def clipped_softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    *,
    reduction: str = "mean",
) -> jax.Array:
    """The reference's exact loss: explicit softmax, clip to [1e-10, 1], -Σ.

    Kept for numeric comparability with the upstream MLP config. ``labels``
    are integer class ids (one-hot happens here, matching
    ``read_data_sets(one_hot=True)`` feeding ``y_``).
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    logp = jnp.log(jnp.clip(probs, 1e-10, 1.0))
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    per_example = -jnp.sum(onehot * logp, axis=-1)
    return _reduce(per_example, reduction)


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    *,
    reduction: str = "mean",
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Stable log-softmax cross-entropy (default loss for all configs)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    n = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, n, dtype=jnp.float32)
    if label_smoothing:
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / n
    per_example = -jnp.sum(onehot * logp, axis=-1)
    return _reduce(per_example, reduction)


def l2_regularization(params, scale: float) -> jax.Array:
    leaves = jax.tree.leaves(params)
    return scale * sum(jnp.sum(jnp.square(a.astype(jnp.float32))) for a in leaves)


def _reduce(x: jax.Array, reduction: str) -> jax.Array:
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":  # the reference reduced with -Σ over the batch too
        return jnp.sum(x)
    if reduction == "none":
        return x
    raise ValueError(f"unknown reduction {reduction!r}")
