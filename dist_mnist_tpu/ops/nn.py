"""Functional NN layers: init fns returning param pytrees + pure apply fns.

Design rules (TPU-first):
- Params are float32; compute may run bfloat16 (`cast` at call sites) —
  matmuls/convs then hit the MXU at full rate while master weights keep
  f32 precision for the optimizer.
- All shapes static; no Python control flow on traced values.
- NHWC images, HWIO conv kernels (XLA:TPU's preferred layouts).

Initializers replicate the reference's
`truncated_normal(stddev=1/sqrt(fan_in))` (SURVEY.md §0.1 step 5) so the MLP
config is numerically comparable.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from dist_mnist_tpu.ops.quant import QuantizedArray, dequantize, q_dot

Params = dict


# ---------------------------------------------------------------------------
# initializers


def truncated_normal(key, shape, stddev: float, dtype=jnp.float32):
    """2-sigma truncated normal — same family as tf.truncated_normal used by
    the reference driver (§0.1 step 5)."""
    return stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


def fan_in_trunc_normal(key, shape, dtype=jnp.float32):
    fan_in = math.prod(shape[:-1])
    return truncated_normal(key, shape, 1.0 / (fan_in**0.5), dtype)


def he_normal(key, shape, dtype=jnp.float32):
    fan_in = math.prod(shape[:-1])
    return jax.random.normal(key, shape, dtype) * (2.0 / fan_in) ** 0.5


def xavier_uniform(key, shape, dtype=jnp.float32):
    fan_in = math.prod(shape[:-1])
    fan_out = int(shape[-1])
    limit = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(key, shape, dtype, -limit, limit)


# ---------------------------------------------------------------------------
# dense


def init_dense(key, in_dim: int, out_dim: int, *, init=fan_in_trunc_normal) -> Params:
    kw, _ = jax.random.split(key)
    return {"w": init(kw, (in_dim, out_dim)), "b": jnp.zeros((out_dim,))}


def dense(p: Params, x: jax.Array) -> jax.Array:
    w = p["w"]
    if isinstance(w, QuantizedArray):
        # weight-only int8 serve path: int8 is what HBM holds; q_dot
        # dispatches fused-Pallas vs XLA-materialize (ops/quant.py)
        return q_dot(x, w) + p["b"].astype(x.dtype)
    return x @ w.astype(x.dtype) + p["b"].astype(x.dtype)


# ---------------------------------------------------------------------------
# conv / pool


def init_conv(
    key, kh: int, kw: int, cin: int, cout: int, *, init=fan_in_trunc_normal
) -> Params:
    k, _ = jax.random.split(key)
    return {"w": init(k, (kh, kw, cin, cout)), "b": jnp.zeros((cout,))}


def conv2d(
    p: Params, x: jax.Array, *, stride: int = 1, padding: str = "SAME"
) -> jax.Array:
    w = p["w"]
    w = (dequantize(w, x.dtype) if isinstance(w, QuantizedArray)
         else w.astype(x.dtype))
    y = lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + p["b"].astype(x.dtype)


def max_pool(x: jax.Array, window: int = 2, stride: int | None = None) -> jax.Array:
    stride = stride or window
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        (1, window, window, 1),
        (1, stride, stride, 1),
        "VALID",
    )


def avg_pool(x: jax.Array, window: int = 2, stride: int | None = None) -> jax.Array:
    stride = stride or window
    summed = lax.reduce_window(
        x.astype(jnp.float32),
        0.0,
        lax.add,
        (1, window, window, 1),
        (1, stride, stride, 1),
        "VALID",
    )
    return (summed / (window * window)).astype(x.dtype)


def global_avg_pool(x: jax.Array) -> jax.Array:
    return jnp.mean(x.astype(jnp.float32), axis=(1, 2)).astype(x.dtype)


# ---------------------------------------------------------------------------
# normalization


def init_layer_norm(dim: int) -> Params:
    return {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}


def layer_norm(p: Params, x: jax.Array, *, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def init_batch_norm(dim: int) -> tuple[Params, Params]:
    """Returns (params, state): state carries EMA running stats (the mutable
    part — threaded through apply, never assigned in place)."""
    params = {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}
    state = {"mean": jnp.zeros((dim,)), "var": jnp.ones((dim,))}
    return params, state


def batch_norm(
    p: Params,
    state: Params,
    x: jax.Array,
    *,
    train: bool,
    momentum: float = 0.9,
    eps: float = 1e-5,
) -> tuple[jax.Array, Params]:
    """NHWC batch norm. Under jit with the batch dim sharded over `data`,
    the mean/var reductions become cross-replica (XLA inserts the all-reduce)
    — i.e. synchronized BN for free, where the reference had no BN at all."""
    xf = x.astype(jnp.float32)
    if train:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(xf, axes)
        var = jnp.var(xf, axes)
        new_state = {
            "mean": momentum * state["mean"] + (1 - momentum) * mean,
            "var": momentum * state["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = state["mean"], state["var"]
        new_state = state
    y = (xf - mean) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return y.astype(x.dtype), new_state


# ---------------------------------------------------------------------------
# regularization / activations


def dropout(key, x: jax.Array, rate: float, *, train: bool) -> jax.Array:
    if not train or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


relu = jax.nn.relu
gelu = jax.nn.gelu
softmax = jax.nn.softmax
log_softmax = jax.nn.log_softmax


# ---------------------------------------------------------------------------
# attention (used by ViT; the sharded/ring variants live in parallel/)


def init_attention(key, dim: int, num_heads: int) -> Params:
    ks = jax.random.split(key, 4)
    init = xavier_uniform
    del num_heads  # static; passed to multi_head_attention, not stored in params
    return {
        "qkv": {"w": init(ks[0], (dim, 3 * dim)), "b": jnp.zeros((3 * dim,))},
        "out": {"w": init(ks[1], (dim, dim)), "b": jnp.zeros((dim,))},
    }


def multi_head_attention(p: Params, x: jax.Array, num_heads: int,
                         mask: jax.Array | None = None) -> jax.Array:
    """[B, S, D] self-attention. Kept simple/fused-friendly; the Pallas flash
    kernel (ops/pallas) and ring attention (parallel/ring_attention.py) are
    drop-in replacements for the inner softmax(QK^T)V. `mask` [B, S] marks
    real tokens (serve-side right-padding, serve/zoo.py); None compiles the
    exact historical maskless program."""
    b, s, d = x.shape
    h = num_heads
    qkv = dense(p["qkv"], x).reshape(b, s, 3, h, d // h)
    q, k, v = jnp.moveaxis(qkv, 2, 0)  # each [B, S, H, Dh]
    out = dot_product_attention(q, k, v, mask=mask)
    return dense(p["out"], out.reshape(b, s, d))


def dot_product_attention(q, k, v, mask: jax.Array | None = None) -> jax.Array:
    """[B, S, H, Dh] -> [B, S, H, Dh]; accumulation in f32 for stability.

    `mask` [B, S_k] marks REAL keys: padded keys get -inf scores before the
    softmax, so no query (real or padded) attends to padding — padded
    QUERIES still produce garbage rows, which the caller must exclude from
    pooling/loss (ViT's masked pooling does). With mask=None the program is
    bit-identical to the historical maskless one.

    The result is tagged `checkpoint_name("attn_out")` so the `save_attn`
    remat policy (train/step.py REMAT_POLICIES) can keep it in HBM instead
    of recomputing the whole O(S^2) score/softmax/apply chain in the
    backward pass. Outside jax.checkpoint the tag is an identity no-op."""
    from jax.ad_checkpoint import checkpoint_name

    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        # [B, S_k] -> [B, 1, 1, S_k]; finite large-negative (not -inf) so a
        # fully-masked row still softmaxes to a uniform finite result
        logits = jnp.where(mask[:, None, None, :].astype(bool), logits,
                           jnp.float32(-1e30))
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return checkpoint_name(
        jnp.einsum("bhqk,bkhd->bqhd", weights, v), "attn_out"
    )


def flatten(x: jax.Array) -> jax.Array:
    return x.reshape(x.shape[0], -1)


def cast(tree, dtype):
    """Cast floating leaves of a pytree (compute-dtype policy entry point)."""
    return jax.tree.map(
        lambda a: a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a,
        tree,
    )
