"""Weight-only int8 quantization for serving: per-channel symmetric scales.

The serve-side memory budget (serve/engine.py `CompiledModelCache`) rations
resident bytes; a bf16/f32 checkpoint spends 2-4x more of that budget than
inference accuracy needs. This module provides the standard weight-only
answer: matmul/conv kernels live in HBM as int8 with float32 per-channel
scales, and the consuming contraction dequantizes on the fly — either the
fused Pallas kernel (`ops/pallas/quant_matmul.py`: int8 tiles streamed
from HBM, scales applied in registers, f32 accumulation; the TPU default)
or the XLA fallback that materializes a transient float copy inside the
traced matmul (`q_dot`/`q_einsum` pick per call, see `fused_matmul_mode`).
Activations, biases, norms, embeddings, and the MoE router gate stay
float.

Representation: `QuantizedArray`, a registered pytree-with-keys node whose
children are `(q: int8, scale: float32)` and whose aux data is the quant
mode. Being a pytree node (not an opaque object) is the load-bearing
choice: sharding trees, `jit` in_shardings, `device_put`, `lax.scan` over
stacked block params, `vmap` over expert stacks, shard_map pytree-prefix
specs, per-device byte accounting, and the engine's hot-swap shape checks
all traverse it with zero special cases.

Scale layout: the amax reduction runs over the CONTRACTION (second-to-
minor) axis only, keepdims — so a 2-D kernel [D, H] gets scales [1, H]
(classic per-output-channel), while stacked leaves keep their leading
dims: scan-stacked ViT blocks [L, D, 3D] -> [L, 1, 3D], MoE expert stacks
[E, D, H] -> [E, 1, H]. Leading dims surviving in the scale is what lets
`lax.scan`/`vmap` slice a QuantizedArray exactly like the float leaf it
replaced. Leaves with a zero-amax channel fall back to ONE shared
per-tensor scale (broadcast to the same keepdims shape so the slicing
contract holds); `mode` records which rule applied.

KV-cache traversal (serve decode, PR 20): the paged KV cache stores its
K/V page pools as QuantizedArray nodes with ``mode="kv_head"`` — int8
``[depth, pages, page_tokens, heads, head_dim]`` with f32 scales
``[..., heads, 1]``, i.e. the amax runs over the LAST axis (one scale
per token per head), produced by `quantize_kv` INSIDE the jitted decode
step (no host pulls — unlike `quantize`, which is load-time-only).
Because scales keep every leading dim, the engine's single
``P(None, None, None, model, None)`` TP spec shards q and scale as a
pytree prefix with no special case, and `dequantize`'s plain broadcast
multiply recovers float pages unchanged.

Hot-path discipline: everything here is jit-traceable except
`error_report` (one batched load-time `device_get`) and the degenerate-
scale check in `quantize` (a load-time scalar `bool`). This file is in
scripts/check_host_sync.py's lint scope.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

#: smallest representable scale — a zero-amax channel quantizes to q == 0
#: with this floor instead of dividing by zero
_EPS = 1e-12

#: int8 symmetric range is [-127, 127] (the -128 slot is unused so the
#: representable grid is symmetric around zero)
_QMAX = 127.0

#: param leaf names the default rule quantizes: dense/conv/attention
#: kernels ("w") and the MoE expert FFN stacks ("w1"/"w2"). Everything
#: else — biases, norm scale/bias, position/cls embeddings, and the MoE
#: router "gate" (router precision drives top-1 agreement) — stays float.
QUANT_LEAF_NAMES = ("w", "w1", "w2")


@jax.tree_util.register_pytree_with_keys_class
class QuantizedArray:
    """int8 weights + float32 per-channel scales, as one pytree node.

    `mode` is "channel" (per-output-channel scales) or "tensor" (one
    shared scale, broadcast — the degenerate-leaf fallback); it is aux
    data, so two QuantizedArrays with different modes are different
    pytree structures and can never silently share a compiled program.
    """

    __slots__ = ("q", "scale", "mode")

    def __init__(self, q, scale, mode: str = "channel"):
        self.q = q
        self.scale = scale
        self.mode = mode

    # --- array-protocol surface so shape checks / byte accounting work ---

    @property
    def shape(self):
        return jnp.shape(self.q)

    @property
    def ndim(self):
        return len(jnp.shape(self.q))

    @property
    def dtype(self):
        # the STORAGE dtype — what HBM holds per element
        return jnp.asarray(self.q).dtype if not hasattr(self.q, "dtype") \
            else self.q.dtype

    def __repr__(self):
        return (f"QuantizedArray(shape={tuple(self.shape)}, "
                f"scale={tuple(jnp.shape(self.scale))}, mode={self.mode!r})")

    # --- pytree protocol ---

    def tree_flatten_with_keys(self):
        return (
            (jax.tree_util.GetAttrKey("q"), self.q),
            (jax.tree_util.GetAttrKey("scale"), self.scale),
        ), self.mode

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux)


def quantize(w) -> QuantizedArray:
    """Symmetric int8 quantization of a 2-D+ float array.

    Per-channel scales over the contraction axis (see module docstring for
    the stacked-leaf layout); per-tensor fallback when any channel's amax
    is exactly zero. Runs eagerly at load time: on an already-sharded
    restored leaf the elementwise ops preserve the NamedSharding, so a
    TP/fsdp layout survives quantization."""
    w = jnp.asarray(w)
    if w.ndim < 2:
        raise ValueError(
            f"quantize() wants a 2-D+ kernel, got shape {w.shape} — 1-D "
            "leaves (biases, norms) should stay float (default_leaf_rule)")
    amax = jnp.max(jnp.abs(w), axis=w.ndim - 2, keepdims=True)
    mode = "channel"
    # load-time scalar pull, never traced: `quantize` runs once per leaf at
    # checkpoint-load/hot-swap, outside the request hot path
    if not bool(jnp.all(amax > 0.0)):
        t_amax = jnp.max(jnp.abs(w))
        # broadcast the single tensor scale to the per-channel keepdims
        # shape: leading (stack) dims keep their extent, so scan/vmap
        # slicing stays identical to the per-channel layout
        amax = jnp.broadcast_to(t_amax, amax.shape)
        mode = "tensor"
    scale = (jnp.maximum(amax, _EPS) / _QMAX).astype(jnp.float32)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                 -_QMAX, _QMAX).astype(jnp.int8)
    return QuantizedArray(q, scale, mode)


def quantize_kv(x):
    """Symmetric int8 for KV-cache tokens: one scale per token per HEAD
    (amax over the LAST axis, keepdims) — returns ``(q int8, scale f32)``
    with ``scale.shape == x.shape[:-1] + (1,)``.

    Differs from `quantize` in two load-bearing ways: the reduction axis
    is the head_dim (a cache line is consumed whole by attention, not
    contracted per output channel), and there is NO degenerate-scale host
    check — this runs inside the jitted decode step every token, so it
    must stay traceable; a zero-amax token just lands on the `_EPS` floor
    (q == 0, exact-zero dequant). The caller pairs the result into a
    ``QuantizedArray(q, scale, mode="kv_head")`` cache node."""
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = (jnp.maximum(amax, _EPS) / _QMAX).astype(jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                 -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def dequantize(qa: QuantizedArray, dtype=None):
    """`q * scale` back to float; `dtype` is the compute dtype (bf16 under
    the serve cast policy). Traced inside the consuming matmul so XLA
    fuses it — the weights never materialize at full width in HBM."""
    dtype = jnp.float32 if dtype is None else dtype
    return qa.q.astype(dtype) * qa.scale.astype(dtype)


def materialize(w, dtype=None):
    """Uniform access for code paths that may see either representation:
    a plain array passes through UNTOUCHED (bit-identical float baseline);
    a QuantizedArray dequantizes into `dtype`."""
    if isinstance(w, QuantizedArray):
        return dequantize(w, dtype)
    return w


#: fused-matmul dispatch mode — "auto" (Pallas kernel on TPU, XLA
#: materialize elsewhere), "pallas" (force the kernel; interpret-mode off
#: TPU — what tests and `bench.py --kernels` use), "xla" (force the
#: materialize fallback). Read ONCE per trace: q_dot inside an already-
#: compiled program keeps the dispatch it was traced with.
FUSED_MATMUL = os.environ.get("DMT_QUANT_MATMUL", "auto")


def _use_fused_matmul() -> bool:
    if FUSED_MATMUL == "pallas":
        return True
    if FUSED_MATMUL == "xla":
        return False
    return jax.default_backend() == "tpu"


def q_dot(x, w):
    """``x @ w`` for either weight representation.

    A plain float array multiplies untouched (bit-identical float
    baseline). A `QuantizedArray` dispatches on `fused_matmul_mode`
    semantics (module var `FUSED_MATMUL`): the DEFAULT on TPU is the
    fused Pallas kernel (ops/pallas/quant_matmul.py) — int8 weight tiles
    streamed from HBM, per-channel scales applied in registers, f32
    accumulation; everywhere else (and under ``"xla"``) the fallback
    MATERIALIZES a transient float dequant copy and lets XLA fold it into
    the matmul — the weight is read at full compute width. Stacked
    scan/MoE leaves arrive here already sliced to 2-D (scan slices the
    leading dim; vmap batches the kernel), so both layouts hit the same
    dispatch."""
    if not isinstance(w, QuantizedArray):
        return x @ w.astype(x.dtype)
    if w.ndim == 2 and _use_fused_matmul():
        from dist_mnist_tpu.ops.pallas.quant_matmul import quant_matmul

        return quant_matmul(x, w.q, w.scale)
    return x @ dequantize(w, x.dtype)


def _matmul_spec(spec: str):
    """Parse an einsum spec that is exactly a last-axis matmul
    (``...k,kh->...h`` shapes, arbitrary labels): returns True when the
    second operand is 2-D, contracts its first axis with the first
    operand's last, and the output is the first operand's leading labels
    + the second's output label."""
    spec = spec.replace(" ", "")
    if "->" not in spec or spec.count(",") != 1:
        return False
    lhs, out = spec.split("->")
    a, b = lhs.split(",")
    if len(b) != 2 or "." in b or len(set(a)) != len(a):
        return False
    k, h = b
    return bool(a) and a[-1] == k and h not in a and out == a[:-1] + h


def q_einsum(spec: str, x, w: QuantizedArray):
    """einsum twin of `q_dot`. Specs that are a plain last-axis matmul in
    disguise take the same fused-vs-materialize dispatch as `q_dot`;
    genuinely non-matmul contractions always use the XLA fallback."""
    if (isinstance(w, QuantizedArray) and w.ndim == 2
            and _matmul_spec(spec) and _use_fused_matmul()):
        from dist_mnist_tpu.ops.pallas.quant_matmul import quant_matmul

        return quant_matmul(x, w.q, w.scale)
    return jnp.einsum(spec, x, dequantize(w, x.dtype))


# ---------------------------------------------------------------------------
# tree-level transform


def _seg(key) -> str:
    """One path component as text (DictKey/GetAttrKey/SequenceKey)."""
    for attr in ("key", "name", "idx"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def path_str(path) -> str:
    return "/".join(_seg(k) for k in path)


def default_leaf_rule(path, leaf) -> bool:
    """Quantize matmul/conv kernels; keep everything else float.

    The rule is name + shape + dtype: the leaf's last path segment must be
    a kernel name (`QUANT_LEAF_NAMES`), the leaf 2-D+ (1-D biases/norms
    excluded even if misnamed), and floating (an already-int leaf is left
    alone). Shared verbatim by dense/ViT/MoE — ViT's pos/cls/LN and the
    MoE router gate fall out by name."""
    if not path:
        return False
    name = _seg(path[-1])
    shape = jnp.shape(leaf) if hasattr(leaf, "shape") else ()
    dtype = getattr(leaf, "dtype", None)
    return (name in QUANT_LEAF_NAMES
            and len(shape) >= 2
            and dtype is not None
            and jnp.issubdtype(dtype, jnp.floating))


def quantize_tree(tree, rule=default_leaf_rule):
    """Apply `quantize` to every leaf the rule selects; structure-preserving
    otherwise. Idempotent: QuantizedArray nodes pass through."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, QuantizedArray))
    out = []
    for path, leaf in flat:
        if not isinstance(leaf, QuantizedArray) and rule(path, leaf):
            leaf = quantize(leaf)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def is_quantized(tree) -> bool:
    """True when any leaf of `tree` is a QuantizedArray."""
    flat = jax.tree_util.tree_leaves(
        tree, is_leaf=lambda x: isinstance(x, QuantizedArray))
    return any(isinstance(x, QuantizedArray) for x in flat)


def error_report(float_tree, quant_tree) -> dict:
    """Per-leaf quantization error of `quant_tree` against the float
    original: {"leaves": {path: {max_abs_err, rel_err, mode}},
    "max_abs_err", "max_rel_err", "n_quantized"}.

    rel_err is max|w - deq(q)| / max|w| per leaf — scale-free, so one
    tolerance covers kernels of any magnitude. All per-leaf maxima are
    stacked device-side and pulled in ONE batched transfer."""
    f_flat = {path_str(p): leaf for p, leaf
              in jax.tree_util.tree_flatten_with_path(float_tree)[0]}
    q_flat, _ = jax.tree_util.tree_flatten_with_path(
        quant_tree, is_leaf=lambda x: isinstance(x, QuantizedArray))
    names, modes, stats = [], [], []
    for path, leaf in q_flat:
        if not isinstance(leaf, QuantizedArray):
            continue
        name = path_str(path)
        w = f_flat.get(name)
        if w is None:
            continue
        wf = jnp.asarray(w, jnp.float32)
        err = jnp.max(jnp.abs(wf - dequantize(leaf, jnp.float32)))
        ref = jnp.max(jnp.abs(wf))
        names.append(name)
        modes.append(leaf.mode)
        stats.append(jnp.stack([err, ref]))
    report = {"leaves": {}, "max_abs_err": 0.0, "max_rel_err": 0.0,
              "n_quantized": len(names)}
    if not names:
        return report
    # lint: ok[host-sync] ONE batched pull of all per-leaf maxima, at load time
    vals = np.asarray(jax.device_get(jnp.stack(stats))).tolist()
    for name, mode, (err, ref) in zip(names, modes, vals):
        rel = err / max(ref, _EPS)
        report["leaves"][name] = {
            "max_abs_err": err, "rel_err": rel, "mode": mode,
        }
        report["max_abs_err"] = max(report["max_abs_err"], err)
        report["max_rel_err"] = max(report["max_rel_err"], rel)
    return report
