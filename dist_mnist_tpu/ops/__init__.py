"""Compute ops: the layer/loss/metric library the models are built from.

Replaces the reference's graph-construction layer (SURVEY.md §1 L5 —
`tf.nn.*`, `tf.Variable`, `tf.gradients`): here a layer is an init function
returning a params pytree plus a pure apply function; autodiff is
`jax.grad` over the composed step. Everything is jit-traceable, static-
shaped, and bfloat16-friendly so XLA can tile onto the MXU.

`ops.pallas` holds hand-written TPU kernels for hot paths with pure-XLA
fallbacks.
"""

from dist_mnist_tpu.ops import quant, nn, losses, metrics

__all__ = ["quant", "nn", "losses", "metrics"]
