"""Hand-written Pallas TPU kernels for hot ops.

The reference's hand-written native layer was op kernels + the gRPC wire
path (SURVEY.md §2.5); here the native layer that matters is what XLA does
NOT already fuse well. Each kernel ships with an interpret-mode path so the
CPU test mesh exercises the same code, and a pure-XLA reference
implementation it is tested against.
"""

from dist_mnist_tpu.ops.pallas.flash_attention import (
    flash_attention,
    flash_attention_lse,
    masked_flash_attention,
    masked_flash_attention_probe,
    masked_key_blocks,
)
from dist_mnist_tpu.ops.pallas.fused_adam import (
    fused_adam_clip_wd_update,
    fused_adam_update,
)
from dist_mnist_tpu.ops.pallas.quant_matmul import quant_matmul

__all__ = [
    "flash_attention",
    "flash_attention_lse",
    "fused_adam_clip_wd_update",
    "fused_adam_update",
    "masked_flash_attention",
    "masked_flash_attention_probe",
    "masked_key_blocks",
    "quant_matmul",
]
