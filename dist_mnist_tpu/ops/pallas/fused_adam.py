"""Fused Adam update as a single Pallas kernel per parameter.

The reference's ApplyAdam was one fused native kernel running on the PS
(training_ops.h:ApplyAdam — SURVEY.md §2.3 row 8). XLA already fuses our
pure-jnp Adam into a few elementwise loops; this kernel goes one step
further and does m/v/delta in ONE pass over HBM (3 reads + 3 writes per
element, the bandwidth floor), and is the template for richer fused
optimizers. Selected via `optim.adam(fused=True)`; bitwise-compatible with
the reference update rule (eps outside the sqrt).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_ROWS = 256  # 256x128 f32 block = 128 KiB per buffer in VMEM


def _adam_kernel(lr_ref, g_ref, m_ref, v_ref, d_ref, mo_ref, vo_ref,
                 *, b1: float, b2: float, eps: float):
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    mo_ref[:] = m
    vo_ref[:] = v
    d_ref[:] = -lr_ref[0] * m / (jnp.sqrt(v) + eps)


@functools.partial(jax.jit, static_argnames=("b1", "b2", "eps"))
def fused_adam_update(grad, m, v, lr_t, *, b1: float = 0.9, b2: float = 0.999,
                      eps: float = 1e-8):
    """One-pass Adam slot+delta update for a single tensor.

    Returns (delta, new_m, new_v); `lr_t` is the bias-corrected step size
    (traced scalar — computed by the caller from the step count).
    interpret-mode on non-TPU backends, so the CPU mesh runs it too.
    """
    shape, dtype = grad.shape, jnp.float32
    n = math.prod(shape) if shape else 1
    rows = max(1, math.ceil(n / _LANES))
    pad = rows * _LANES - n
    as2d = lambda x: jnp.pad(
        x.astype(jnp.float32).reshape(-1), (0, pad)
    ).reshape(rows, _LANES)
    block_rows = min(_ROWS, rows)
    grid = (math.ceil(rows / block_rows),)
    tile = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((rows, _LANES), dtype)
    delta, m2, v2 = pl.pallas_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps),
        out_shape=(out_shape, out_shape, out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lr_t scalar
            tile, tile, tile,
        ],
        out_specs=(tile, tile, tile),
        interpret=jax.default_backend() != "tpu",
    )(jnp.reshape(lr_t, (1,)).astype(jnp.float32), as2d(grad), as2d(m), as2d(v))
    unflat = lambda x: x.reshape(-1)[:n].reshape(shape)
    return unflat(delta), unflat(m2), unflat(v2)


def _adam_clip_wd_kernel(sc_ref, g_ref, m_ref, v_ref, p_ref, d_ref, mo_ref,
                         vo_ref, *, b1: float, b2: float, eps: float):
    """`_adam_kernel` + global-norm clip + decoupled weight decay in the
    SAME pass: sc_ref (SMEM) holds [lr_t, clip_scale, lr*wd]. The clip
    scale multiplies the gradient BEFORE the moments (exactly
    `clip_by_global_norm >> adam` chaining) and the decay subtracts
    `lr*wd*p` from the delta (exactly adamw's decoupled term) — one HBM
    pass instead of three kernel launches reading grad/param again."""
    g = g_ref[:].astype(jnp.float32) * sc_ref[1]
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    mo_ref[:] = m
    vo_ref[:] = v
    d_ref[:] = (-sc_ref[0] * m / (jnp.sqrt(v) + eps)
                - sc_ref[2] * p_ref[:].astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("b1", "b2", "eps"))
def fused_adam_clip_wd_update(grad, m, v, param, lr_t, clip_scale, wd_step,
                              *, b1: float = 0.9, b2: float = 0.999,
                              eps: float = 1e-8):
    """One-pass clip + Adam + decoupled weight decay for a single tensor.

    Returns (delta, new_m, new_v). `clip_scale` is the global-norm clip
    factor (min(1, max_norm/norm) — computed ONCE across the whole tree by
    the caller, since the norm is a cross-tensor reduction a per-leaf
    kernel cannot see); `wd_step` is `lr * weight_decay`. With
    clip_scale=1 and wd_step=0 this is mathematically `fused_adam_update`
    plus two no-op FMAs — `optim.fused_adamw` routes to the exact original
    kernel in that case so the off-path stays bit-identical."""
    shape, dtype = grad.shape, jnp.float32
    n = math.prod(shape) if shape else 1
    rows = max(1, math.ceil(n / _LANES))
    pad = rows * _LANES - n
    as2d = lambda x: jnp.pad(
        x.astype(jnp.float32).reshape(-1), (0, pad)
    ).reshape(rows, _LANES)
    block_rows = min(_ROWS, rows)
    grid = (math.ceil(rows / block_rows),)
    tile = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((rows, _LANES), dtype)
    scalars = jnp.stack([
        jnp.asarray(lr_t, jnp.float32).reshape(()),
        jnp.asarray(clip_scale, jnp.float32).reshape(()),
        jnp.asarray(wd_step, jnp.float32).reshape(()),
    ])
    delta, m2, v2 = pl.pallas_call(
        functools.partial(_adam_clip_wd_kernel, b1=b1, b2=b2, eps=eps),
        out_shape=(out_shape, out_shape, out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # [lr_t, clip, lr*wd]
            tile, tile, tile, tile,
        ],
        out_specs=(tile, tile, tile),
        interpret=jax.default_backend() != "tpu",
    )(scalars, as2d(grad), as2d(m), as2d(v), as2d(param))
    unflat = lambda x: x.reshape(-1)[:n].reshape(shape)
    return unflat(delta), unflat(m2), unflat(v2)
