"""Fused int8 dequant-matmul Pallas kernel for the weight-only serve path.

The XLA path (`ops/quant.q_dot` fallback) materializes a full float copy
of the int8 kernel before the matmul — HBM reads the weight TWICE (once
int8, once at compute width) and a transient float tensor exists at all.
This kernel streams the int8 tiles straight from HBM into VMEM (half/quarter
the weight bytes of bf16/f32), upcasts in registers, accumulates the GEMM
in f32 on the MXU, and applies the per-output-channel scale ONCE to the
f32 accumulator at the epilogue — dequant commutes with the contraction
(`sum_k x[m,k] * (q[k,h] * s[h]) == s[h] * sum_k x[m,k] * q[k,h]`), so the
scale never touches HBM-resident data.

Grid: (M/bm, H/bn), both parallel; the contraction axis stays RESIDENT per
tile (this repo's weights top out at D=768, so an int8 [D, 128] tile is
<=96 KiB and an f32 [128, D] activation tile <=384 KiB — far inside the
~16 MiB/core VMEM; see docs/PERF.md "Kernels" for the budget math). A
K-streamed third grid dimension is the obvious extension for D beyond a
few thousand.

Scale layout contract (ops/quant.py): a 2-D kernel [D, H] carries scales
[1, H]; "tensor"-mode leaves broadcast their single scale to the same
[1, H] shape, so ONE kernel serves both modes. Stacked scan/MoE leaves
([L, D, 3D] with [L, 1, 3D] scales, [E, D, H] with [E, 1, H]) reach this
kernel already sliced to 2-D — `lax.scan` slices the leading dim away and
`vmap` batches the kernel via the pallas batching rule (grid dim added).

`interpret=True` (auto off-TPU) runs the same kernel under the Pallas
interpreter so the CPU tier-1 mesh covers it; parity vs the XLA reference
is gated in tests/test_kernels.py and `bench.py --kernels`.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BM = 128  # activation rows per tile (MXU-sized)
_BN = 128  # output channels per tile (lane width)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)        # [bm, D] activations
    w = q_ref[...].astype(jnp.float32)        # [D, bn] int8 -> f32 in regs
    acc = jax.lax.dot_general(                # f32 MXU accumulation
        x, w, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # per-output-channel scale on the f32 accumulator — dequant commutes
    # with the contraction, so this is the whole dequantize
    o_ref[...] = (acc * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _qmm_2d(x, w_q, w_scale, interpret: bool):
    m, d = x.shape
    h = w_q.shape[1]
    # 16-row granule covers both f32 (8) and bf16 (16) sublane tiles;
    # the row pad must then reach a whole number of bm-row tiles
    bm = min(_BM, _round_up(m, 16))
    mp, hp = _round_up(m, bm), _round_up(h, _BN)
    xp = jnp.pad(x, ((0, mp - m), (0, 0)))
    qp = jnp.pad(w_q, ((0, 0), (0, hp - h)))
    # padded channels get scale 0 -> exact zeros, sliced off below
    sp = jnp.pad(w_scale.reshape(1, h), ((0, 0), (0, hp - h)))
    out = pl.pallas_call(
        _qmm_kernel,
        out_shape=jax.ShapeDtypeStruct((mp, hp), x.dtype),
        grid=(mp // bm, hp // _BN),
        in_specs=[
            pl.BlockSpec((bm, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d, _BN), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BN), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, _BN), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xp, qp, sp)
    return out[:m, :h]


def quant_matmul(x, w_q, w_scale, *, interpret: bool | None = None):
    """`x @ (w_q * w_scale)` without materializing the float weight.

    x ``[..., D]`` float, w_q ``[D, H]`` int8, w_scale ``[1, H]`` (or
    ``[H]``) f32 — the `QuantizedArray` 2-D layout, covering both
    "channel" and broadcast "tensor" scales. Leading activation dims are
    flattened into the row axis; rows/channels are padded to tile
    multiples inside the jit (XLA fuses the pads) and sliced back off.
    Returns x.dtype, accumulation in f32."""
    if w_q.ndim != 2:
        raise ValueError(
            f"quant_matmul wants a 2-D int8 kernel, got {w_q.shape}; "
            "stacked leaves are sliced by scan/vmap before dispatch")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    m = math.prod(lead) if lead else 1
    out = _qmm_2d(x.reshape(m, x.shape[-1]), w_q, w_scale, interpret)
    return out.reshape(*lead, w_q.shape[1])


def quant_matmul_cost(x_shape, w_shape, x_dtype=jnp.float32) -> dict:
    """Analytic roofline inputs for one `quant_matmul` call: MACs x2 FLOPs
    and the HBM bytes the kernel actually moves (int8 weights + f32 scales
    + activations in/out at compute width) — the numerator pair for
    `bench.py --kernels` achieved-vs-peak attribution."""
    d, h = (int(s) for s in w_shape)
    m = math.prod(int(s) for s in x_shape[:-1]) or 1
    act = jnp.dtype(x_dtype).itemsize
    return {
        "flops": 2.0 * m * d * h,
        # lint: ok[host-sync] pure python-int arithmetic, no device values
        "hbm_bytes": float(m * d * act      # activations in
                           + d * h          # int8 weight tiles
                           + 4 * h          # f32 scales
                           + m * h * act),  # output
    }
