"""Fused attention Pallas kernel (flash-style: no HBM score matrix).

The XLA einsum path (ops/nn.dot_product_attention) materializes the
[B,H,S,S] score tensor in HBM for long S; this kernel tiles queries over a
grid and keeps each [block_q, S] score tile in VMEM — scores never touch
HBM. Softmax is computed per tile in f32 (exact, since the full key axis is
resident per tile); the MXU sees two GEMMs per tile.

Two kernel families, selected by `block_k`:
- `block_k=None` (default): full K/V resident per q tile. Layout: grid =
  (B*H, S/block_q); per program: q tile [block_q, D], full K/V [S, D] for
  that (batch, head). VMEM budget at default block_q=128, S<=8192, D<=128,
  bf16: ~2 MB score tile + ~4 MB K/V — inside the ~16 MB/core VMEM.
- `block_k=N`: ONLINE-softmax streaming (the classic flash recipe) — a
  third, sequential grid dimension walks K/V (and the corresponding
  resident axis of each backward kernel) one [block_k, D] tile at a time
  with running max/denominator/accumulator in f32 VMEM scratch, lifting
  the resident-axis ceiling for long single-device S. Both families are
  pinned equal to each other and to the dense reference
  (tests/test_parallel_attention.py::TestFlashBlockK).

For even longer S, shard the sequence (parallel/ring_attention.py) and let
each device run this kernel on its local block: `flash_attention_lse`
returns the merge-ready `(out, lse)` pair and `ring_attention_inner`
(`impl="flash"`) consumes it as a blockwise-LSE contribution `(num=out,
den=1, m=lse)` — that composition is tested, not prose
(tests/test_parallel_attention.py::test_ring_flash_*).

Training: `flash_attention` carries a `jax.custom_vjp`. The forward kernel
additionally emits the per-row log-sum-exp (LSE); the backward recomputes
the score tiles from (q, k, lse) — the flash recipe: never store P — in two
kernels, one tiled over query blocks (dQ) and one over key blocks (dK, dV),
with `delta = rowsum(dO * O)` precomputed in XLA. Zero-padding of the
sequence axis makes the padded rows/columns self-cancelling everywhere
except the key-padding mask inside the dQ kernel (where forward masked the
logits to -1e30, backward must too, or softmax mass leaks into dQ).

`interpret=True` (auto on non-TPU backends) runs the same kernels under the
Pallas interpreter so the CPU test mesh covers forward AND backward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams -> CompilerParams in newer jax
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))


def _attn_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float,
                     s_real: int):
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    k = k_ref[0]  # [S_pad, D]
    v = v_ref[0]
    logits = jax.lax.dot_general(
        q, k.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [block_q, S_pad]
    # mask key padding (S was rounded up to the lane tile)
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < s_real, logits, -1e30)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        (p / l).astype(v.dtype), v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = o.astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, 0]


def _attn_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                    *, scale: float, s_real: int):
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    k = k_ref[0].astype(jnp.float32)  # [S_pad, D]
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)  # [block_q, D]
    lse = lse_ref[0]  # [block_q]
    delta = delta_ref[0]  # [block_q]
    logits = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [block_q, S_pad]
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < s_real, logits, -1e30)  # forward's mask, replayed
    p = jnp.exp(logits - lse[:, None])  # normalized probs, recomputed
    dp = jax.lax.dot_general(  # dO @ V^T : [block_q, S_pad]
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None])
    dq = jax.lax.dot_general(  # dS @ K : [block_q, D]
        ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _attn_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, *, scale: float):
    """One key tile against the full query axis. Query padding is zero-filled
    (q=0, dO=0, delta=0) so padded columns cancel in both products; padded
    KEY rows land in dk/dv rows that the caller slices off."""
    k = k_ref[0].astype(jnp.float32)  # [block_k, D]
    v = v_ref[0].astype(jnp.float32)
    q = q_ref[0].astype(jnp.float32)  # [Q_pad, D]
    do = do_ref[0].astype(jnp.float32)  # [Q_pad, D]
    lse = lse_ref[0]  # [Q_pad]
    delta = delta_ref[0]  # [Q_pad]
    logits_t = jax.lax.dot_general(  # K_tile @ Q^T : [block_k, Q_pad]
        k, q, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    p_t = jnp.exp(logits_t - lse[None, :])  # P^T, recomputed
    dv = jax.lax.dot_general(  # P^T @ dO : [block_k, D]
        p_t, do, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp_t = jax.lax.dot_general(  # V_tile @ dO^T : [block_k, Q_pad]
        v, do, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds_t = p_t * (dp_t - delta[None, :])
    dk = jax.lax.dot_general(  # dS^T @ Q : [block_k, D]
        ds_t, q, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _attn_fwd_kernel_kt(q_ref, k_ref, v_ref, o_ref, lse_ref,
                        m_scr, l_scr, acc_scr, *, scale: float, s_real: int,
                        block_k: int, nk: int):
    """Online-softmax forward: grid (BH, nq, nk) with the key axis as the
    INNERMOST (sequential, 'arbitrary') dimension — K/V stream through
    VMEM one [block_k, D] tile at a time while running max/denominator/
    accumulator live in scratch. Removes the full-K-resident VMEM ceiling
    of `_attn_fwd_kernel` (the classic flash recipe; selected via
    `block_k=`)."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    k = k_ref[0]  # [block_k, D]
    v = v_ref[0]
    logits = jax.lax.dot_general(
        q, k.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [block_q, block_k]
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < s_real, logits, -1e30)
    m_prev, l_prev = m_scr[...], l_scr[...]
    m_cur = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
    alpha = jnp.exp(m_prev - m_cur)  # rescale of everything accumulated
    p = jnp.exp(logits - m_cur[:, None])
    l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1)
    acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[...] = m_cur

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_scr[...] / l_scr[...][:, None]).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(l_scr[...])


def _attn_dq_kernel_kt(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, acc_scr, *, scale: float, s_real: int,
                       block_k: int, nk: int):
    """dQ with the key axis streamed (grid (BH, nq, nk), nk innermost):
    no rescale pass needed — the forward's LSE makes p exact per tile, so
    dq accumulates tile-by-tile in f32 scratch."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    k = k_ref[0].astype(jnp.float32)  # [block_k, D]
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    logits = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < s_real, logits, -1e30)  # forward's mask
    p = jnp.exp(logits - lse[:, None])
    dp = jax.lax.dot_general(
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None])
    acc_scr[...] = acc_scr[...] + jax.lax.dot_general(
        ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _attn_dkv_kernel_qt(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                        dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                        nq: int):
    """dK/dV with the QUERY axis streamed (grid (BH, nk, nq), nq
    innermost). Query padding is zero-filled (q=0, dO=0, delta=0) so
    padded tiles contribute zero, exactly as in `_attn_dkv_kernel`."""
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    k = k_ref[0].astype(jnp.float32)  # [block_k, D]
    v = v_ref[0].astype(jnp.float32)
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    logits_t = jax.lax.dot_general(  # K_tile @ Q_tile^T
        k, q, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    p_t = jnp.exp(logits_t - lse[None, :])
    dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
        p_t, do, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp_t = jax.lax.dot_general(
        v, do, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds_t = p_t * (dp_t - delta[None, :])
    dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
        ds_t, q, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _to_bh(x, b, h, s, d, length):  # [B,S,H,D] -> [B*H, length, D], zero-pad
    x = jnp.moveaxis(x, 2, 1).reshape(b * h, s, d)
    return jnp.pad(x, ((0, 0), (0, length - s), (0, 0)))


def _from_bh(x, b, h, s, d):  # [B*H, length, D] -> [B,S,H,D]
    return jnp.moveaxis(x[:, :s].reshape(b, h, s, d), 1, 2)


_SEQ3 = ("parallel", "parallel", "arbitrary")


@functools.partial(jax.jit,
                   static_argnames=("block_q", "interpret", "block_k"))
def _flash_fwd_impl(q, k, v, block_q: int, interpret: bool,
                    block_k: int | None = None):
    b, s, h, d = q.shape
    scale = d**-0.5
    s_pad = _round_up(s, block_k or 128)
    q_pad = _round_up(s, block_q)

    qb = _to_bh(q, b, h, s, d, q_pad)
    kb = _to_bh(k, b, h, s, d, s_pad)
    vb = _to_bh(v, b, h, s, d, s_pad)
    out_shape = (
        jax.ShapeDtypeStruct((b * h, q_pad, d), q.dtype),
        jax.ShapeDtypeStruct((b * h, q_pad), jnp.float32),
    )
    q_spec = pl.BlockSpec((1, block_q, d), lambda i, j, *ki: (i, j, 0),
                          memory_space=pltpu.VMEM)
    o_specs = (
        pl.BlockSpec((1, block_q, d), lambda i, j, *ki: (i, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q), lambda i, j, *ki: (i, j),
                     memory_space=pltpu.VMEM),
    )
    if block_k is None:
        out, lse = pl.pallas_call(
            functools.partial(_attn_fwd_kernel, scale=scale, s_real=s),
            out_shape=out_shape,
            grid=(b * h, q_pad // block_q),
            in_specs=[
                q_spec,
                pl.BlockSpec((1, s_pad, d), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, s_pad, d), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=o_specs,
            interpret=interpret,
        )(qb, kb, vb)
    else:
        nk = s_pad // block_k
        kv_spec = pl.BlockSpec((1, block_k, d), lambda i, j, ki: (i, ki, 0),
                               memory_space=pltpu.VMEM)
        out, lse = pl.pallas_call(
            functools.partial(_attn_fwd_kernel_kt, scale=scale, s_real=s,
                              block_k=block_k, nk=nk),
            out_shape=out_shape,
            grid=(b * h, q_pad // block_q, nk),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=o_specs,
            scratch_shapes=[
                pltpu.VMEM((block_q,), jnp.float32),
                pltpu.VMEM((block_q,), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=_SEQ3),
            interpret=interpret,
        )(qb, kb, vb)
    return _from_bh(out, b, h, s, d), lse


@functools.partial(jax.jit,
                   static_argnames=("block_q", "interpret", "block_k"))
def _flash_bwd_impl(q, k, v, out, lse, do, dlse, block_q: int,
                    interpret: bool, block_k: int | None = None):
    """dlse is the [B,H,S] f32 cotangent of the returned LSE (zeros for the
    out-only entry point). It needs no kernel change: dlogits =
    p*(dp - delta + dlse) row-wise, so it folds into the delta argument as
    `delta - dlse`; dV is p^T @ dO, independent of lse.

    `block_k=None` (default): dQ holds full K/V per tile and dK/dV holds
    full Q — the proven small-S path. With `block_k`, both kernels stream
    their resident axis through VMEM (grid accumulation in f32 scratch),
    matching the forward's online path."""
    b, s, h, d = q.shape
    scale = d**-0.5
    s_pad = _round_up(s, block_k or 128)
    q_pad = _round_up(s, block_q)

    qb = _to_bh(q, b, h, s, d, q_pad)
    kb = _to_bh(k, b, h, s, d, s_pad)
    vb = _to_bh(v, b, h, s, d, s_pad)
    ob = _to_bh(out, b, h, s, d, q_pad)
    dob = _to_bh(do, b, h, s, d, q_pad)
    # delta_i = sum_d dO_id * O_id — one cheap fused elementwise pass in XLA;
    # zero on padded rows because dO and O are zero-padded (and so is the
    # padded tail of the dlse fold-in below).
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32), axis=-1)
    delta = delta - jnp.pad(
        dlse.astype(jnp.float32).reshape(b * h, s),
        ((0, 0), (0, q_pad - s)))

    vec_spec_q = pl.BlockSpec((1, block_q), lambda i, j, *kk: (i, j),
                              memory_space=pltpu.VMEM)
    mat_tile_q = pl.BlockSpec((1, block_q, d), lambda i, j, *kk: (i, j, 0),
                              memory_space=pltpu.VMEM)

    if block_k is None:
        mat_full_s = pl.BlockSpec((1, s_pad, d), lambda i, j: (i, 0, 0),
                                  memory_space=pltpu.VMEM)
        dqb = pl.pallas_call(
            functools.partial(_attn_dq_kernel, scale=scale, s_real=s),
            out_shape=jax.ShapeDtypeStruct((b * h, q_pad, d), q.dtype),
            grid=(b * h, q_pad // block_q),
            in_specs=[mat_tile_q, mat_full_s, mat_full_s, mat_tile_q,
                      vec_spec_q, vec_spec_q],
            out_specs=mat_tile_q,
            interpret=interpret,
        )(qb, kb, vb, dob, lse, delta)
    else:
        nk = s_pad // block_k
        kv_tile = pl.BlockSpec((1, block_k, d), lambda i, j, ki: (i, ki, 0),
                               memory_space=pltpu.VMEM)
        dqb = pl.pallas_call(
            functools.partial(_attn_dq_kernel_kt, scale=scale, s_real=s,
                              block_k=block_k, nk=nk),
            out_shape=jax.ShapeDtypeStruct((b * h, q_pad, d), q.dtype),
            grid=(b * h, q_pad // block_q, nk),
            in_specs=[mat_tile_q, kv_tile, kv_tile, mat_tile_q,
                      vec_spec_q, vec_spec_q],
            out_specs=mat_tile_q,
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=_SEQ3),
            interpret=interpret,
        )(qb, kb, vb, dob, lse, delta)

    bk_tile = 128
    mat_tile_k = pl.BlockSpec((1, bk_tile, d), lambda i, j, *qq: (i, j, 0),
                              memory_space=pltpu.VMEM)
    dkv_shape = (
        jax.ShapeDtypeStruct((b * h, s_pad, d), k.dtype),
        jax.ShapeDtypeStruct((b * h, s_pad, d), v.dtype),
    )
    if block_k is None:
        mat_full_q = pl.BlockSpec((1, q_pad, d), lambda i, j: (i, 0, 0),
                                  memory_space=pltpu.VMEM)
        vec_full_q = pl.BlockSpec((1, q_pad), lambda i, j: (i, 0),
                                  memory_space=pltpu.VMEM)
        dkb, dvb = pl.pallas_call(
            functools.partial(_attn_dkv_kernel, scale=scale),
            out_shape=dkv_shape,
            grid=(b * h, s_pad // bk_tile),
            in_specs=[mat_tile_k, mat_tile_k, mat_full_q, mat_full_q,
                      vec_full_q, vec_full_q],
            out_specs=(mat_tile_k, mat_tile_k),
            interpret=interpret,
        )(kb, vb, qb, dob, lse, delta)
    else:
        nq = q_pad // block_q
        q_tile_inner = pl.BlockSpec((1, block_q, d),
                                    lambda i, j, qi: (i, qi, 0),
                                    memory_space=pltpu.VMEM)
        vec_tile_inner = pl.BlockSpec((1, block_q),
                                      lambda i, j, qi: (i, qi),
                                      memory_space=pltpu.VMEM)
        dkb, dvb = pl.pallas_call(
            functools.partial(_attn_dkv_kernel_qt, scale=scale, nq=nq),
            out_shape=dkv_shape,
            grid=(b * h, s_pad // bk_tile, nq),
            in_specs=[mat_tile_k, mat_tile_k, q_tile_inner, q_tile_inner,
                      vec_tile_inner, vec_tile_inner],
            out_specs=(mat_tile_k, mat_tile_k),
            scratch_shapes=[pltpu.VMEM((bk_tile, d), jnp.float32),
                            pltpu.VMEM((bk_tile, d), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=_SEQ3),
            interpret=interpret,
        )(kb, vb, qb, dob, lse, delta)

    return (_from_bh(dqb, b, h, s, d), _from_bh(dkb, b, h, s, d),
            _from_bh(dvb, b, h, s, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, block_q: int, interpret: bool,
                     block_k: int | None):
    out, _ = _flash_fwd_impl(q, k, v, block_q, interpret, block_k)
    return out


def _flash_attention_fwd(q, k, v, block_q: int, interpret: bool,
                         block_k: int | None):
    out, lse = _flash_fwd_impl(q, k, v, block_q, interpret, block_k)
    return out, (q, k, v, out, lse)


def _flash_attention_bwd(block_q: int, interpret: bool,
                         block_k: int | None, res, do):
    q, k, v, out, lse = res
    zero_dlse = jnp.zeros((q.shape[0], q.shape[2], q.shape[1]), jnp.float32)
    return _flash_bwd_impl(q, k, v, out, lse, do, zero_dlse, block_q,
                           interpret, block_k)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_lse(q, k, v, block_q: int, interpret: bool,
                         block_k: int | None):
    out, lse = _flash_fwd_impl(q, k, v, block_q, interpret, block_k)
    b, s, h, _ = q.shape
    return out, lse[:, :s].reshape(b, h, s)


def _flash_attention_lse_fwd(q, k, v, block_q: int, interpret: bool,
                             block_k: int | None):
    out, lse = _flash_fwd_impl(q, k, v, block_q, interpret, block_k)
    b, s, h, _ = q.shape
    return (out, lse[:, :s].reshape(b, h, s)), (q, k, v, out, lse)


def _flash_attention_lse_bwd(block_q: int, interpret: bool,
                             block_k: int | None, res, cts):
    q, k, v, out, lse = res
    do, dlse = cts
    return _flash_bwd_impl(q, k, v, out, lse, do, dlse, block_q, interpret,
                           block_k)


_flash_attention_lse.defvjp(_flash_attention_lse_fwd,
                            _flash_attention_lse_bwd)


# ---------------------------------------------------------------------------
# key-padding-masked variable-length kernels
#
# The zoo's sub-native seq buckets (serve/zoo.py) and the decode cache
# (models/causal_lm.py) both mask a PREFIX of the key axis per batch row:
# row b attends keys [0, lengths[b]). The kernels below take that lengths
# vector (int32, >= 1) through SMEM and make the streaming key-block grid
# SKIP fully-padded blocks — a 64-token request in a 256 bucket runs 1/4
# of the attention FLOPs instead of full-bucket math behind a -1e30 mask.
# The skip predicate (`ki * block_k < lengths[bh]`) is the same expression
# `masked_key_blocks` exposes for tests/bench FLOP attribution, and the
# forward kernel counts its own active blocks into a `visits` output so
# the scaling is asserted from INSIDE the kernel, not from prose.


def _masked_attn_fwd_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                            vis_ref, m_scr, l_scr, acc_scr, cnt_scr,
                            *, scale: float, block_k: int, nk: int):
    """Online-softmax forward with per-row key lengths: grid (BH, nq, nk),
    nk innermost ('arbitrary'). Identical math to `_attn_fwd_kernel_kt`
    except the static `s_real` becomes `len_ref[bh]` and a whole key block
    past the row's length is skipped, not just masked."""
    s_real = len_ref[pl.program_id(0)]
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        cnt_scr[...] = jnp.zeros_like(cnt_scr)

    @pl.when(ki * block_k < s_real)  # the skip: padded blocks do NO math
    def _tile():
        q = q_ref[0].astype(jnp.float32)  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        logits = jnp.where(col < s_real, logits, -1e30)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_cur = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(logits - m_cur[:, None])
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_cur
        cnt_scr[...] = cnt_scr[...] + 1.0  # active-block probe

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_scr[...] / l_scr[...][:, None]).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(l_scr[...])
        vis_ref[0] = jnp.broadcast_to(cnt_scr[...], vis_ref[0].shape)


def _masked_attn_dq_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                           delta_ref, dq_ref, acc_scr, *, scale: float,
                           block_k: int, nk: int):
    """dQ with streamed keys and the forward's skip predicate replayed:
    a skipped key block contributed no probability mass forward, so it
    contributes no dq backward — skipping is exact, not approximate."""
    s_real = len_ref[pl.program_id(0)]
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(ki * block_k < s_real)
    def _tile():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        logits = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        logits = jnp.where(col < s_real, logits, -1e30)  # forward's mask
        p = jnp.exp(logits - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        acc_scr[...] = acc_scr[...] + jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _masked_attn_dkv_kernel(len_ref, k_ref, v_ref, q_ref, do_ref, lse_ref,
                            delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                            *, scale: float, bk_tile: int, nq: int):
    """dK/dV with the query axis streamed (grid (BH, nk, nq), nq
    innermost). A fully-padded key tile skips all math and finalizes to
    exact zeros (a masked key's probability was zero forward, so its
    gradient is zero); a PARTIAL tile row-masks the keys past the row's
    length — unlike the unmasked kernels, padded keys here live inside
    the array, not in a sliced-off tail."""
    s_real = len_ref[pl.program_id(0)]
    j = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(j * bk_tile < s_real)
    def _tile():
        k = k_ref[0].astype(jnp.float32)  # [bk_tile, D]
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)  # [block_q, D]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        logits_t = jax.lax.dot_general(  # K_tile @ Q_tile^T
            k, q, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p_t = jnp.exp(logits_t - lse[None, :])
        row = j * bk_tile + jax.lax.broadcasted_iota(
            jnp.int32, p_t.shape, 0)
        p_t = jnp.where(row < s_real, p_t, 0.0)  # mask keys past length
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p_t, do, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v, do, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta[None, :])
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds_t, q, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_q", "block_k", "interpret"))
def _masked_flash_fwd_impl(q, k, v, lengths, block_q: int, block_k: int,
                           interpret: bool):
    """Returns (out [B,Sq,H,D], lse [B*H, q_pad], visits [B*H, q_pad]).
    Cross-attention shapes allowed (decode: Sq=1 against a cached Sk)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = d**-0.5
    s_pad = _round_up(sk, block_k)
    q_pad = _round_up(sq, block_q)
    qb = _to_bh(q, b, h, sq, d, q_pad)
    kb = _to_bh(k, b, h, sk, d, s_pad)
    vb = _to_bh(v, b, h, sk, d, s_pad)
    len_bh = jnp.repeat(lengths.astype(jnp.int32), h)  # b-major, like _to_bh
    nk = s_pad // block_k
    out_shape = (
        jax.ShapeDtypeStruct((b * h, q_pad, d), q.dtype),
        jax.ShapeDtypeStruct((b * h, q_pad), jnp.float32),
        jax.ShapeDtypeStruct((b * h, q_pad), jnp.float32),
    )
    q_spec = pl.BlockSpec((1, block_q, d), lambda i, j, ki: (i, j, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, d), lambda i, j, ki: (i, ki, 0),
                           memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, block_q), lambda i, j, ki: (i, j),
                            memory_space=pltpu.VMEM)
    out, lse, visits = pl.pallas_call(
        functools.partial(_masked_attn_fwd_kernel, scale=scale,
                          block_k=block_k, nk=nk),
        out_shape=out_shape,
        grid=(b * h, q_pad // block_q, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lengths [B*H]
            q_spec, kv_spec, kv_spec,
        ],
        out_specs=(q_spec, vec_spec, vec_spec),
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((1,), jnp.float32),
        ],
        compiler_params=_CompilerParams(dimension_semantics=_SEQ3),
        interpret=interpret,
    )(len_bh, qb, kb, vb)
    return _from_bh(out, b, h, sq, d), lse, visits


@functools.partial(jax.jit,
                   static_argnames=("block_q", "block_k", "interpret"))
def _masked_flash_bwd_impl(q, k, v, lengths, out, lse, do, block_q: int,
                           block_k: int, interpret: bool):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = d**-0.5
    s_pad = _round_up(sk, block_k)
    q_pad = _round_up(sq, block_q)
    qb = _to_bh(q, b, h, sq, d, q_pad)
    kb = _to_bh(k, b, h, sk, d, s_pad)
    vb = _to_bh(v, b, h, sk, d, s_pad)
    ob = _to_bh(out, b, h, sq, d, q_pad)
    dob = _to_bh(do, b, h, sq, d, q_pad)
    len_bh = jnp.repeat(lengths.astype(jnp.int32), h)
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)
    nk = s_pad // block_k
    nq = q_pad // block_q

    len_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    mat_tile_q = pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0),
                              memory_space=pltpu.VMEM)
    vec_spec_q = pl.BlockSpec((1, block_q), lambda i, j, kk: (i, j),
                              memory_space=pltpu.VMEM)
    kv_tile = pl.BlockSpec((1, block_k, d), lambda i, j, ki: (i, ki, 0),
                           memory_space=pltpu.VMEM)
    dqb = pl.pallas_call(
        functools.partial(_masked_attn_dq_kernel, scale=scale,
                          block_k=block_k, nk=nk),
        out_shape=jax.ShapeDtypeStruct((b * h, q_pad, d), q.dtype),
        grid=(b * h, nq, nk),
        in_specs=[len_spec, mat_tile_q, kv_tile, kv_tile, mat_tile_q,
                  vec_spec_q, vec_spec_q],
        out_specs=mat_tile_q,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(dimension_semantics=_SEQ3),
        interpret=interpret,
    )(len_bh, qb, kb, vb, dob, lse, delta)

    mat_tile_k = pl.BlockSpec((1, block_k, d), lambda i, j, qq: (i, j, 0),
                              memory_space=pltpu.VMEM)
    q_tile_inner = pl.BlockSpec((1, block_q, d), lambda i, j, qi: (i, qi, 0),
                                memory_space=pltpu.VMEM)
    vec_tile_inner = pl.BlockSpec((1, block_q), lambda i, j, qi: (i, qi),
                                  memory_space=pltpu.VMEM)
    dkb, dvb = pl.pallas_call(
        functools.partial(_masked_attn_dkv_kernel, scale=scale,
                          bk_tile=block_k, nq=nq),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, s_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s_pad, d), v.dtype),
        ),
        grid=(b * h, nk, nq),
        in_specs=[len_spec, mat_tile_k, mat_tile_k, q_tile_inner,
                  q_tile_inner, vec_tile_inner, vec_tile_inner],
        out_specs=(mat_tile_k, mat_tile_k),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(dimension_semantics=_SEQ3),
        interpret=interpret,
    )(len_bh, kb, vb, qb, dob, lse, delta)
    return (_from_bh(dqb, b, h, sq, d), _from_bh(dkb, b, h, sk, d),
            _from_bh(dvb, b, h, sk, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _masked_flash_attention(q, k, v, lengths, block_q: int, block_k: int,
                            interpret: bool):
    out, _, _ = _masked_flash_fwd_impl(q, k, v, lengths, block_q, block_k,
                                       interpret)
    return out


def _masked_flash_attention_fwd(q, k, v, lengths, block_q: int,
                                block_k: int, interpret: bool):
    out, lse, _ = _masked_flash_fwd_impl(q, k, v, lengths, block_q, block_k,
                                         interpret)
    return out, (q, k, v, lengths, out, lse)


def _masked_flash_attention_bwd(block_q: int, block_k: int, interpret: bool,
                                res, do):
    import numpy as np

    q, k, v, lengths, out, lse = res
    dq, dk, dv = _masked_flash_bwd_impl(q, k, v, lengths, out, lse, do,
                                        block_q, block_k, interpret)
    # int lengths take a float0 zero cotangent
    dlen = np.zeros(np.shape(lengths), dtype=jax.dtypes.float0)
    return dq, dk, dv, dlen


_masked_flash_attention.defvjp(_masked_flash_attention_fwd,
                               _masked_flash_attention_bwd)


def masked_key_blocks(lengths, block_k: int):
    """Active key blocks per batch row — ceil(length / block_k), the exact
    skip predicate the kernels run (`ki * block_k < length`). Shared by
    tests and `bench.py --kernels` so the reported FLOPs come from the
    same expression as the kernel's grid skipping."""
    lengths = jnp.asarray(lengths)
    return -(-lengths // block_k)


def masked_flash_flops(lengths, sq: int, heads: int, head_dim: int,
                       block_k: int) -> float:
    """Analytic forward FLOPs at block granularity: per row, 2 GEMMs
    (scores + apply) over `active_blocks * block_k` keys — what the
    masked kernel actually executes, scaling with REAL token length, vs
    the -1e30 einsum's full-bucket `Sk` math."""
    import numpy as np

    active = np.asarray(masked_key_blocks(lengths, block_k)) * block_k
    # lint: ok[host-sync] bench/test-side analytic count on host numpy
    return float((2 * 2 * sq * head_dim * heads * active).sum())


def _check_lengths_arg(k, lengths):
    if lengths.ndim != 1 or lengths.shape[0] != k.shape[0]:
        raise ValueError(
            f"lengths must be [batch] = [{k.shape[0]}], got "
            f"{lengths.shape}")


def masked_flash_attention(q, k, v, lengths, *, block_q: int = 128,
                           block_k: int = 128,
                           interpret: bool | None = None):
    """Variable-length attention: q ``[B,Sq,H,D]`` against k/v
    ``[B,Sk,H,D]`` where row b attends only keys ``[0, lengths[b])``
    (int32, 1 <= lengths[b] <= Sk — the key-prefix masks of zoo serving
    and the decode cache). Equals the -1e30 pre-softmax einsum on the
    same mask, but fully-padded key blocks are SKIPPED by the grid, so
    the attention FLOPs scale with each row's real length instead of the
    bucket ceiling. Differentiable (recompute-based custom VJP with the
    same skipping); `interpret` auto-selects off-TPU so the CPU tier-1
    mesh covers forward and backward."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_lengths_arg(k, lengths)
    bq = _quantize_block_q(block_q, q.shape[1])
    bk = min(_round_up(block_k, 128), _round_up(k.shape[1], 128))
    return _masked_flash_attention(q, k, v, lengths, bq, bk, interpret)


def masked_flash_attention_probe(q, k, v, lengths, *, block_q: int = 128,
                                 block_k: int = 128,
                                 interpret: bool | None = None):
    """Forward-only variant returning ``(out, visits [B, H, Sq])``:
    `visits` is the number of key blocks the kernel ACTUALLY entered per
    query row, counted inside the kernel's skip predicate — the
    structural evidence that masked buckets stop paying full-length
    math. visits[b] == masked_key_blocks(lengths, block_k)[b] for every
    head/row."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    _check_lengths_arg(k, lengths)
    b, sq, h, _ = q.shape
    bq = _quantize_block_q(block_q, sq)
    bk = min(_round_up(block_k, 128), _round_up(k.shape[1], 128))
    out, _, visits = _masked_flash_fwd_impl(q, k, v, lengths, bq, bk,
                                            interpret)
    return out, visits[:, :sq].reshape(b, h, sq)


def _quantize_block_q(block_q: int, s: int) -> int:
    # 128-align the q tile in BOTH directions (round a small/odd block_q
    # UP, cap at the padded sequence): the LSE rides the lane axis in the
    # backward kernels and TPU lanes want multiples of 128. Padded rows
    # are zero-filled and self-cancelling.
    return min(_round_up(block_q, 128), _round_up(s, 128))


def _quantize_block_k(block_k: int | None, s: int) -> int | None:
    if block_k is None:
        return None
    bk = min(_round_up(block_k, 128), _round_up(s, 128))
    # streaming only pays off with >1 tile; a single tile IS the full-K
    # path, so take the simpler kernel
    return bk if _round_up(s, bk) // bk > 1 else None


def flash_attention(q, k, v, *, block_q: int = 128,
                    block_k: int | None = None,
                    interpret: bool | None = None):
    """[B,S,H,D] self-attention, fused in VMEM. Drop-in for
    ops/nn.dot_product_attention (non-causal), forward and backward —
    differentiable via a recompute-based custom VJP.

    `block_q` is quantized to 128-lane multiples (rounded UP, capped at the
    padded sequence length): requesting e.g. block_q=8 runs with 128, so it
    cannot be tuned *below* 128 for VMEM headroom — shrink S per device
    (sequence-shard, see flash_attention_lse) instead.

    `block_k=None` (default) keeps the full key axis resident per q tile
    (exact per-tile softmax; VMEM budget caps single-device S at ~8192).
    Setting `block_k` (same 128-quantization) selects the ONLINE-softmax
    kernels: K/V (and, in the backward, the dQ kernel's K axis and the
    dK/dV kernel's Q axis) stream through VMEM one tile at a time with
    running max/denominator in scratch — the classic flash recipe, lifting
    the resident-axis ceiling for long single-device sequences. Both paths
    are numerically pinned against each other and the dense reference."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention(q, k, v, _quantize_block_q(block_q, q.shape[1]),
                            interpret,
                            _quantize_block_k(block_k, q.shape[1]))


def flash_attention_lse(q, k, v, *, block_q: int = 128,
                        block_k: int | None = None,
                        interpret: bool | None = None):
    """Like `flash_attention` but returns `(out [B,S,H,D], lse [B,H,S])` —
    the merge-ready pair for blockwise/ring composition: a caller holding
    per-block `(out_b, lse_b)` recovers the exact global softmax via the
    LSE identity (treat each block as numerator `out_b`, denominator 1,
    running max `lse_b`). Differentiable in BOTH outputs: the lse cotangent
    folds into the same backward kernels as `delta - dlse` (see
    _flash_bwd_impl), which is what makes ring(flash-local) train-grade.
    Same block_q/block_k quantization and kernel selection as
    `flash_attention`."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention_lse(
        q, k, v, _quantize_block_q(block_q, q.shape[1]), interpret,
        _quantize_block_k(block_k, q.shape[1]))
