"""Fused attention Pallas kernel (flash-style: no HBM score matrix).

The XLA einsum path (ops/nn.dot_product_attention) materializes the
[B,H,S,S] score tensor in HBM for long S; this kernel tiles queries over a
grid and keeps each [block_q, S] score tile in VMEM — scores never touch
HBM. Softmax is computed per tile in f32 (exact, since the full key axis is
resident per tile); the MXU sees two GEMMs per tile.

Layout: grid = (B*H, S/block_q); per program: q tile [block_q, D], full K/V
[S, D] for that (batch, head). VMEM budget at default block_q=128, S<=8192,
D<=128, bf16: ~2 MB score tile + ~4 MB K/V — inside the ~16 MB/core VMEM.
For longer S, shard the sequence first (parallel/ring_attention.py) and let
each device run this kernel on its local block.

`interpret=True` (auto on non-TPU backends) runs the same kernel under the
Pallas interpreter so the CPU test mesh covers it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, s_real: int):
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    k = k_ref[0]  # [S_pad, D]
    v = v_ref[0]
    logits = jax.lax.dot_general(
        q, k.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [block_q, S_pad]
    # mask key padding (S was rounded up to the lane tile)
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < s_real, logits, -1e30)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = o.astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.partial(jax.jit, static_argnames=("block_q", "interpret"))
def _flash_attention(q, k, v, block_q: int, interpret: bool):
    b, s, h, d = q.shape
    scale = d**-0.5
    s_pad = _round_up(s, 128)
    q_pad = _round_up(s, block_q)

    def to_bh(x, length):  # [B,S,H,D] -> [B*H, length, D]
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, s, d)
        return jnp.pad(x, ((0, 0), (0, length - s), (0, 0)))

    qb, kb, vb = to_bh(q, q_pad), to_bh(k, s_pad), to_bh(v, s_pad)
    grid = (b * h, q_pad // block_q)
    out = pl.pallas_call(
        functools.partial(_attn_kernel, scale=scale, s_real=s),
        out_shape=jax.ShapeDtypeStruct((b * h, q_pad, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_pad, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(qb, kb, vb)
    out = out[:, :s].reshape(b, h, s, d)
    return jnp.moveaxis(out, 1, 2)  # [B,S,H,D]


def flash_attention(q, k, v, *, block_q: int = 128,
                    interpret: bool | None = None):
    """[B,S,H,D] self-attention, fused in VMEM. Drop-in for
    ops/nn.dot_product_attention (non-causal)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention(q, k, v, block_q=min(block_q, _round_up(q.shape[1], 8)),
                            interpret=interpret)
