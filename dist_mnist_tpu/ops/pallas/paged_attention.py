"""Paged-attention decode Pallas kernel: one-token queries against a
page-table-indirected int8 KV pool.

The decode hot path (models/causal_lm.py paged layout) holds K/V as int8
page pools ``[pages, page_tokens, heads, head_dim]`` plus per-token-per-
head f32 scales (`ops/quant.quantize_kv`), with a page table ``[rows,
n]`` mapping each slot's token range ``[j*T, (j+1)*T)`` to a pool page.
The XLA reference path gathers the table's pages into a dense
``[rows, n*T, H, D]`` float copy in HBM before attending; this kernel
never materializes that copy:

- **Page-table indirection in the index_map**: the flattened table rides
  `pltpu.PrefetchScalarGridSpec` (scalar-prefetched, so it is available
  to the BlockSpec index_maps), and each grid step (r, h, ki) DMAs pool
  page ``table[r, ki]`` directly from HBM into VMEM — int8 bytes plus a
  thin scale stripe, never a float page.
- **Per-slot lengths in SMEM**: the second scalar-prefetch operand;
  ``pl.when(ki * T < length)`` skips the compute of pages past a slot's
  live prefix (unallocated table entries alias scratch pages, so their
  fetches are safe and their math is skipped).
- **Fused dequant in registers**: ``q_int8 * scale`` happens on the VMEM
  tile right before the two MXU GEMMs, f32 accumulation, online softmax
  in scratch across the sequential page axis — the masked-flash recipe
  (ops/pallas/flash_attention.py) at block_q=1.

`interpret=True` (auto off-TPU) runs the same kernel under the Pallas
interpreter — that is a PARITY surface for tests, not the CPU serving
path: off-TPU serving uses the XLA gather path (`use_paged_kernel`,
same auto/pallas/xla dispatch contract as ops/quant.FUSED_MATMUL).
`paged_attention_cost` is the host-side analytic bytes/FLOPs twin the
`bench.py --kernels` roofline table consumes.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dist_mnist_tpu.ops.quant import QuantizedArray

# renamed TPUCompilerParams -> CompilerParams in newer jax
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

#: kernel dispatch mode — "auto" (kernel on TPU, XLA gather elsewhere),
#: "pallas" (force the kernel; interpret-mode off TPU — tests and
#: `bench.py --kernels`), "xla" (force the gather reference). Read once
#: per trace, like ops/quant.FUSED_MATMUL.
PAGED_ATTENTION = os.environ.get("DMT_PAGED_ATTENTION", "auto")


def use_paged_kernel() -> bool:
    if PAGED_ATTENTION == "pallas":
        return True
    if PAGED_ATTENTION == "xla":
        return False
    return jax.default_backend() == "tpu"


def _paged_attn_kernel(pt_ref, len_ref, q_ref, kq_ref, ks_ref, vq_ref,
                       vs_ref, o_ref, vis_ref, m_scr, l_scr, acc_scr,
                       cnt_scr, *, t: int, n: int, scale: float):
    """Grid (rows, heads, n_pages), page axis innermost/sequential.
    pt_ref/len_ref are the scalar-prefetch operands (pt_ref already
    consumed by the index_maps; len_ref drives the skip predicate)."""
    r = pl.program_id(0)
    ki = pl.program_id(2)
    length = len_ref[r]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        cnt_scr[...] = jnp.zeros_like(cnt_scr)

    @pl.when(ki * t < length)
    def _page():
        q = q_ref[0].astype(jnp.float32)  # [1, D]
        # fused dequant in registers: int8 page tile * [T, 1] scales
        k = (kq_ref[0, :, 0, :].astype(jnp.float32)
             * ks_ref[0, :, 0, :].astype(jnp.float32))  # [T, D]
        v = (vq_ref[0, :, 0, :].astype(jnp.float32)
             * vs_ref[0, :, 0, :].astype(jnp.float32))
        logits = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [1, T]
        col = ki * t + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(col < length, logits, -1e30)
        m_prev = m_scr[...]  # [1]
        l_prev = l_scr[...]
        m_cur = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(logits - m_cur[:, None])  # [1, T]
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p, v, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [1, D]
        m_scr[...] = m_cur
        cnt_scr[...] = cnt_scr[...] + 1.0  # visited-page probe

    @pl.when(ki == n - 1)
    def _finalize():
        o_ref[0, 0] = (acc_scr[...] / l_scr[...][:, None])[0].astype(
            o_ref.dtype)
        vis_ref[0, 0] = cnt_scr[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_attention_impl(q, kq, ks, vq, vs, page_table, lengths,
                          interpret: bool):
    """q [R, H, D] f32-ish; kq/vq [P, T, H, D] int8 with [P, T, H, 1]
    f32 scales; page_table [R, n] int32; lengths [R] int32. Returns
    (out [R, H, D], visits [R, H] f32)."""
    r, h, d = q.shape
    t = kq.shape[1]
    n = page_table.shape[1]
    scale = d**-0.5
    pt_flat = page_table.astype(jnp.int32).reshape(-1)

    q_idx = lambda ri, hi, ki, pt, ln: (ri, hi, 0)  # noqa: E731
    pool_idx = lambda ri, hi, ki, pt, ln: (pt[ri * n + ki], 0, hi, 0)  # noqa: E731
    q_spec = pl.BlockSpec((1, 1, d), q_idx, memory_space=pltpu.VMEM)
    pq_spec = pl.BlockSpec((1, t, 1, d), pool_idx, memory_space=pltpu.VMEM)
    ps_spec = pl.BlockSpec((1, t, 1, 1), pool_idx, memory_space=pltpu.VMEM)
    vis_spec = pl.BlockSpec((1, 1), lambda ri, hi, ki, pt, ln: (ri, hi),
                            memory_space=pltpu.VMEM)
    out, vis = pl.pallas_call(
        functools.partial(_paged_attn_kernel, t=t, n=n, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(r, h, n),
            in_specs=[q_spec, pq_spec, ps_spec, pq_spec, ps_spec],
            out_specs=(q_spec, vis_spec),
            scratch_shapes=[
                pltpu.VMEM((1,), jnp.float32),   # running max
                pltpu.VMEM((1,), jnp.float32),   # running denominator
                pltpu.VMEM((1, d), jnp.float32),  # output accumulator
                pltpu.VMEM((1,), jnp.float32),   # visits probe
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((r, h, d), q.dtype),
            jax.ShapeDtypeStruct((r, h), jnp.float32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(pt_flat, lengths.astype(jnp.int32), q, kq, ks, vq, vs)
    return out, vis


def _check_args(q, k_pool, v_pool, page_table, lengths):
    if not (isinstance(k_pool, QuantizedArray)
            and isinstance(v_pool, QuantizedArray)):
        raise ValueError(
            "paged_attention wants int8 QuantizedArray pools (kv_quant="
            "'int8'); float pools take the XLA gather path, which is "
            "already bitwise-exact and needs no kernel")
    if q.ndim != 4 or q.shape[1] != 1:
        raise ValueError(
            f"q must be [rows, 1, heads, head_dim] (one decode token per "
            f"slot), got {q.shape}")
    if page_table.ndim != 2 or page_table.shape[0] != q.shape[0]:
        raise ValueError(
            f"page_table must be [rows={q.shape[0]}, n_pages], got "
            f"{page_table.shape}")
    if lengths.ndim != 1 or lengths.shape[0] != q.shape[0]:
        raise ValueError(
            f"lengths must be [rows={q.shape[0]}], got {lengths.shape}")


def paged_attention(q, k_pool, v_pool, page_table, lengths, *,
                    interpret: bool | None = None):
    """Single-token paged attention: q ``[R, 1, H, D]`` against int8
    K/V pools through ``page_table`` [R, n] (row r attends positions
    ``[0, lengths[r])`` of its gathered ``n*T`` view). Equals the XLA
    gather+dequant+`_attend` reference to f32 roundoff — the parity
    tests/test_serve_paged.py and `bench.py --kernels` gate. `interpret`
    auto-selects off-TPU."""
    _check_args(q, k_pool, v_pool, page_table, lengths)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, _ = _paged_attention_impl(q[:, 0], k_pool.q, k_pool.scale,
                                   v_pool.q, v_pool.scale, page_table,
                                   lengths, interpret)
    return out[:, None]


def paged_attention_probe(q, k_pool, v_pool, page_table, lengths, *,
                          interpret: bool | None = None):
    """Forward plus ``visits [R, H]``: pages the kernel actually entered
    per (row, head) — the structural evidence that pages past a slot's
    prefix stop paying attention math. ``visits[r] ==
    paged_attention_pages(lengths, T)[r]`` clipped to the table width."""
    _check_args(q, k_pool, v_pool, page_table, lengths)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, vis = _paged_attention_impl(q[:, 0], k_pool.q, k_pool.scale,
                                     v_pool.q, v_pool.scale, page_table,
                                     lengths, interpret)
    return out[:, None], vis


def paged_attention_pages(lengths, page_tokens: int):
    """Active pages per row — ceil(length / T), the exact skip predicate
    the kernel runs (``ki * T < length``). Shared by tests and the bench
    so reported FLOPs come from the kernel's own expression."""
    lengths = jnp.asarray(lengths)
    return -(-lengths // page_tokens)


def paged_attention_cost(lengths, n_pages: int, page_tokens: int,
                         heads: int, head_dim: int) -> dict:
    """Analytic roofline inputs for one `paged_attention` call.

    flops: 2 GEMMs (scores + apply) over each row's ACTIVE pages — the
    `pl.when` skip predicate at block granularity. hbm_bytes: ALL
    ``n_pages`` page tiles per (row, head) — the pipeline DMAs skipped
    blocks too (the skip is compute-only), so the bytes win is the
    page-bucket truncation (n_pages tracks the batch's live prefix, not
    max_seq) plus int8 storage (1 byte/elem + the [T, 1] scale stripe),
    NOT the pl.when."""
    import numpy as np

    active = np.asarray(
        np.minimum(np.asarray(paged_attention_pages(lengths, page_tokens)),
                   n_pages)) * page_tokens
    r = len(active)
    # lint: ok[host-sync] bench/test-side analytic count on host numpy
    flops = float((2 * 2 * heads * head_dim * active).sum())
    page_tile = page_tokens * head_dim + page_tokens * 4  # int8 + f32 scale
    # lint: ok[host-sync] pure python-int arithmetic, no device values
    hbm_bytes = float(r * heads * n_pages * 2 * page_tile  # K and V tiles
                      + 2 * r * heads * head_dim * 4       # q in, out back
                      + r * n_pages * 4 + r * 4)           # table + lengths
    return {"flops": flops, "hbm_bytes": hbm_bytes}
