"""Asynchronous device-prefetch ring between a batch iterator and the loop.

The reference hid input latency behind queue runners and staged feeds
(SURVEY.md §2.1/§3.3); the SPMD rebuild's host batchers lost that overlap:
`ShardedBatcher.__iter__` gathered numpy rows and issued the sharded
`device_put` inline in the hot loop, so every step paid H2D transfer
serially before dispatch. `DevicePrefetcher` restores the overlap the way
flax/MaxText keep TPUs fed: a background worker pulls host batches from the
wrapped iterator and eagerly issues sharded transfers `depth` batches ahead
into a bounded ring, so XLA overlaps the copies with the running step.

Contract with the wrapped iterator:
- if it exposes `host_batches()` + `.mesh` (ShardedBatcher, NativeBatcher),
  the worker pulls HOST batches and performs `shard_batch` itself — the
  transfer issue moves off the training thread entirely;
- otherwise the worker just drives `iter(inner)` in the background (whatever
  device placement the inner does happens off the hot loop).

Determinism: the ring never reorders or drops batches, so a prefetched feed
yields the bit-identical stream (and loss trajectory) of the sync feed.
`at_step(step)` re-seeks by re-seeking the wrapped iterator — the
preemption-recovery replay contract (train/loop.py restore path) passes
straight through; cumulative stats survive the re-seek (shared object).

Cleanup: every stream's worker drains and joins on StopIteration of the
inner iterator, on an exception in it (re-raised in the consumer), and on
generator close (`iter(...).close()` — what TrainLoop calls in its
`finally`). Workers are named `DevicePrefetcher-*` so tests can assert
none leak (tests/conftest.py fixture).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator

#: worker-thread name prefix — the leak-check contract (tests/conftest.py)
THREAD_NAME_PREFIX = "DevicePrefetcher"

_POLL_S = 0.05  # stop-flag poll granularity for blocking queue ops


class _EndOfStream:
    """Sentinel: the wrapped iterator exhausted; worker exited cleanly."""


class _Raised:
    """Sentinel: the wrapped iterator raised; deliver to the consumer."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchStats:
    """Cumulative prefetch counters, thread-safe, SHARED across `at_step`
    re-seeks (recovery must not zero the run's attribution)."""

    def __init__(self, depth: int):
        self.depth = depth
        self._lock = threading.Lock()
        self.batches = 0            # batches delivered to the consumer
        self.h2d_bytes = 0          # bytes issued to devices by the worker
        self.get_wait_s = 0.0       # consumer time blocked on an empty ring
        self.occupancy_sum = 0      # ring size sampled at each get
        self.occupancy_samples = 0

    def record_transfer(self, nbytes: int) -> None:
        with self._lock:
            self.h2d_bytes += nbytes

    def record_get(self, wait_s: float, occupancy: int) -> None:
        with self._lock:
            self.batches += 1
            self.get_wait_s += wait_s
            self.occupancy_sum += occupancy
            self.occupancy_samples += 1

    def as_dict(self) -> dict:
        with self._lock:
            occ = (self.occupancy_sum / self.occupancy_samples
                   if self.occupancy_samples else 0.0)
            return {
                "depth": self.depth,
                "batches": self.batches,
                "h2d_bytes": self.h2d_bytes,
                "get_wait_s": self.get_wait_s,
                "mean_occupancy": occ,
            }


def _batch_nbytes(batch) -> int:
    if isinstance(batch, dict):
        return sum(getattr(v, "nbytes", 0) for v in batch.values())
    return getattr(batch, "nbytes", 0)


class _Stream:
    """One live iteration: a worker filling a bounded ring."""

    def __init__(self, source: Iterator, transfer, depth: int,
                 stats: PrefetchStats):
        self._source = source
        self._transfer = transfer
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._stats = stats
        self._thread = threading.Thread(
            target=self._produce,
            name=f"{THREAD_NAME_PREFIX}-{id(self):x}",
            daemon=True,
        )
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that yields to the stop flag (a plain blocking put
        on a full ring would deadlock close())."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            for host in self._source:
                if self._stop.is_set():
                    return
                batch = self._transfer(host)  # issues the sharded H2D copy
                self._stats.record_transfer(_batch_nbytes(batch))
                if not self._put(batch):
                    return
        except BaseException as exc:  # noqa: BLE001 — re-raised by consumer
            self._put(_Raised(exc))
        else:
            self._put(_EndOfStream)

    def get(self):
        occupancy = self._q.qsize()
        t0 = time.monotonic()
        while True:
            try:
                item = self._q.get(timeout=_POLL_S)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # worker died without a sentinel (killed interpreter
                    # teardown path) — treat as end of stream, don't spin
                    item = _EndOfStream
                    break
        self._stats.record_get(time.monotonic() - t0, occupancy)
        return item

    def close(self) -> None:
        self._stop.set()
        # unblock a producer waiting on a full ring, then reap the thread
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)


class DevicePrefetcher:
    """Wrap any batch iterator; yield its batches `depth` transfers ahead.

    >>> batches = DevicePrefetcher(ShardedBatcher(ds, 512, mesh), depth=2)
    >>> for batch in batches: ...   # batch is already on device

    `at_step(step)` delegates to the wrapped iterator (TrainLoop recovery
    re-seek) and keeps the cumulative `stats()`. `close()` stops every
    stream this instance started; per-iteration cleanup also happens
    automatically when the iterator is closed or exhausted.
    """

    def __init__(self, inner, depth: int = 2, *, stats: PrefetchStats = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.inner = inner
        self.depth = depth
        self._stats = stats if stats is not None else PrefetchStats(depth)
        self._streams: list[_Stream] = []
        self._lock = threading.Lock()

    def at_step(self, step: int) -> "DevicePrefetcher":
        """Re-seek pass-through: a prefetcher over `inner.at_step(step)`,
        sharing this instance's cumulative stats."""
        if not hasattr(self.inner, "at_step"):
            raise TypeError(
                f"{type(self.inner).__name__} has no at_step(); cannot "
                "re-seek a prefetched stream over it"
            )
        return DevicePrefetcher(self.inner.at_step(step), self.depth,
                                stats=self._stats)

    def stats(self) -> dict:
        return self._stats.as_dict()

    def _make_stream(self) -> _Stream:
        host_fn = getattr(self.inner, "host_batches", None)
        mesh = getattr(self.inner, "mesh", None)
        if callable(host_fn) and mesh is not None:
            from dist_mnist_tpu.data.pipeline import shard_batch

            return _Stream(host_fn(), lambda b: shard_batch(b, mesh),
                           self.depth, self._stats)
        return _Stream(iter(self.inner), lambda b: b, self.depth, self._stats)

    def __iter__(self) -> Iterator:
        stream = self._make_stream()
        with self._lock:
            self._streams.append(stream)
        try:
            while True:
                item = stream.get()
                if item is _EndOfStream:
                    return
                if isinstance(item, _Raised):
                    raise item.exc
                yield item
        finally:
            stream.close()
            with self._lock:
                if stream in self._streams:
                    self._streams.remove(stream)

    def close(self) -> None:
        with self._lock:
            streams, self._streams = self._streams, []
        for s in streams:
            s.close()
