"""Deterministic procedural datasets.

This environment is air-gapped (the reference's `read_data_sets` *downloaded*
MNIST — impossible here), so every named dataset has a procedural twin with
the same shapes/dtypes and enough class structure that the real models train
to high accuracy on it. Generation is fully vectorized numpy, seeded with
Philox counters, so any (seed, split) pair is bitwise reproducible across
hosts — a requirement for multi-host determinism tests (SURVEY.md §7 hard
part (c)).

Digits are rendered from an embedded 5x7 font through a random affine warp
(shift / rotate / scale / shear) with bilinear sampling plus pixel noise —
i.e. a miniature, self-contained MNIST generator.
"""

from __future__ import annotations

import numpy as np

# 5x7 digit glyphs (rows are strings; '#' = ink). Classic LCD-ish font.
_DIGIT_GLYPHS = [
    [" ### ", "#   #", "#  ##", "# # #", "##  #", "#   #", " ### "],  # 0
    ["  #  ", " ##  ", "  #  ", "  #  ", "  #  ", "  #  ", " ### "],  # 1
    [" ### ", "#   #", "    #", "   # ", "  #  ", " #   ", "#####"],  # 2
    [" ### ", "#   #", "    #", "  ## ", "    #", "#   #", " ### "],  # 3
    ["   # ", "  ## ", " # # ", "#  # ", "#####", "   # ", "   # "],  # 4
    ["#####", "#    ", "#### ", "    #", "    #", "#   #", " ### "],  # 5
    [" ### ", "#    ", "#    ", "#### ", "#   #", "#   #", " ### "],  # 6
    ["#####", "    #", "   # ", "  #  ", "  #  ", " #   ", " #   "],  # 7
    [" ### ", "#   #", "#   #", " ### ", "#   #", "#   #", " ### "],  # 8
    [" ### ", "#   #", "#   #", " ####", "    #", "    #", " ### "],  # 9
]

# 10 abstract garment-ish silhouettes for the fashion twin: coarse 5x7 masks.
_FASHION_GLYPHS = [
    ["#####", "#####", "#####", "#####", "#####", "#####", "#####"],  # block
    ["  #  ", " ### ", " ### ", " ### ", " ### ", " ### ", "  #  "],  # column
    ["#   #", "## ##", "#####", " ### ", " ### ", " ### ", " ### "],  # shirt
    [" ### ", " ### ", "  #  ", " ### ", "#####", "#####", "#####"],  # dress
    ["#####", "#   #", "#   #", "#   #", "#   #", "#   #", "#####"],  # frame
    ["#### ", "#####", "   ##", "  ## ", " ##  ", "##   ", "#####"],  # sandal?
    ["#    ", "##   ", "###  ", "#### ", "#####", "#### ", "###  "],  # wedge
    [" # # ", " # # ", " # # ", " # # ", " # # ", " # # ", " # # "],  # trouser
    ["  ## ", " ####", "#####", "#####", "## ##", "#   #", "##  #"],  # bag
    ["###  ", "###  ", "###  ", "###  ", "#####", "#####", " ####"],  # boot
]


def _glyph_canvases(glyphs: list[list[str]], canvas: int = 20) -> np.ndarray:
    """(10, canvas, canvas) float32 glyph images, nearest-upscaled, blurred."""
    out = np.zeros((len(glyphs), canvas, canvas), np.float32)
    for i, g in enumerate(glyphs):
        bitmap = np.array(
            [[1.0 if ch == "#" else 0.0 for ch in row] for row in g], np.float32
        )
        # nearest-neighbour upscale 5x7 -> canvas x canvas (aspect stretched)
        ys = np.clip((np.arange(canvas) * bitmap.shape[0]) // canvas, 0, 6)
        xs = np.clip((np.arange(canvas) * bitmap.shape[1]) // canvas, 0, 4)
        img = bitmap[np.ix_(ys, xs)]
        # 3x3 box blur for soft edges (ink spread like anti-aliased pen)
        padded = np.pad(img, 1)
        img = sum(
            padded[dy : dy + canvas, dx : dx + canvas]
            for dy in range(3)
            for dx in range(3)
        ) / 9.0
        out[i] = img
    return out


def _random_affine(rng: np.random.Generator, n: int) -> np.ndarray:
    """(n, 2, 3) inverse affine maps: output pixel -> glyph-canvas coords."""
    angle = rng.uniform(-0.25, 0.25, n)  # radians, ~±14°
    scale = rng.uniform(0.75, 1.1, n)
    shear = rng.uniform(-0.15, 0.15, n)
    tx = rng.uniform(-3.0, 3.0, n)
    ty = rng.uniform(-3.0, 3.0, n)
    ca, sa = np.cos(angle) / scale, np.sin(angle) / scale
    mats = np.zeros((n, 2, 3), np.float32)
    mats[:, 0, 0] = ca
    mats[:, 0, 1] = sa + shear
    mats[:, 1, 0] = -sa
    mats[:, 1, 1] = ca
    mats[:, 0, 2] = tx
    mats[:, 1, 2] = ty
    return mats


def _render(
    glyphs: np.ndarray, labels: np.ndarray, rng: np.random.Generator, size: int = 28
) -> np.ndarray:
    """Warp each sample's glyph into a size x size image. Vectorized bilinear."""
    n = labels.shape[0]
    canvas = glyphs.shape[1]
    mats = _random_affine(rng, n)
    yy, xx = np.meshgrid(np.arange(size), np.arange(size), indexing="ij")
    # center both frames, then apply per-sample inverse map
    oy = yy.ravel().astype(np.float32) - (size - 1) / 2
    ox = xx.ravel().astype(np.float32) - (size - 1) / 2
    coords = np.stack([oy, ox, np.ones_like(ox)])  # (3, P)
    src = mats @ coords  # (n, 2, P)
    sy = src[:, 0] + (canvas - 1) / 2
    sx = src[:, 1] + (canvas - 1) / 2
    y0 = np.floor(sy).astype(np.int32)
    x0 = np.floor(sx).astype(np.int32)
    wy = sy - y0
    wx = sx - x0
    imgs = glyphs[labels]  # (n, canvas, canvas)
    flat = imgs.reshape(n, -1)

    def gather(yi, xi):
        valid = (yi >= 0) & (yi < canvas) & (xi >= 0) & (xi < canvas)
        idx = np.clip(yi, 0, canvas - 1) * canvas + np.clip(xi, 0, canvas - 1)
        return np.take_along_axis(flat, idx, axis=1) * valid

    val = (
        gather(y0, x0) * (1 - wy) * (1 - wx)
        + gather(y0, x0 + 1) * (1 - wy) * wx
        + gather(y0 + 1, x0) * wy * (1 - wx)
        + gather(y0 + 1, x0 + 1) * wy * wx
    )
    out = val.reshape(n, size, size)
    out *= rng.uniform(0.7, 1.0, (n, 1, 1)).astype(np.float32)
    out += rng.normal(0.0, 0.06, out.shape).astype(np.float32)
    return np.clip(out, 0.0, 1.0)


def _make_glyph_dataset(
    glyphs_src: list[list[str]], n: int, seed: int, split: int
) -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.Generator(np.random.Philox(key=[seed, split]))
    glyphs = _glyph_canvases(glyphs_src)
    labels = rng.integers(0, 10, n, dtype=np.int64)
    images = _render(glyphs, labels, rng)
    return (images * 255).astype(np.uint8)[..., None], labels.astype(np.int32)


def synthetic_mnist(n: int, seed: int = 0, split: int = 0):
    """(images uint8 [n,28,28,1], labels int32 [n]) — procedural digits."""
    return _make_glyph_dataset(_DIGIT_GLYPHS, n, seed, split)


def synthetic_fashion_mnist(n: int, seed: int = 0, split: int = 1):
    return _make_glyph_dataset(_FASHION_GLYPHS, n, seed, split + 100)


def synthetic_cifar10(n: int, seed: int = 0, split: int = 0):
    """(images uint8 [n,32,32,3], labels int32 [n]).

    Class signal = class-specific oriented sinusoid gratings + a class hue,
    randomized in phase/contrast, plus broadband noise. A small conv net
    separates these easily; a linear probe does not (phases are random), so
    it exercises real representation learning.
    """
    size = 32
    rng = np.random.Generator(np.random.Philox(key=[seed, 1000 + split]))
    labels = rng.integers(0, 10, n, dtype=np.int64)
    yy, xx = np.meshgrid(
        np.linspace(0, 2 * np.pi, size, dtype=np.float32),
        np.linspace(0, 2 * np.pi, size, dtype=np.float32),
        indexing="ij",
    )
    # class k -> frequency (1 + k//2), orientation (k * 36°)
    ks = labels.astype(np.float32)
    freq = (1.0 + ks // 2)[:, None, None]
    theta = (ks * (np.pi / 5.0))[:, None, None]
    phase = rng.uniform(0, 2 * np.pi, (n, 1, 1)).astype(np.float32)
    proj = np.cos(theta) * yy[None] + np.sin(theta) * xx[None]
    grating = np.sin(freq * proj + phase)
    contrast = rng.uniform(0.4, 1.0, (n, 1, 1)).astype(np.float32)
    lum = 0.5 + 0.35 * contrast * grating
    hue = (ks[:, None, None] / 10.0 + rng.uniform(-0.03, 0.03, (n, 1, 1))) % 1.0
    # cheap HSV->RGB with s=0.6, v=lum
    h6 = (hue * 6.0) % 6.0
    c = 0.6 * lum
    x_ = c * (1 - np.abs(h6 % 2 - 1))
    m = lum - c
    zeros = np.zeros_like(c)
    sector = h6.astype(np.int32) % 6
    rgb_by_sector = np.stack(
        [
            np.stack([c, x_, zeros], -1),
            np.stack([x_, c, zeros], -1),
            np.stack([zeros, c, x_], -1),
            np.stack([zeros, x_, c], -1),
            np.stack([x_, zeros, c], -1),
            np.stack([c, zeros, x_], -1),
        ]
    )  # (6, n, H, W, 3)
    img = np.take_along_axis(
        rgb_by_sector, sector[None, ..., None].repeat(3, -1), axis=0
    )[0] + m[..., None]
    img += rng.normal(0, 0.05, img.shape).astype(np.float32)
    img = np.clip(img, 0, 1)
    return (img * 255).astype(np.uint8), labels.astype(np.int32)
