"""On-device data augmentation, fused into the compiled step.

The reference had no augmentation (MNIST feed_dict of raw pixels); the
CIFAR rungs of the ladder (BASELINE.md configs 4-5) need the standard
pad-crop-flip recipe to reach competitive accuracy. TPU-native design:
augmentation is pure jax on the ALREADY-SHARDED uint8 batch inside jit —
each device augments only its slice, the host does nothing, and XLA fuses
the gather/select chain into the input pipeline of the first conv.

All ops are static-shape (pad + dynamic_slice via per-example gather
indices) — no data-dependent shapes, scan/vmap-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def random_crop_flip(
    key: jax.Array,
    images: jax.Array,
    *,
    pad: int = 4,
    flip: bool = True,
) -> jax.Array:
    """Pad-reflect by `pad`, random-crop back to HxW, random horizontal
    flip. [B,H,W,C] any dtype -> same shape/dtype.

    Index-arithmetic formulation instead of per-example dynamic_slice:
    crops become one fused gather, which XLA tiles well on TPU (a vmapped
    dynamic_slice would lower to B scalar-offset slices).
    """
    b, h, w, c = images.shape
    k_crop, k_flip = jax.random.split(key)
    padded = jnp.pad(
        images, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect"
    )
    # per-example crop origins in [0, 2*pad]
    oy, ox = jax.random.randint(k_crop, (2, b), 0, 2 * pad + 1)
    rows = oy[:, None] + jnp.arange(h)[None, :]  # [B,H]
    cols = ox[:, None] + jnp.arange(w)[None, :]  # [B,W]
    out = padded[jnp.arange(b)[:, None, None], rows[:, :, None],
                 cols[:, None, :], :]
    if flip:
        do = jax.random.bernoulli(k_flip, 0.5, (b,))
        out = jnp.where(do[:, None, None, None], out[:, :, ::-1, :], out)
    return out
