"""Native (C++) input pipeline: background batch assembly + prefetch ring.

See loader.cc for the design; `NativeBatcher` is the drop-in alternative to
`pipeline.ShardedBatcher` with host-side gather moved off the critical path
onto a C++ producer thread. Falls back is the caller's choice — construction
raises if the toolchain is unavailable."""

from dist_mnist_tpu.data.native.batcher import NativeBatcher, build_library

__all__ = ["NativeBatcher", "build_library"]
