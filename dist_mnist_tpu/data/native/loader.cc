// Native threaded batch prefetcher — the input-pipeline role that TF's
// C++ queue runners played under `DataSet.next_batch` (SURVEY.md §2.1 row
// 2, §2.3 rows 11-12): batch assembly (shuffled gather of rows into a
// contiguous buffer) runs on background producer threads in C++, decoupled
// from the Python consumer by a bounded ring buffer, so host-side input
// work overlaps device compute instead of sitting on the step's critical
// path.
//
// Determinism: epoch shuffles are Fisher-Yates driven by splitmix64 seeded
// with (seed, epoch) — identical across instances/processes, so multi-host
// consumers slice disjoint ranges of the same permutation (the same
// contract as data/pipeline.epoch_batches, with a different — but equally
// pinned — PRNG).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <numeric>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

void shuffle_epoch(std::vector<int64_t>& idx, uint64_t seed, uint64_t epoch) {
  std::iota(idx.begin(), idx.end(), 0);
  uint64_t s = seed * 0x9E3779B97F4A7C15ull + epoch + 1;
  for (int64_t i = (int64_t)idx.size() - 1; i > 0; --i) {
    const int64_t j = (int64_t)(splitmix64(s) % (uint64_t)(i + 1));
    std::swap(idx[i], idx[j]);
  }
}

class Loader {
 public:
  Loader(const uint8_t* images, const int32_t* labels, int64_t n,
         int64_t row_bytes, int64_t batch, uint64_t seed, int depth,
         int64_t slice_begin, int64_t slice_size, int64_t start_step)
      : images_(images),
        labels_(labels),
        n_(n),
        row_bytes_(row_bytes),
        batch_(batch),
        seed_(seed),
        depth_(depth),
        slice_begin_(slice_begin),
        slice_size_(slice_size > 0 ? slice_size : batch),
        start_step_(start_step),
        slots_(depth) {
    for (auto& s : slots_) {
      s.img.resize((size_t)(slice_size_)*row_bytes_);
      s.lab.resize((size_t)slice_size_);
    }
    producer_ = std::thread([this] { produce(); });
  }

  ~Loader() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
      cv_.notify_all();
    }
    if (producer_.joinable()) producer_.join();
  }

  // Blocks for the next batch slice; copies into caller buffers. Returns
  // the global step index of the batch, or -1 after close().
  int64_t next(uint8_t* img_out, int32_t* lab_out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return stop_ || head_ < tail_; });
    if (stop_ && head_ >= tail_) return -1;
    Slot& s = slots_[head_ % depth_];
    std::memcpy(img_out, s.img.data(), s.img.size());
    std::memcpy(lab_out, s.lab.data(), s.lab.size() * sizeof(int32_t));
    const int64_t step = start_step_ + head_++;
    cv_.notify_all();
    return step;
  }

  void close() {
    std::unique_lock<std::mutex> lk(mu_);
    stop_ = true;
    cv_.notify_all();
  }

 private:
  struct Slot {
    std::vector<uint8_t> img;
    std::vector<int32_t> lab;
  };

  void produce() {
    std::vector<int64_t> perm((size_t)n_);
    const int64_t per_epoch = n_ / batch_;
    // resume-aware: position is a pure function of step, so a restored
    // trainer passes start_step and the stream continues exactly where the
    // pre-preemption run left off (mirrors pipeline.ShardedBatcher).
    uint64_t epoch = (uint64_t)(start_step_ / per_epoch);
    shuffle_epoch(perm, seed_, epoch);
    for (int64_t step = start_step_;; ++step) {
      const int64_t in_epoch = step % per_epoch;
      if (step > start_step_ && in_epoch == 0)
        shuffle_epoch(perm, seed_, ++epoch);
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || tail_ - head_ < depth_; });
        if (stop_) return;
      }
      Slot& s = slots_[tail_ % depth_];
      const int64_t base = in_epoch * batch_ + slice_begin_;
      for (int64_t r = 0; r < slice_size_; ++r) {
        const int64_t src = perm[(size_t)(base + r)];
        std::memcpy(s.img.data() + (size_t)r * row_bytes_,
                    images_ + (size_t)src * row_bytes_, (size_t)row_bytes_);
        s.lab[(size_t)r] = labels_[src];
      }
      {
        std::unique_lock<std::mutex> lk(mu_);
        ++tail_;
        cv_.notify_all();
      }
    }
  }

  const uint8_t* images_;
  const int32_t* labels_;
  const int64_t n_, row_bytes_, batch_;
  const uint64_t seed_;
  const int depth_;
  const int64_t slice_begin_, slice_size_, start_step_;
  std::vector<Slot> slots_;
  std::mutex mu_;
  std::condition_variable cv_;
  int64_t head_ = 0, tail_ = 0;
  bool stop_ = false;
  std::thread producer_;
};

}  // namespace

extern "C" {

void* loader_create(const uint8_t* images, const int32_t* labels, int64_t n,
                    int64_t row_bytes, int64_t batch, uint64_t seed,
                    int depth, int64_t slice_begin, int64_t slice_size,
                    int64_t start_step) {
  if (batch > n || batch <= 0 || depth <= 0 || start_step < 0) return nullptr;
  return new Loader(images, labels, n, row_bytes, batch, seed, depth,
                    slice_begin, slice_size, start_step);
}
int64_t loader_next(void* l, uint8_t* img, int32_t* lab) {
  return static_cast<Loader*>(l)->next(img, lab);
}
void loader_close(void* l) { static_cast<Loader*>(l)->close(); }
void loader_destroy(void* l) { delete static_cast<Loader*>(l); }

}  // extern "C"
