"""ctypes wrapper over the native prefetching loader."""

from __future__ import annotations

import ctypes
import logging
from pathlib import Path

import numpy as np

from dist_mnist_tpu.utils.native_build import build_shared_lib, load_lib

log = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "loader.cc"
_LIB = Path(__file__).parent / "libloader.so"


def build_library(force: bool = False) -> Path:
    return build_shared_lib(_SRC, _LIB, force=force)


def _get_lib():
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64 = ctypes.c_int64
    return load_lib(_SRC, _LIB, {
        "loader_create": ([u8p, i32p, i64, i64, i64, ctypes.c_uint64,
                           ctypes.c_int, i64, i64, i64], ctypes.c_void_p),
        "loader_next": ([ctypes.c_void_p, u8p, i32p], i64),
        "loader_close": ([ctypes.c_void_p], None),
        "loader_destroy": ([ctypes.c_void_p], None),
    })


class NativeBatcher:
    """Deterministic shuffled epochs, assembled+prefetched in C++.

    Multi-host: every process sees the same permutation (seeded shuffle in
    the library) and extracts its own disjoint slice of each global batch
    (slice_begin/slice_size), mirroring ShardedBatcher's contract. Iterating
    yields device-sharded batches via pipeline.shard_batch.
    """

    def __init__(self, dataset, global_batch: int, mesh, *, seed: int = 0,
                 prefetch_depth: int = 4, start_step: int = 0):
        import jax

        self._ctor_args = (dataset, global_batch, mesh)
        self._ctor_kwargs = dict(seed=seed, prefetch_depth=prefetch_depth)

        n = dataset.train_images.shape[0]
        if global_batch > n:
            raise ValueError(f"global batch {global_batch} > dataset {n}")
        n_proc, pid = jax.process_count(), jax.process_index()
        if global_batch % n_proc:
            raise ValueError("global batch must divide across processes")
        self.local = global_batch // n_proc
        # keep references so the C++ side's borrowed pointers stay alive
        self._images = np.ascontiguousarray(dataset.train_images)
        self._labels = np.ascontiguousarray(dataset.train_labels, np.int32)
        self._row_bytes = int(self._images[0].nbytes)
        self._img_shape = self._images.shape[1:]
        self.mesh = mesh
        lib = _get_lib()
        self._lib = lib
        self._h = lib.loader_create(
            self._images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self._labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, self._row_bytes, global_batch, seed, prefetch_depth,
            pid * self.local, self.local, start_step,
        )
        if not self._h:
            raise RuntimeError("loader_create failed (bad batch/depth)")

    def next_local(self) -> tuple[np.ndarray, np.ndarray, int]:
        """(images uint8 [local,...], labels int32 [local], step) — host."""
        img = np.empty((self.local, *self._img_shape), np.uint8)
        lab = np.empty((self.local,), np.int32)
        step = self._lib.loader_next(
            self._h,
            img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lab.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if step < 0:
            raise StopIteration
        return img, lab, int(step)

    def host_batches(self):
        """Host-side half of the stream (numpy, pre-placement) — the same
        split ShardedBatcher.host_batches makes, so `DevicePrefetcher`
        (data/prefetch.py) can issue the sharded transfer in its worker
        on top of the C++ assembly ring."""
        while True:
            try:
                img, lab, _ = self.next_local()
            except StopIteration:
                return
            yield {"image": img, "label": lab}

    def __iter__(self):
        from dist_mnist_tpu.data.pipeline import shard_batch

        for batch in self.host_batches():
            yield shard_batch(batch, self.mesh)

    def at_step(self, step: int) -> "NativeBatcher":
        """A fresh batcher positioned at `step` — non-destructive, matching
        ShardedBatcher.at_step (this instance keeps streaming; its producer
        thread is reclaimed on GC)."""
        return NativeBatcher(*self._ctor_args, **self._ctor_kwargs,
                             start_step=step)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.loader_close(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.loader_destroy(self._h)
                self._h = None
        except Exception:
            pass
