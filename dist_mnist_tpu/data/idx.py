"""IDX file codec — the MNIST on-disk format.

The reference consumed this format through
`input_data.read_data_sets(FLAGS.data_dir, one_hot=True)` (SURVEY.md §0.1
step 1; the module is removed from TF 2.x). This is a clean-room codec for
the same files: magic = two zero bytes, a dtype code, a rank byte, then
big-endian uint32 dims, then row-major data. Transparent .gz support because
the canonical distribution ships gzipped.
"""

from __future__ import annotations

import gzip
import struct
from pathlib import Path

import numpy as np

_DTYPES: dict[int, np.dtype] = {
    0x08: np.dtype(">u1"),
    0x09: np.dtype(">i1"),
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}
_CODES = {v.newbyteorder("="): k for k, v in _DTYPES.items()}


def _open(path: str | Path, mode: str):
    path = Path(path)
    if path.suffix == ".gz":
        return gzip.open(path, mode)
    return open(path, mode)


def read_idx(path: str | Path) -> np.ndarray:
    """Parse one IDX file (optionally .gz) into a native-endian ndarray."""
    with _open(path, "rb") as f:
        header = f.read(4)
        if len(header) != 4 or header[0] != 0 or header[1] != 0:
            raise ValueError(f"{path}: not an IDX file (bad magic {header!r})")
        code, ndim = header[2], header[3]
        if code not in _DTYPES:
            raise ValueError(f"{path}: unknown IDX dtype code 0x{code:02x}")
        dims_raw = f.read(4 * ndim)
        if len(dims_raw) != 4 * ndim:
            raise ValueError(f"{path}: truncated IDX header")
        dims = struct.unpack(f">{ndim}I", dims_raw)
        dtype = _DTYPES[code]
        count = int(np.prod(dims, dtype=np.int64)) if ndim else 1
        raw = f.read(count * dtype.itemsize)
        if len(raw) != count * dtype.itemsize:
            raise ValueError(
                f"{path}: truncated payload ({len(raw)} bytes, "
                f"expected {count * dtype.itemsize})"
            )
        arr = np.frombuffer(raw, dtype=dtype).reshape(dims)
        return arr.astype(dtype.newbyteorder("="))


def write_idx(path: str | Path, arr: np.ndarray) -> None:
    """Write an ndarray as IDX (gzipped when path ends in .gz)."""
    arr = np.ascontiguousarray(arr)
    key = np.dtype(arr.dtype).newbyteorder("=")
    if key not in _CODES:
        raise ValueError(f"dtype {arr.dtype} not representable in IDX")
    if arr.ndim > 255:
        raise ValueError("IDX rank limit is 255")
    with _open(path, "wb") as f:
        f.write(bytes([0, 0, _CODES[key], arr.ndim]))
        f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
        f.write(arr.astype(_DTYPES[_CODES[key]]).tobytes())
