"""Batching + device placement.

Replaces `DataSet.next_batch(batch_size)` (SURVEY.md §2.1 row 2) and the
feed_dict hop (§3.3: every batch crossed Py→C++→gRPC per step). Two paths:

- `ShardedBatcher`: host-side deterministic shuffled epochs; each process
  loads only its slice of the global batch and assembles the global array
  with `jax.make_array_from_process_local_data` (multi-host correct).
- `DeviceDataset`: the whole dataset resident in HBM (MNIST is ~11 MB as
  uint8 — SURVEY.md §7 hard part (e): input must never bottleneck the <60 s
  target), with batch *sampling fused into the jit-compiled step* so the
  host does zero per-step work.

Determinism: shuffle order = Philox(key=[seed, epoch]) permutation, identical
on every host; each host reads its disjoint contiguous slice.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import DATA_AXIS, compat_shard_map
from dist_mnist_tpu.data.datasets import Dataset


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim sharded over the data axis, rest replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def shard_batch(batch: dict[str, np.ndarray], mesh: Mesh) -> dict[str, jax.Array]:
    """Host-local batch slices -> global device arrays sharded over `data`.

    On one process this is a plain device_put with a sharded layout; on many
    it stitches each process's slice into one global array (the SPMD
    equivalent of every worker feeding its own feed_dict — §0.1 step 9).
    """
    sharding = batch_sharding(mesh)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, sharding) for k, v in batch.items()}
    return {
        k: jax.make_array_from_process_local_data(sharding, v)
        for k, v in batch.items()
    }


def epoch_batches(
    n: int, batch_size: int, *, seed: int, epoch: int, drop_remainder: bool = True
) -> Iterator[np.ndarray]:
    """Deterministic shuffled index batches for one epoch (all hosts agree)."""
    rng = np.random.Generator(np.random.Philox(key=[seed, epoch]))
    perm = rng.permutation(n)
    end = (n // batch_size) * batch_size if drop_remainder else n
    for i in range(0, end, batch_size):
        yield perm[i : i + batch_size]


@dataclasses.dataclass
class ShardedBatcher:
    """Infinite deterministic iterator of device-sharded train batches.

    Each process materializes only rows for its own slice of the global
    batch; labels ride along. Normalization (uint8 -> [0,1] float32) happens
    on device inside the step, not here.
    """

    dataset: Dataset
    global_batch: int
    mesh: Mesh
    seed: int = 0
    start_step: int = 0

    def at_step(self, step: int) -> "ShardedBatcher":
        """A batcher positioned at `step` (TrainLoop recovery re-seek)."""
        return dataclasses.replace(self, start_step=step)

    def host_batches(self) -> Iterator[dict[str, np.ndarray]]:
        """Host-side half of the stream: this process's numpy slice of each
        global batch, BEFORE device placement. Split out from `__iter__` so
        `DevicePrefetcher` (data/prefetch.py) can pull host batches in its
        worker and issue the sharded transfer off the training thread."""
        n = self.dataset.train_images.shape[0]
        n_proc, pid = jax.process_count(), jax.process_index()
        if self.global_batch % n_proc:
            raise ValueError("global batch must divide evenly across processes")
        if self.global_batch > n:
            raise ValueError(
                f"global batch {self.global_batch} exceeds dataset size {n}: "
                "an epoch yields zero batches"
            )
        local = self.global_batch // n_proc
        # resume exactly where a restored step left off — the reference
        # could not (next_batch position lived in process memory and died
        # with it; SURVEY.md §3.5 restores variables only). Position is a
        # pure function of step, so restart = seek.
        steps_per_epoch = n // self.global_batch
        epoch = self.start_step // steps_per_epoch
        skip = self.start_step % steps_per_epoch
        while True:
            for b, idx in enumerate(epoch_batches(
                n, self.global_batch, seed=self.seed, epoch=epoch
            )):
                if b < skip:
                    continue
                mine = idx[pid * local : (pid + 1) * local]
                yield {
                    "image": self.dataset.train_images[mine],
                    "label": self.dataset.train_labels[mine],
                }
            skip = 0
            epoch += 1

    def __iter__(self) -> Iterator[dict[str, jax.Array]]:
        for batch in self.host_batches():
            yield shard_batch(batch, self.mesh)


class DeviceDataset:
    """Whole dataset in HBM; sampling is part of the compiled step.

    `sample(rngkey)` is meant to be called INSIDE jit: it draws a with-
    replacement batch via on-device RNG, so step latency has no host
    component at all. Images stay uint8 in HBM (4x less capacity/bandwidth
    than f32) and are normalized after the gather, on the sharded batch.

    Residency layout: images are stored FLATTENED to [N, H*W*C] and
    reshaped to NHWC after the gather. Reason (measured on v5e): XLA tiles
    a resident uint8 NHWC array over its two minor dims — for CIFAR
    u8[60000,32,32,3] the (8,128)(4,1) tiling pads 32x3 out to a 4.0x
    expansion and inserts a 703 MB relayout copy of the dataset into every
    compiled program that gathers from it (OOM-report evidence: "copy.257 =
    copy(data_0_.1), extra memory due to padding 527 MB"). Flat rows tile
    along H*W*C with no padding, so the gather reads the resident array
    in place: zero copy, zero padding, identical numerics.

    Two residency modes:
    - `shard=False` (default): dataset REPLICATED per device — right for
      MNIST-class sizes (~11 MB), zero-communication gathers.
    - `shard=True`: dataset rows SHARDED over the `data` axis — per-device
      HBM cost is 1/data_axis of the dataset, so capacity scales with the
      mesh instead of capping at one chip's HBM. Each device samples from
      its own shard only (after a one-time deterministic global shuffle, so
      shards are i.i.d.); the gather stays device-local — no collectives.
    """

    def __init__(self, dataset: Dataset, mesh: Mesh, *, shard: bool = False,
                 seed: int = 0):
        self.mesh = mesh
        self.sharded = shard
        self.n = dataset.train_images.shape[0]
        images, labels = dataset.train_images, dataset.train_labels
        self.image_shape = images.shape[1:]  # NHWC restored post-gather
        images = images.reshape(self.n, -1)  # flat rows: see class docstring
        if shard:
            data_axis = mesh.shape[DATA_AXIS]
            # one-time global shuffle so class structure in file order
            # (e.g. class-sorted synthetic sets) cannot skew any shard
            perm = np.random.Generator(
                np.random.Philox(key=[seed, 0xD5])
            ).permutation(self.n)
            keep = (self.n // data_axis) * data_axis  # equal shards
            images, labels = images[perm[:keep]], labels[perm[:keep]]
            self.n = keep
            placement = NamedSharding(mesh, P(DATA_AXIS))
        else:
            placement = NamedSharding(mesh, P())  # gather needs all rows
        if jax.process_count() == 1:
            self.images = jax.device_put(images, placement)
            self.labels = jax.device_put(labels, placement)
        else:
            # multi-process: device_put cannot target non-addressable
            # devices; every process holds the full (identically-loaded)
            # array, so the callback hands each addressable shard its
            # global slice (same reason shard_batch branches above)
            put = lambda arr: jax.make_array_from_callback(
                arr.shape, placement, lambda idx, a=arr: a[idx]
            )
            self.images = put(images)
            self.labels = put(labels)

    @property
    def arrays(self) -> tuple[jax.Array, jax.Array]:
        """The resident arrays, for passing INTO a jitted step as explicit
        arguments (required in multi-process runs: closing over an array
        that spans non-addressable devices is illegal)."""
        return self.images, self.labels

    def sample(self, key: jax.Array, batch: int) -> dict[str, jax.Array]:
        return self.sample_arrays(key, batch, self.images, self.labels)

    def sample_arrays(self, key: jax.Array, batch: int, images, labels
                      ) -> dict[str, jax.Array]:
        """Sampling body usable on traced arguments (images/labels may be
        jit tracers — see `arrays`)."""
        if self.sharded:
            return self._sample_sharded(key, batch, images, labels)
        idx = jax.random.randint(key, (batch,), 0, self.n)
        sharded = batch_sharding(self.mesh)
        img = jax.lax.with_sharding_constraint(jnp.take(images, idx, 0), sharded)
        lab = jax.lax.with_sharding_constraint(jnp.take(labels, idx, 0), sharded)
        return {"image": img.reshape(batch, *self.image_shape), "label": lab}

    def _sample_sharded(self, key: jax.Array, batch: int, images, labels
                        ) -> dict[str, jax.Array]:
        """Each device draws its slice of the batch from its LOCAL rows —
        the gather never leaves the device (shard_map over `data`)."""
        data_axis = self.mesh.shape[DATA_AXIS]
        if batch % data_axis:
            raise ValueError(f"batch {batch} % data axis {data_axis} != 0")
        per_dev = batch // data_axis

        def local_sample(key, images, labels):
            k = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
            idx = jax.random.randint(k, (per_dev,), 0, images.shape[0])
            return jnp.take(images, idx, 0), jnp.take(labels, idx, 0)

        img, lab = compat_shard_map(
            local_sample,
            mesh=self.mesh,
            in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        )(key, images, labels)
        return {"image": img.reshape(batch, *self.image_shape), "label": lab}
