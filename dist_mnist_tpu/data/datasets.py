"""Named dataset registry: disk-first, synthetic-fallback.

Mirrors the contract of the reference's
`input_data.read_data_sets(FLAGS.data_dir, one_hot=True)` (SURVEY.md §0.1
step 1): given a --data_dir it loads the canonical 4-IDX-file layout (MNIST /
Fashion-MNIST) or the CIFAR-10 python pickles; when the files are absent it
synthesizes a deterministic procedural twin instead of downloading (this
environment has no egress). Labels stay integer; one-hot is applied in the
loss (ops/losses.py), not the pipeline.
"""

from __future__ import annotations

import dataclasses
import logging
import pickle
import tarfile
from pathlib import Path

import numpy as np

from dist_mnist_tpu.data import synthetic
from dist_mnist_tpu.data.idx import read_idx

log = logging.getLogger(__name__)

_MNIST_FILES = {
    "train_x": "train-images-idx3-ubyte",
    "train_y": "train-labels-idx1-ubyte",
    "test_x": "t10k-images-idx3-ubyte",
    "test_y": "t10k-labels-idx1-ubyte",
}


@dataclasses.dataclass
class Dataset:
    """In-memory dataset. Images uint8 NHWC; labels int32 [N]."""

    name: str
    train_images: np.ndarray
    train_labels: np.ndarray
    test_images: np.ndarray
    test_labels: np.ndarray
    num_classes: int = 10
    synthetic: bool = False

    @property
    def image_shape(self) -> tuple[int, ...]:
        return self.train_images.shape[1:]

    def normalized(self, arr: np.ndarray) -> np.ndarray:
        """uint8 [0,255] -> float32 [0,1], matching the reference pipeline's
        1/255 scaling (old DataSet applied it at load; we defer to use time
        so the resident copy stays uint8 = 4x less HBM)."""
        return arr.astype(np.float32) / 255.0


def _find_idx(data_dir: Path, stem: str) -> Path | None:
    for cand in (data_dir / stem, data_dir / f"{stem}.gz"):
        if cand.exists():
            return cand
    return None


def _load_idx_quad(data_dir: Path) -> dict[str, np.ndarray] | None:
    paths = {k: _find_idx(data_dir, v) for k, v in _MNIST_FILES.items()}
    if not all(paths.values()):
        return None
    out = {k: read_idx(p) for k, p in paths.items()}
    out["train_x"] = out["train_x"][..., None]  # HW -> HWC
    out["test_x"] = out["test_x"][..., None]
    return out


def _load_cifar10_dir(data_dir: Path) -> dict[str, np.ndarray] | None:
    batch_dir = data_dir / "cifar-10-batches-py"
    if not batch_dir.exists():
        tars = list(data_dir.glob("cifar-10-python.tar.gz"))
        if not tars:
            return None
        with tarfile.open(tars[0]) as tf:
            tf.extractall(data_dir, filter="data")
        if not batch_dir.exists():
            return None

    def load_batch(p: Path):
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x, np.asarray(d[b"labels"], np.int32)

    train = [load_batch(batch_dir / f"data_batch_{i}") for i in range(1, 6)]
    test_x, test_y = load_batch(batch_dir / "test_batch")
    return {
        "train_x": np.concatenate([t[0] for t in train]),
        "train_y": np.concatenate([t[1] for t in train]),
        "test_x": test_x,
        "test_y": test_y,
    }


def _synth(name: str, n_train: int, n_test: int, seed: int):
    gen = {
        "mnist": synthetic.synthetic_mnist,
        "fashion_mnist": synthetic.synthetic_fashion_mnist,
        "cifar10": synthetic.synthetic_cifar10,
    }[name]
    tx, ty = gen(n_train, seed=seed, split=0)
    vx, vy = gen(n_test, seed=seed, split=7)
    return {"train_x": tx, "train_y": ty, "test_x": vx, "test_y": vy}


def _cache_paths(data_dir: Path, name: str):
    if name == "cifar10":
        return None  # cached as npz below
    prefix = "" if name == "mnist" else f"{name}."
    return {k: data_dir / f"{prefix}{v}" for k, v in _MNIST_FILES.items()}


def _synth_marker(data_dir: Path, name: str) -> Path:
    return data_dir / f".{name}.synthetic-twin"


def _write_synth_cache(data_dir: Path, name: str, raw: dict) -> None:
    """Persist the synthesized twin in the dataset's canonical on-disk
    format so later runs (and other tools) load instead of regenerate
    (~15 s for 60k MNIST images) — the analogue of read_data_sets' download
    cache in --data_dir. Writes are atomic (tmp + rename) so an interrupted
    or concurrent run can never leave a torn file behind, and a marker file
    records that these files are procedural, not the real dataset."""
    import os

    from dist_mnist_tpu.data.idx import write_idx

    data_dir.mkdir(parents=True, exist_ok=True)

    def atomic(path: Path, write_fn):
        tmp = path.with_name(path.name + f".tmp{os.getpid()}")
        try:
            write_fn(tmp)
            os.replace(tmp, path)
        finally:
            tmp.unlink(missing_ok=True)

    if name == "cifar10":
        atomic(data_dir / "cifar10_synth.npz",
               lambda p: np.savez(p.open("wb"), **raw))
    else:
        paths = _cache_paths(data_dir, name)
        atomic(paths["train_x"], lambda p: write_idx(p, raw["train_x"][..., 0]))
        atomic(paths["train_y"],
               lambda p: write_idx(p, raw["train_y"].astype(np.uint8)))
        atomic(paths["test_x"], lambda p: write_idx(p, raw["test_x"][..., 0]))
        atomic(paths["test_y"],
               lambda p: write_idx(p, raw["test_y"].astype(np.uint8)))
    _synth_marker(data_dir, name).touch()


def _load_fashion_or_mnist(data_dir: Path, name: str):
    """IDX quad; fashion files carry a `fashion_mnist.` prefix so both
    datasets can share one directory."""
    if name == "mnist":
        return _load_idx_quad(data_dir)
    paths = _cache_paths(data_dir, name)
    if not all(p.exists() or p.with_suffix(p.suffix + ".gz").exists()
               for p in paths.values()):
        return None
    from dist_mnist_tpu.data.idx import read_idx

    out = {k: read_idx(p if p.exists() else p.with_suffix(p.suffix + ".gz"))
           for k, p in paths.items()}
    out["train_x"] = out["train_x"][..., None]
    out["test_x"] = out["test_x"][..., None]
    return out


def _load_cifar10(data_dir: Path):
    npz = data_dir / "cifar10_synth.npz"
    if npz.exists():
        with np.load(npz) as z:
            return {k: z[k] for k in ("train_x", "train_y", "test_x", "test_y")}
    return _load_cifar10_dir(data_dir)


def load_dataset(
    name: str,
    data_dir: str | Path = "/tmp/mnist-data",
    *,
    seed: int = 0,
    synthetic_sizes: tuple[int, int] = (60_000, 10_000),
    cache_synthetic: bool = True,
) -> Dataset:
    """Load `name` from data_dir, else synthesize its procedural twin (and
    cache it to data_dir in the canonical on-disk format)."""
    if name not in DATASETS:
        raise KeyError(f"unknown dataset {name!r}; have {sorted(DATASETS)}")
    data_dir = Path(data_dir)
    raw = None
    if data_dir.exists():
        try:
            raw = (
                _load_cifar10(data_dir)
                if name == "cifar10"
                else _load_fashion_or_mnist(data_dir, name)
            )
        except (ValueError, OSError) as e:
            # torn/corrupt files (e.g. a cache write that raced an old
            # non-atomic writer) must not brick training — resynthesize
            log.warning("unreadable %s under %s (%s); falling back to "
                        "synthesis", name, data_dir, e)
            raw = None
    # files written by _write_synth_cache are procedural — keep the flag
    # true on cache reloads (the marker), but only regenerate when no
    # readable files exist at all
    is_synth = raw is None or _synth_marker(data_dir, name).exists()
    if raw is None:
        log.warning("%s not found under %s — using synthetic twin", name, data_dir)
        raw = _synth(name, *synthetic_sizes, seed)
        if cache_synthetic and synthetic_sizes == (60_000, 10_000):
            try:
                _write_synth_cache(data_dir, name, raw)
            except OSError as e:  # read-only data_dir is fine
                log.info("could not cache synthetic %s: %s", name, e)
    return Dataset(
        name=name,
        train_images=np.ascontiguousarray(raw["train_x"]),
        train_labels=raw["train_y"].astype(np.int32),
        test_images=np.ascontiguousarray(raw["test_x"]),
        test_labels=raw["test_y"].astype(np.int32),
        synthetic=is_synth,
    )


DATASETS = {
    "mnist": dict(image_shape=(28, 28, 1), num_classes=10),
    "fashion_mnist": dict(image_shape=(28, 28, 1), num_classes=10),
    "cifar10": dict(image_shape=(32, 32, 3), num_classes=10),
}
