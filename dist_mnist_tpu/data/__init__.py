"""Input pipeline.

Replaces the reference's MNIST input module (SURVEY.md §2.1 row 2:
`input_data.read_data_sets` + `DataSet.next_batch` — removed from TF 2.x):
- `idx.py` — our own IDX file codec (the 4-file MNIST on-disk format).
- `synthetic.py` — deterministic procedural datasets so every config runs
  (and converges) in an air-gapped environment with no downloads.
- `datasets.py` — named dataset registry (mnist / fashion_mnist / cifar10)
  with disk-first, synthetic-fallback loading.
- `pipeline.py` — deterministic shuffled batching, per-host sharding, and a
  device-resident fast path that fuses batch sampling into the jit step.
- `prefetch.py` — `DevicePrefetcher`: background worker issuing sharded
  H2D transfers `depth` batches ahead of the loop (overlapped input feed).
"""

from dist_mnist_tpu.data.idx import read_idx, write_idx
from dist_mnist_tpu.data.datasets import Dataset, load_dataset, DATASETS
from dist_mnist_tpu.data.pipeline import (
    epoch_batches,
    ShardedBatcher,
    DeviceDataset,
    shard_batch,
)
from dist_mnist_tpu.data.prefetch import DevicePrefetcher

__all__ = [
    "read_idx",
    "write_idx",
    "Dataset",
    "load_dataset",
    "DATASETS",
    "epoch_batches",
    "ShardedBatcher",
    "DeviceDataset",
    "DevicePrefetcher",
    "shard_batch",
]
