"""Built-in hooks, each mapped to its reference counterpart
(basic_session_run_hooks.py — SURVEY.md §2.4 row 18)."""

from __future__ import annotations

import inspect
import logging
import math
import time

import jax

from dist_mnist_tpu.hooks.base import Hook, EverySteps
from dist_mnist_tpu.obs import events as obs_events

log = logging.getLogger(__name__)


class NanLossError(RuntimeError):
    """≙ NanLossDuringTrainingError raised by NanTensorHook (:761)."""


class StopAtStepHook(Hook):
    """≙ StopAtStepHook (:393-453): stop at last_step or after num_steps."""

    def __init__(self, num_steps: int | None = None, last_step: int | None = None):
        if (num_steps is None) == (last_step is None):
            raise ValueError("exactly one of num_steps / last_step")
        self._num_steps = num_steps
        self._last_step = last_step

    def begin(self, loop):
        self._loop = loop
        if self._last_step is None:
            self._last_step = loop.initial_step + self._num_steps
        if loop.initial_step >= self._last_step:
            # restored at/past the limit: exit without training an extra step
            loop.request_stop("already at last step")

    def after_step(self, step, state, outputs):
        if step >= self._last_step:
            self._loop.request_stop("reached last step")


class StepCounterHook(Hook):
    """≙ StepCounterHook (:673-750): periodic steps/sec (+ examples/sec when
    batch size is known) — the BASELINE.md metric."""

    def __init__(self, every_steps: int = 100, batch_size: int | None = None,
                 writer=None):
        self._timer = EverySteps(every_steps=every_steps)
        self._batch = batch_size
        self._writer = writer
        self._last_step = None
        self._last_time = None
        self.last_rate = None  # exposed for bench harnesses

    def begin(self, loop):
        self._last_step = loop.initial_step
        self._last_time = time.monotonic()
        self._timer.prime(loop.initial_step)

    def after_step(self, step, state, outputs):
        if not self._timer.should_trigger(step):
            return
        now = time.monotonic()
        rate = (step - self._last_step) / max(now - self._last_time, 1e-9)
        self.last_rate = rate
        self._last_step, self._last_time = step, now
        self._timer.mark()
        msg = f"step {step}: {rate:.1f} steps/sec"
        if self._batch:
            msg += f", {rate * self._batch:.0f} examples/sec"
        log.info(msg)
        if self._writer:
            self._writer.scalar("steps_per_sec", rate, step)


class InputPipelineHook(Hook):
    """Input-stall attribution for the overlapped feed path (no reference
    counterpart — queue runners hid the cost instead of measuring it).

    Reads the loop's cumulative feed/runahead wait clocks (train/loop.py)
    and, when the batch source is a `DevicePrefetcher` (anything exposing
    `stats()`), the prefetch ring counters, and writes per-interval rates
    through the obs writers at its cadence:

      input/feed_stall_ms_per_step     host blocked pulling the next batch
      input/runahead_wait_ms_per_step  host blocked on the dispatch bound
      input/prefetch_occupancy         mean ring fill at consume time
      input/h2d_mbytes_per_step        bytes the worker pushed to devices

    A healthy overlapped pipeline shows near-zero feed stall and a ring
    occupancy near its depth; occupancy ~0 with high stall means the host
    batcher (not the device) is the bottleneck. `last` keeps the most
    recent values for bench harnesses (bench.py --input)."""

    def __init__(self, writer=None, every_steps: int = 100):
        self._writer = writer
        self._timer = EverySteps(every_steps=every_steps)
        self.last: dict[str, float] = {}
        self._base = None

    def begin(self, loop):
        self._loop = loop
        self._timer.prime(loop.initial_step)
        self._base = self._snapshot(loop.initial_step)

    def _snapshot(self, step):
        snap = {
            "step": step,
            "feed_wait_s": getattr(self._loop, "feed_wait_s", 0.0),
            "runahead_wait_s": getattr(self._loop, "runahead_wait_s", 0.0),
        }
        # re-read loop.batches each time: recovery re-seek replaces it (the
        # replacement prefetcher shares its stats object, so deltas hold)
        stats_fn = getattr(self._loop.batches, "stats", None)
        snap["prefetch"] = dict(stats_fn()) if callable(stats_fn) else None
        return snap

    def after_step(self, step, state, outputs):
        if not self._timer.should_trigger(step):
            return
        self._timer.mark()
        cur = self._snapshot(step)
        base, self._base = self._base, cur
        dsteps = max(1, step - base["step"])
        vals = {
            "input/feed_stall_ms_per_step":
                1e3 * (cur["feed_wait_s"] - base["feed_wait_s"]) / dsteps,
            "input/runahead_wait_ms_per_step":
                1e3 * (cur["runahead_wait_s"] - base["runahead_wait_s"])
                / dsteps,
        }
        if cur["prefetch"] is not None:
            p0 = base["prefetch"] or {}
            p = cur["prefetch"]
            vals["input/prefetch_occupancy"] = p["mean_occupancy"]
            vals["input/h2d_mbytes_per_step"] = (
                (p["h2d_bytes"] - p0.get("h2d_bytes", 0)) / dsteps / 2**20
            )
        self.last = vals
        if self._writer is not None:
            batch_write = getattr(self._writer, "scalars", None)
            if callable(batch_write):
                batch_write(vals, step)
            else:
                for k, v in vals.items():
                    self._writer.scalar(k, v, step)


class StepTimeHook(Hook):
    """Per-step wall-time percentiles from the loop's streaming histogram
    (train/loop.py `step_time_hist`, obs/hist.py). Publishes at a cadence
    so p50/p95/p99 land in the same sinks (and live registry) as every
    other scalar:

      step_time/p50_ms  step_time/p95_ms  step_time/p99_ms
      step_time/mean_ms

    The histogram itself can also be attached to a MetricRegistry for
    full-distribution /metrics exposition; this hook is the scalar-sink
    (CSV/TB) view of the same ladder."""

    def __init__(self, writer=None, every_steps: int = 100):
        self._writer = writer
        self._timer = EverySteps(every_steps=every_steps)
        self.last: dict[str, float] = {}

    def begin(self, loop):
        self._loop = loop
        self._timer.prime(loop.initial_step)

    def _emit(self, step):
        snap = self._loop.step_time_hist.snapshot()
        if not snap["count"]:
            return
        vals = {
            "step_time/p50_ms": snap["p50"],
            "step_time/p95_ms": snap["p95"],
            "step_time/p99_ms": snap["p99"],
            "step_time/mean_ms": snap["mean"],
        }
        self.last = vals
        if self._writer is not None:
            batch_write = getattr(self._writer, "scalars", None)
            if callable(batch_write):
                batch_write(vals, step)
            else:
                for k, v in vals.items():
                    self._writer.scalar(k, v, step)

    def after_step(self, step, state, outputs):
        if not self._timer.should_trigger(step):
            return
        self._timer.mark()
        self._emit(step)

    def end(self, state):
        # final-distribution summary even for runs shorter than the cadence
        self._emit(getattr(self._loop, "_host_step", 0))


class LoggingHook(Hook):
    """≙ LoggingTensorHook (:169): periodic metric prints. Syncs device
    scalars only at its cadence."""

    def __init__(self, every_steps: int = 100, keys: tuple[str, ...] | None = None):
        self._timer = EverySteps(every_steps=every_steps)
        self._keys = keys

    def begin(self, loop):
        self._timer.prime(loop.initial_step)

    def after_step(self, step, state, outputs):
        if not self._timer.should_trigger(step):
            return
        self._timer.mark()
        keys = self._keys or outputs.keys()
        # ONE device_get for every logged key: per-key float() was one
        # blocking sync per metric per cadence, serializing dispatch
        wanted = {k: outputs[k] for k in keys
                  if k in outputs and getattr(outputs[k], "size", 1) == 1}
        vals = jax.device_get(wanted)  # lint: ok[host-sync] one batched fetch per cadence
        parts = [f"{k}={float(v):.4f}" for k, v in vals.items()]  # lint: ok[host-sync] numpy scalars post-fetch
        log.info("step %d: %s", step, ", ".join(parts))


class NaNGuardHook(Hook):
    """≙ NanTensorHook (:761): abort (or just warn) on non-finite loss.

    The reference fetched the loss every step; syncing every step would
    serialize dispatch, so the default cadence is 25 — set 1 for parity.
    """

    def __init__(self, key: str = "loss", every_steps: int = 25,
                 fail_on_nan: bool = True):
        self._key = key
        self._timer = EverySteps(every_steps=every_steps)
        self._fail = fail_on_nan

    def begin(self, loop):
        self._loop = loop
        self._timer.prime(loop.initial_step)

    def after_step(self, step, state, outputs):
        if self._key not in outputs or not self._timer.should_trigger(step):
            return
        self._timer.mark()
        # explicit single fetch (float() on a device scalar is an implicit
        # blocking sync; keep the sync surface to one call per cadence)
        val = float(jax.device_get(outputs[self._key]))  # lint: ok[host-sync] one scalar per cadence, NaN check NEEDS the value
        if math.isfinite(val):
            return
        if self._fail:
            raise NanLossError(f"{self._key} is {val} at step {step}")
        log.warning("%s is %s at step %d; stopping", self._key, val, step)
        self._loop.request_stop("non-finite loss")


class CheckpointHook(Hook):
    """≙ CheckpointSaverHook (:524-670): save at begin (save-on-create,
    :585-602), on a step/secs cadence (:607-616), and at end (:618-623)."""

    def __init__(self, manager, every_steps: int | None = None,
                 every_secs: float | None = 600.0):
        self._mgr = manager
        self._timer = EverySteps(every_steps=every_steps, every_secs=every_secs)
        self._save_s = 0.0

    def begin(self, loop):
        self._loop = loop
        # save-on-create (:585-602): guarantees a restore point exists before
        # the first cadence trigger. Skipped when one ALREADY exists for the
        # loop's initial step (the restore that produced this state): the
        # save would dedupe anyway, but probing latest_step here avoids even
        # forking a snapshot on the async path. Blocks the first step only
        # as long as the manager's save() does — milliseconds under
        # AsyncSnapshotter, where the write rides the background path.
        self._timer.prime(loop.initial_step)
        latest = None
        probe = getattr(self._mgr, "latest_step", None)
        if probe is not None:
            try:
                latest = probe()
            except TypeError:  # duck-typed managers with odd signatures
                latest = None
        if latest is None or latest < loop.initial_step:
            self._mgr.save(loop.state)

    def after_step(self, step, state, outputs):
        if self._timer.should_trigger(step):
            self._timer.mark()
            # journal the save as a `checkpoint` span — HOST-SIDE DISPATCH
            # only (async managers return at the fork/handoff; the paired
            # `checkpoint_commit` event lands when the background write is
            # durable, so dispatch→durable shows as a real span in
            # scripts/fleet_trace.py). The save cadence IS the span's
            # cadence gate, and emit() is a no-op without a journal, so
            # the clock costs nothing extra.
            t0 = time.monotonic()
            self._mgr.save(state)
            dt = time.monotonic() - t0
            self._save_s += dt  # drained by the loop into goodput save_s
            obs_events.emit(
                "span", name="checkpoint", step=int(step),
                dur_ms=round(dt * 1e3, 3))
        # commit markers for async saves land the moment the write is
        # durable, not at the next cadence save — a kill inside the
        # cadence window must not quarantine a durable step
        flush = getattr(self._mgr, "flush_commits", None)
        if flush is not None:
            flush()

    def consume_save_s(self) -> float:
        """Hook-side save time since last drain (TrainLoop charges it to
        the goodput `save_s` bucket and keeps it out of productive)."""
        s, self._save_s = self._save_s, 0.0
        return s

    def end(self, state):
        self._mgr.save(state)
        self._mgr.wait()


class SummaryHook(Hook):
    """≙ SummarySaverHook (:793) + SummaryWriterCache: periodic summaries to
    a metric writer (obs/writers.py). Scalar outputs become scalar
    summaries; array outputs (e.g. the per-leaf `grad_norms` vector from
    `make_train_step(with_grad_norm=True)`) become histograms — the
    arbitrary-summary-proto parity the reference hook had beyond scalars.

    `param_histograms_every` additionally writes one histogram per PARAM
    LEAF on its own (slower) cadence — it pulls every param to the host, so
    it defaults off and should stay a few orders sparser than scalars.
    """

    def __init__(self, writer, every_steps: int = 100,
                 param_histograms_every: int | None = None):
        self._writer = writer
        self._timer = EverySteps(every_steps=every_steps)
        self._param_timer = (
            EverySteps(every_steps=param_histograms_every)
            if param_histograms_every else None
        )

    def begin(self, loop):
        self._timer.prime(loop.initial_step)
        if self._param_timer:
            self._param_timer.prime(loop.initial_step)

    def after_step(self, step, state, outputs):
        if self._param_timer and self._param_timer.should_trigger(step):
            self._param_timer.mark()
            self._write_param_histograms(step, state)
        if not self._timer.should_trigger(step):
            return
        self._timer.mark()
        # ONE device_get for the whole cadence — histograms AND scalars.
        # The per-key `float(v)` here was one blocking sync per metric per
        # cadence (the same serialized-dispatch bug LoggingHook fixed).
        fetched = jax.device_get(dict(outputs))  # lint: ok[host-sync] one batched fetch per cadence
        vals = {}
        for k, v in fetched.items():
            if getattr(v, "size", 1) > 1:
                self._write_histogram(k, v, step)
                continue
            try:
                vals[k] = float(v)  # lint: ok[host-sync] numpy scalar post-fetch
            except (TypeError, ValueError):
                pass
        batch_write = getattr(self._writer, "scalars", None)
        if callable(batch_write):
            batch_write(vals, step)
        else:
            for k, v in vals.items():
                self._writer.scalar(k, v, step)

    def _write_histogram(self, tag, values, step):
        if hasattr(self._writer, "histogram"):
            self._writer.histogram(tag, values, step)
            return
        # pre-histogram custom writers (scalar/flush-only MetricWriter
        # protocol): degrade to summary-stat scalars instead of crashing
        from dist_mnist_tpu.obs.writers import _summary_stats

        for k, v in _summary_stats(values).items():
            self._writer.scalar(f"{tag}/{k}", v, step)

    def _write_param_histograms(self, step, state):
        from dist_mnist_tpu.parallel.sharding import _paths

        flat, _, paths = _paths(state.params)
        wanted = {p: leaf for p, (_, leaf) in zip(paths, flat)
                  if getattr(leaf, "size", 0)}
        fetched = jax.device_get(wanted)  # lint: ok[host-sync] one batched pull per (slow) param-histogram cadence
        for path, vals in fetched.items():
            self._write_histogram(f"params/{path}", vals, step)

    def end(self, state):
        self._writer.flush()


class ProfilerHook(Hook):
    """≙ ProfilerHook (:1013-1095): Chrome-trace a window of steps. Uses
    jax.profiler (XLA + ICI in one TensorBoard trace) instead of
    RunMetadata/Timeline. `start_step`/`num_steps` are relative to THIS
    run's first step (resume-aware)."""

    def __init__(self, logdir: str, start_step: int = 10, num_steps: int = 3):
        self._logdir = logdir
        self._start_offset = start_step  # relative to THIS run's first step
        self._num = num_steps
        self._start = self._stop = None
        self._active = False
        self._done = False

    def begin(self, loop):
        # anchor to the restored step — a run resumed at step 100 traces
        # steps 110..112, not never. Under a chunked loop (steps_per_call
        # > 1) before_step only ever sees chunk boundaries, so align the
        # window start DOWN to the boundary whose chunk contains it — the
        # trace then covers that whole chunk (incl. a single-chunk run
        # where before_step(0) is the only pre-window call).
        stride = getattr(loop, "steps_per_call", 1)
        offset = (self._start_offset // stride) * stride if stride > 1 \
            else self._start_offset
        self._start = loop.initial_step + offset
        self._stop = self._start + self._num

    def before_step(self, step):
        # >= not ==: a chunked loop (scan_chunk) strides past the exact
        # start step; the trace then covers whole chunks (the finest
        # granularity a compiled multi-step program can offer). _done
        # guards against restarting once the window has been captured.
        if not self._done and not self._active and step >= self._start:
            jax.profiler.start_trace(self._logdir)
            self._active = True

    def _stop_and_export(self):
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        log.info("profile (window [%d, %d)) -> %s",
                 self._start, self._stop, self._logdir)
        try:
            # reference UX parity: a chrome://tracing-loadable
            # timeline-*.json next to the profile (obs/timeline.py)
            from dist_mnist_tpu.obs.timeline import export_chrome_trace

            out = export_chrome_trace(self._logdir)
            if out is not None:
                log.info("chrome trace -> %s", out)
        except Exception:  # noqa: BLE001 — triage aid must not kill training
            log.exception("chrome trace export failed")

    def after_step(self, step, state, outputs):
        # after_step sees the post-increment step: steps _start.._stop-1
        # (num_steps of them) run inside the trace window
        if self._active and step >= self._stop:
            jax.block_until_ready(outputs.get("loss"))
            self._stop_and_export()

    def end(self, state):
        # a run shorter than the trace window still gets its timeline —
        # same export path as the cadence stop (ADVICE r1 item 1)
        if self._active:
            self._stop_and_export()


class MemoryProfileHook(Hook):
    """Dump a device-memory profile (pprof) at a chosen step — the HBM
    triage companion to ProfilerHook's timeline. No reference counterpart
    (the PS design had no device-memory pressure to triage); exists because
    OOM-at-scale is the TPU failure mode the reference never had."""

    def __init__(self, logdir: str, after_steps: int = 20):
        # default 20 stays clear of ProfilerHook's default trace window
        # (steps 10..12 of the run) — the blocking dump would otherwise
        # land mid-trace and distort the timeline it accompanies
        self._logdir = logdir
        self._after = after_steps  # relative: fires this many steps into
        self._at = None            # THIS run (restored runs included)

    def begin(self, loop):
        # anchor to the restored step, and never past the run's end — a
        # short run still gets its profile on the final step
        self._at = loop.initial_step + self._after

    def _dump(self, path, sync_on=None):
        try:
            if sync_on is not None:
                jax.block_until_ready(sync_on)
            jax.profiler.save_device_memory_profile(path)
            log.info("device memory profile -> %s", path)
        except Exception:  # noqa: BLE001 — triage aid must not kill training
            log.exception("device memory profile failed")

    def after_step(self, step, state, outputs):
        if self._at is None or step < self._at:
            return
        self._at = None  # fire once
        self._dump(f"{self._logdir}/memory-step{step}.prof",
                   sync_on=outputs.get("loss"))

    def end(self, state):
        # run shorter than after_steps: still capture (post-final-step)
        if self._at is not None:
            self._at = None
            self._dump(f"{self._logdir}/memory-final.prof")


class MemoryHook(Hook):
    """Per-device HBM attribution through the obs writers — the hook face
    of `bench.py --memory`. No reference counterpart: the PS design spread
    state across hosts' RAM; under SPMD the scarce resource is device HBM
    and WHERE the bytes live (replicated vs 1/data-th under `fsdp`) is a
    placement decision this hook makes observable.

    At `begin` it writes the resident-state attribution computed from
    shard shapes (train/state.state_memory_bytes — pure metadata, no
    transfer):

      memory/param_bytes_per_device        master weights
      memory/opt_state_bytes_per_device    Adam m/v + counters
      memory/model_state_bytes_per_device  BN stats etc.
      memory/total_bytes_per_device

    and at its cadence, live allocator stats when the backend exposes
    them (`device.memory_stats()` — TPU yes, CPU no):

      memory/bytes_in_use
      memory/peak_bytes_in_use

    `last` keeps the newest values for bench harnesses."""

    def __init__(self, writer=None, every_steps: int = 100):
        self._writer = writer
        self._timer = EverySteps(every_steps=every_steps)
        self.last: dict[str, float] = {}

    def begin(self, loop):
        from dist_mnist_tpu.train.state import state_memory_bytes

        self._timer.prime(loop.initial_step)
        vals = {f"memory/{k}_per_device": v
                for k, v in state_memory_bytes(loop.state).items()}
        log.info(
            "resident state per device: params %.2f MiB, opt state %.2f "
            "MiB, model state %.2f MiB",
            vals["memory/param_bytes_per_device"] / 2**20,
            vals["memory/opt_state_bytes_per_device"] / 2**20,
            vals["memory/model_state_bytes_per_device"] / 2**20,
        )
        self._emit(vals, loop.initial_step)

    def _live_stats(self) -> dict:
        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — backends without allocator stats
            return {}
        if not stats:
            return {}
        return {f"memory/{k}": stats[k]
                for k in ("bytes_in_use", "peak_bytes_in_use") if k in stats}

    def _emit(self, vals, step):
        self.last.update(vals)
        if self._writer is None:
            return
        batch_write = getattr(self._writer, "scalars", None)
        if callable(batch_write):
            batch_write(vals, step)
        else:
            for k, v in vals.items():
                self._writer.scalar(k, v, step)

    def after_step(self, step, state, outputs):
        if not self._timer.should_trigger(step):
            return
        self._timer.mark()
        vals = self._live_stats()
        if vals:
            self._emit(vals, step)


class OverlapHook(Hook):
    """Publishes the fsdp comm/compute-overlap plan (parallel/overlap.py
    plan_stats) as `overlap/*` scalars at `begin` — the registry face of
    `bench.py --overlap`. The plan is static for a run (pure metadata from
    shard shapes), so one write at the initial step is the honest cadence:

      overlap/buckets            all-gather flush groups in the plan
      overlap/sharded_leaves     leaves actually gathered (fsdp-sharded)
      overlap/total_leaves       all param leaves (context for the above)
      overlap/gathered_bytes     unsharded bytes materialized per step
      overlap/bucket_mb          configured bucket granularity
      overlap/serial             1.0 = ablation twin (comm exposed on purpose)

    `last` keeps the values for bench harnesses."""

    def __init__(self, writer=None, stats: dict | None = None):
        self._writer = writer
        self._stats = dict(stats or {})
        self.last: dict[str, float] = {}

    def begin(self, loop):
        vals = {}
        for k, v in self._stats.items():
            if isinstance(v, bool):
                vals[f"overlap/{k}"] = 1.0 if v else 0.0
            elif isinstance(v, (int, float)):
                vals[f"overlap/{k}"] = v
        log.info(
            "fsdp overlap plan: %d buckets over %d sharded leaves "
            "(%.2f MiB gathered per step, bucket_mb=%.1f, chunk=%s)",
            self._stats.get("buckets", 0),
            self._stats.get("sharded_leaves", 0),
            self._stats.get("gathered_bytes", 0) / 2**20,
            self._stats.get("bucket_mb", 0.0),
            self._stats.get("chunk", "?"),
        )
        self.last.update(vals)
        if self._writer is None:
            return
        batch_write = getattr(self._writer, "scalars", None)
        if callable(batch_write):
            batch_write(vals, loop.initial_step)
        else:
            for k, v in vals.items():
                self._writer.scalar(k, v, loop.initial_step)


class GlobalStepWaiterHook(Hook):
    """≙ GlobalStepWaiterHook (basic_session_run_hooks.py:902): delay this
    process's training until the job's global step reaches `wait_until_step`.

    The reference polled the PS-resident global_step variable (the only
    cross-worker channel); under SPMD the cross-JOB channel is the
    checkpoint directory, so this polls `checkpoint_manager.latest_step()`.
    A state already restored at/past the threshold passes immediately.
    Typical use: stagger a follower job (eval/export/continuation) until a
    trainer job's checkpoints reach step N.
    """

    def __init__(self, wait_until_step: int, checkpoint_manager=None,
                 poll_secs: float = 0.5, timeout_secs: float | None = None,
                 log_every_secs: float = 10.0):
        self._wait_until = wait_until_step
        self._mgr = checkpoint_manager
        self._poll = poll_secs
        self._timeout = timeout_secs
        self._log_every = log_every_secs

    def begin(self, loop):
        if self._wait_until <= 0 or loop.initial_step >= self._wait_until:
            return
        if self._mgr is None:
            raise ValueError(
                "GlobalStepWaiterHook needs a checkpoint_manager to observe "
                "another job's progress (no shared global_step exists)"
            )
        log.info("waiting for global step %d...", self._wait_until)
        t0 = last_log = time.monotonic()
        # a FOREIGN job is writing the checkpoints, so each poll must rescan
        # the directory — cached step lists (orbax caches at init) would spin
        # forever. Our CheckpointManager: latest_step(refresh=True); bare
        # orbax managers: reload() first; fakes: plain latest_step().
        try:
            has_refresh = "refresh" in inspect.signature(
                self._mgr.latest_step
            ).parameters
        except (TypeError, ValueError):
            has_refresh = False
        reload_fn = getattr(self._mgr, "reload", None)

        def poll():
            if has_refresh:
                return self._mgr.latest_step(refresh=True)
            if callable(reload_fn):
                reload_fn()
            return self._mgr.latest_step()

        while True:
            latest = poll()
            if latest is not None and latest >= self._wait_until:
                log.info("global step %d reached (%.1fs)", latest,
                         time.monotonic() - t0)
                return
            now = time.monotonic()
            if self._timeout is not None and now - t0 > self._timeout:
                raise TimeoutError(
                    f"global step {self._wait_until} not reached in "
                    f"{self._timeout}s (latest={latest})"
                )
            if now - last_log >= self._log_every:
                # reference cadence: a progress line every 10 s (:986-994)
                log.info("still waiting for step %d (latest=%s)",
                         self._wait_until, latest)
                last_log = now
            time.sleep(self._poll)


class FinalOpsHook(Hook):
    """≙ FinalOpsHook (basic_session_run_hooks.py:1098): evaluate one last
    thing on the final state; result kept on `.final_result`."""

    def __init__(self, final_fn):
        self._fn = final_fn
        self.final_result = None

    def end(self, state):
        self.final_result = self._fn(state)


class EvalHook(Hook):
    """Periodic full-test-set eval (the reference did this ad hoc at the end
    of the train loop — §0.1 step 9; as a hook it also serves the 'validation
    while training' role MonitoredTrainingSession left to summaries)."""

    def __init__(self, eval_fn, every_steps: int = 1000, writer=None,
                 name: str = "test"):
        self._eval = eval_fn
        self._timer = EverySteps(every_steps=every_steps)
        self._writer = writer
        self._name = name
        self.last_result: dict | None = None
        self._last_eval_step: int | None = None

    def begin(self, loop):
        self._timer.prime(loop.initial_step)

    def _run(self, step, state):
        res = self._eval(state)
        self.last_result = res
        self._last_eval_step = step
        log.info("%s eval @ step %d: loss=%.4f acc=%.4f",
                 self._name, step, res["loss"], res["accuracy"])
        if self._writer:
            self._writer.scalar(f"{self._name}/loss", res["loss"], step)
            self._writer.scalar(f"{self._name}/accuracy", res["accuracy"], step)

    def after_step(self, step, state, outputs):
        if self._timer.should_trigger(step):
            self._timer.mark()
            self._run(step, state)

    def end(self, state):
        step = -1 if state is None else int(state.step)
        if step == self._last_eval_step:
            return  # final step landed on the cadence; don't eval twice
        self._run(step, state)
