"""Hook lifecycle — the SessionRunHook system, functional.

Replaces SURVEY.md §2.4 row 18 (basic_session_run_hooks.py). Same lifecycle
shape (begin / before-step / after-step / end), but hooks receive the step's
returned metrics dict instead of injecting fetches into a feed/fetch merge
(there is no session to merge into — the step is one compiled program).
"""

from dist_mnist_tpu.hooks.base import Hook
from dist_mnist_tpu.hooks.builtin import (
    StopAtStepHook,
    StepCounterHook,
    InputPipelineHook,
    StepTimeHook,
    LoggingHook,
    NaNGuardHook,
    NanLossError,
    CheckpointHook,
    SummaryHook,
    ProfilerHook,
    EvalHook,
    GlobalStepWaiterHook,
    FinalOpsHook,
    MemoryProfileHook,
    MemoryHook,
    OverlapHook,
)

__all__ = [
    "Hook",
    "StopAtStepHook",
    "StepCounterHook",
    "InputPipelineHook",
    "StepTimeHook",
    "LoggingHook",
    "NaNGuardHook",
    "NanLossError",
    "CheckpointHook",
    "SummaryHook",
    "ProfilerHook",
    "EvalHook",
    "GlobalStepWaiterHook",
    "FinalOpsHook",
    "MemoryProfileHook",
    "MemoryHook",
    "OverlapHook",
]
