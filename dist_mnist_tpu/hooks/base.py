"""Hook protocol (SessionRunHook analogue, SURVEY.md §2.4 row 18).

Lifecycle, in loop order (train/loop.py):
  begin(loop)                    — once, before the first step; the hook may
                                   keep the loop handle to request_stop()
                                   (≙ begin + after_create_session)
  before_step(step)              — step is the int about to execute
  after_step(step, state, out)   — `out` is the step's metrics dict of
                                   device scalars; calling float() on one
                                   syncs the device — hooks should do so
                                   only at their cadence to keep dispatch
                                   async (the analogue of not adding fetches
                                   to every run)
  end(state)                     — once, after the last step or stop request
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from dist_mnist_tpu.train.loop import TrainLoop


class Hook:
    def begin(self, loop: "TrainLoop") -> None:
        pass

    def before_step(self, step: int) -> None:
        pass

    def after_step(self, step: int, state, outputs: dict[str, Any]) -> None:
        pass

    def end(self, state) -> None:
        pass


class EverySteps:
    """Cadence helper ≙ SecondOrStepTimer (basic_session_run_hooks.py:86):
    triggers on a step multiple and/or a wall-clock interval."""

    def __init__(self, every_steps: int | None = None,
                 every_secs: float | None = None):
        if every_steps is None and every_secs is None:
            raise ValueError("need every_steps or every_secs")
        self.every_steps = every_steps
        self.every_secs = every_secs
        self._last_time = time.monotonic()
        self._last_step: int | None = None

    def prime(self, step: int) -> None:
        """Anchor the crossing detector at the run's initial step (hooks
        call this from begin(loop)). Without it the FIRST observation has
        no predecessor, so a chunk that crosses a multiple without landing
        on one (e.g. first after_step(150) with every=100) can't be seen
        as a crossing."""
        self._last_step = step

    def should_trigger(self, step: int) -> bool:
        """True when a step multiple was REACHED OR CROSSED since the last
        observed step — not bare `step % every == 0`, which silently aliases
        when the loop advances in chunks (scan_chunk: steps arrive as
        64, 128, ... and would hit a multiple of 100 only at the LCM)."""
        if self.every_steps is not None:
            prev, self._last_step = self._last_step, step
            if prev is None:
                if step % self.every_steps == 0:
                    return True
            elif step // self.every_steps > prev // self.every_steps:
                return True
        if (
            self.every_secs is not None
            and time.monotonic() - self._last_time >= self.every_secs
        ):
            return True
        return False

    def mark(self) -> None:
        self._last_time = time.monotonic()
