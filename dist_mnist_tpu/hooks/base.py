"""Hook protocol (SessionRunHook analogue, SURVEY.md §2.4 row 18).

Lifecycle, in loop order (train/loop.py):
  begin(loop)                    — once, before the first step; the hook may
                                   keep the loop handle to request_stop()
                                   (≙ begin + after_create_session)
  before_step(step)              — step is the int about to execute
  after_step(step, state, out)   — `out` is the step's metrics dict of
                                   device scalars; calling float() on one
                                   syncs the device — hooks should do so
                                   only at their cadence to keep dispatch
                                   async (the analogue of not adding fetches
                                   to every run)
  end(state)                     — once, after the last step or stop request
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from dist_mnist_tpu.train.loop import TrainLoop


class Hook:
    def begin(self, loop: "TrainLoop") -> None:
        pass

    def before_step(self, step: int) -> None:
        pass

    def after_step(self, step: int, state, outputs: dict[str, Any]) -> None:
        pass

    def end(self, state) -> None:
        pass


class EverySteps:
    """Cadence helper ≙ SecondOrStepTimer (basic_session_run_hooks.py:86):
    triggers on a step multiple and/or a wall-clock interval."""

    def __init__(self, every_steps: int | None = None,
                 every_secs: float | None = None):
        if every_steps is None and every_secs is None:
            raise ValueError("need every_steps or every_secs")
        self.every_steps = every_steps
        self.every_secs = every_secs
        self._last_time = time.monotonic()

    def should_trigger(self, step: int) -> bool:
        if self.every_steps is not None and step % self.every_steps == 0:
            return True
        if (
            self.every_secs is not None
            and time.monotonic() - self._last_time >= self.every_secs
        ):
            return True
        return False

    def mark(self) -> None:
        self._last_time = time.monotonic()
