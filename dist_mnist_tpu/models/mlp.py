"""2-layer MLP — the reference driver's exact model (SURVEY.md §0.1 step 5).

Geometry parity: ``hid_w [784, hidden]``, ``sm_w [hidden, 10]``, truncated-
normal init with stddev 1/sqrt(fan_in), ReLU hidden layer. The reference
applied an explicit softmax and clipped-log loss; we emit raw logits and pair
the model with `ops.losses.clipped_softmax_cross_entropy` for bit-level
comparability (the softmax lives in the loss, where XLA fuses it anyway).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from dist_mnist_tpu.ops import nn


@dataclasses.dataclass(frozen=True)
class MLP:
    hidden_units: int = 100  # reference flag default (§0.1 flag table)
    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.float32  # tiny model: MXU gain ≈ 0, keep f32

    def init(self, rng, sample_input):
        in_dim = 1
        for d in sample_input.shape[1:]:
            in_dim *= int(d)
        k1, k2 = jax.random.split(rng)
        params = {
            "hid": nn.init_dense(k1, in_dim, self.hidden_units),
            "sm": nn.init_dense(k2, self.hidden_units, self.num_classes),
        }
        return params, {}

    def flops_per_example(self, sample_shape) -> float:
        """Analytic FORWARD FLOPs per example (matmul MACs x2; elementwise
        ignored) — the standard MFU numerator. XLA's cost analysis cannot
        be trusted for models whose layers run under `lax.scan` (it counts
        a scan body once — utils/flops.py), so every model also publishes
        the analytic count."""
        in_dim = 1
        for d in sample_shape[1:]:
            in_dim *= int(d)
        return 2.0 * (in_dim * self.hidden_units
                      + self.hidden_units * self.num_classes)

    def apply(self, params, state, x, *, train=False, rng=None):
        x = nn.flatten(x).astype(self.compute_dtype)
        h = nn.relu(nn.dense(params["hid"], x))
        logits = nn.dense(params["sm"], h)
        return logits.astype(jnp.float32), state
