"""ViT-Tiny for CIFAR-10 (BASELINE.md config 5 — the attention-path stretch
config for pod slices).

Standard ViT-Ti geometry (dim 192, depth 12, heads 3), 4x4 patches so a
32x32 image is a 64-token sequence, learned position embeddings, CLS token,
pre-LN blocks. The attention inner loop is swappable: the default XLA
einsum path (ops/nn.dot_product_attention), the Pallas flash kernel
(ops/pallas/flash_attention.py), ring attention over the `seq` mesh axis
(parallel/ring_attention.py), or Ulysses all-to-all sequence parallelism
(parallel/ulysses.py; needs heads % seq == 0) — selected by
`attention_impl`.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from dist_mnist_tpu.ops import nn


@dataclasses.dataclass(frozen=True)
class ViTTiny:
    num_classes: int = 10
    patch: int = 4
    dim: int = 192
    depth: int = 12
    heads: int = 3
    mlp_ratio: int = 4
    dropout_rate: float = 0.1
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "xla"  # "xla" | "flash" | "ring" | "ulysses"
    pool: str = "cls"  # "cls" | "mean" (mean keeps token count a power of
    # two — required when the sequence dim is sharded, e.g. ring attention)
    mlp_impl: str = "dense"  # "dense" | "moe" (switch-routed expert FFN,
    # expert-parallel over the `model` axis when it matches n_experts —
    # parallel/moe.py)
    n_experts: int = 4
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2  # load-balance loss weight (Switch form);
    # the train step adds state["moe_aux"] to the loss
    scan_blocks: bool = False  # compile ONE block and lax.scan over stacked
    # per-layer params instead of unrolling `depth` copies of the program —
    # ~depth x less HLO to build/compile, identical numerics. The required
    # idiom for deep stacks under XLA; off by default only so per-block
    # param paths (block0/...) stay addressable by older sharding rules.

    def init(self, rng, sample_input):
        h, w, c = (int(d) for d in sample_input.shape[1:])
        n_tokens = (h // self.patch) * (w // self.patch)
        if self.pool == "cls":
            n_tokens += 1
        keys = jax.random.split(rng, 4 + self.depth)
        d = self.dim
        params: dict = {
            "patch": nn.init_conv(keys[0], self.patch, self.patch,
                                  c, d, init=nn.xavier_uniform),
            "pos": 0.02 * jax.random.normal(keys[1], (1, n_tokens, d)),
            "head": nn.init_dense(keys[2], d, self.num_classes,
                                  init=nn.xavier_uniform),
            "final_ln": nn.init_layer_norm(d),
        }
        if self.pool == "cls":
            params["cls"] = jnp.zeros((1, 1, d))
        blocks = []
        for i in range(self.depth):
            k1, k2, k3 = jax.random.split(keys[3 + i], 3)
            block = {
                "ln1": nn.init_layer_norm(d),
                "attn": nn.init_attention(k1, d, self.heads),
                "ln2": nn.init_layer_norm(d),
            }
            if self.mlp_impl == "moe":
                from dist_mnist_tpu.parallel.moe import init_moe

                block["moe"] = init_moe(k2, d, d * self.mlp_ratio,
                                        self.n_experts)
            else:
                block["mlp_in"] = nn.init_dense(k2, d, d * self.mlp_ratio,
                                                init=nn.xavier_uniform)
                block["mlp_out"] = nn.init_dense(k3, d * self.mlp_ratio, d,
                                                 init=nn.xavier_uniform)
            blocks.append(block)
        if self.scan_blocks:
            # one stacked pytree ([depth, ...] leaves) scanned by apply;
            # per-block init is identical to the unrolled layout, so the
            # two layouts are numerically interchangeable (stack/unstack)
            params["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
        else:
            for i, block in enumerate(blocks):
                params[f"block{i}"] = block
        # state carries the load-balance aux loss so the train step can add
        # it to the objective (structure must match apply's output)
        state = {"moe_aux": jnp.zeros(())} if self.mlp_impl == "moe" else {}
        return params, state

    def _attention(self, p, x):
        if self.attention_impl == "xla":
            return nn.multi_head_attention(p, x, self.heads)
        b, s, d = x.shape
        h = self.heads
        qkv = nn.dense(p["qkv"], x).reshape(b, s, 3, h, d // h)
        q, k, v = jnp.moveaxis(qkv, 2, 0)
        if self.attention_impl == "flash":
            from dist_mnist_tpu.ops.pallas.flash_attention import flash_attention

            out = flash_attention(q, k, v)
        elif self.attention_impl == "ring":
            from dist_mnist_tpu.parallel.ring_attention import ring_attention

            out = ring_attention(q, k, v)
        elif self.attention_impl == "ulysses":
            from dist_mnist_tpu.parallel.ulysses import ulysses_attention

            out = ulysses_attention(q, k, v)
        else:
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r}; "
                "use 'xla' | 'flash' | 'ring' | 'ulysses'"
            )
        return nn.dense(p["out"], out.reshape(b, s, d))

    def _block(self, p, x, layer_rng, use_dropout):
        """One pre-LN transformer block; returns (x, moe_aux)."""
        y = nn.layer_norm(p["ln1"], x)
        x = x + self._attention(p["attn"], y)
        y = nn.layer_norm(p["ln2"], x)
        aux = jnp.zeros((), jnp.float32)
        if self.mlp_impl == "moe":
            from dist_mnist_tpu.parallel.moe import moe_ffn_adaptive

            bb, ss, dd = y.shape
            y, aux = moe_ffn_adaptive(
                p["moe"], y.reshape(bb * ss, dd),
                capacity_factor=self.moe_capacity_factor,
            )
            y = y.reshape(bb, ss, dd)
        else:
            y = nn.gelu(nn.dense(p["mlp_in"], y))
        if use_dropout:
            y = nn.dropout(layer_rng, y, self.dropout_rate, train=True)
        x = x + (y if self.mlp_impl == "moe" else nn.dense(p["mlp_out"], y))
        return x, aux

    def apply(self, params, state, x, *, train=False, rng=None):
        x = x.astype(self.compute_dtype)
        x = nn.conv2d(params["patch"], x, stride=self.patch, padding="VALID")
        b, ph, pw, d = x.shape
        x = x.reshape(b, ph * pw, d)
        if self.pool == "cls":
            cls = jnp.broadcast_to(params["cls"].astype(x.dtype), (b, 1, d))
            x = jnp.concatenate([cls, x], axis=1)
        x = x + params["pos"].astype(x.dtype)
        use_dropout = train and rng is not None
        rngs = (jax.random.split(rng, self.depth) if use_dropout
                else jnp.zeros((self.depth,)))  # scannable dummy
        if self.scan_blocks:
            def body(carry, xs):
                x, aux_total = carry
                p, layer_rng = xs
                x, aux = self._block(p, x, layer_rng, use_dropout)
                return (x, aux_total + aux), None

            (x, aux_total), _ = jax.lax.scan(
                body, (x, jnp.zeros((), jnp.float32)),
                (params["blocks"], rngs),
            )
        else:
            aux_total = jnp.zeros((), jnp.float32)
            for i in range(self.depth):
                x, aux = self._block(params[f"block{i}"], x, rngs[i],
                                     use_dropout)
                aux_total = aux_total + aux
        x = nn.layer_norm(params["final_ln"], x)
        pooled = x[:, 0] if self.pool == "cls" else jnp.mean(x, axis=1)
        logits = nn.dense(params["head"], pooled)
        if self.mlp_impl == "moe":
            state = {"moe_aux": self.moe_aux_weight * aux_total / self.depth}
        return logits.astype(jnp.float32), state
